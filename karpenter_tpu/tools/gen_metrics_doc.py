"""Generate docs/metrics.md from the metric names in the source tree.

The reference generates its metrics reference page from code
(hack/docs/metrics_gen_docs.go -> website concepts/metrics.md); this is
the same contract here: the doc is derived, never hand-edited, and a test
(tests/test_tools.py) fails when it drifts from the source.

Run: ``python -m karpenter_tpu.tools.gen_metrics_doc``
"""

from __future__ import annotations

import pathlib
import re
from collections import defaultdict
from typing import Dict, List

_METRIC_RE = re.compile(r'"(karpenter_[a-z0-9_]+)"')

# subsystem ordering mirrors the reference page's grouping
_GROUPS = [
    ("karpenter_provisioner_", "Provisioner"),
    ("karpenter_nodeclaims_", "NodeClaims"),
    ("karpenter_nodes_", "Nodes"),
    ("karpenter_pods_", "Pods"),
    ("karpenter_deprovisioning_", "Deprovisioning"),
    ("karpenter_consistency_", "Consistency"),
    ("karpenter_interruption_", "Interruption"),
    ("karpenter_cloudprovider_", "CloudProvider"),
    ("karpenter_cloud_api_", "Cloud API resilience"),
    ("karpenter_provider_cache_", "Provider caches"),
    ("karpenter_tpu_controller_", "Controller health"),
    ("karpenter_batcher_", "Batcher"),
    ("karpenter_cache_", "Cache"),
    ("karpenter_instancetype_", "Instance types"),
    ("karpenter_solver_", "Solver"),
    ("karpenter_consolidation_", "Consolidation"),
    ("karpenter_sim_", "Simulator"),
]

# metric type / label set / movement semantics, rendered as a sub-line.
# Start with the resilience-layer series (ISSUE 2); grow as families gain
# documentation.
_DETAILS = {
    "karpenter_cloud_api_retries_total": (
        "counter",
        "api, classification",
        "bumped each time RetryingCloud retries a cloud call classified "
        "throttle or transient; terminal errors (ICE, NotFound) never move it",
    ),
    "karpenter_cloud_api_circuit_state": (
        "gauge",
        "api",
        "0 closed / 1 half-open / 2 open; opens after "
        "cloud_circuit_failure_threshold consecutive classified failures, "
        "half-opens when cloud_circuit_reset_timeout elapses, closes on the "
        "next success",
    ),
    "karpenter_provider_cache_stale_seconds": (
        "gauge",
        "provider",
        "age of the last-good data a degraded provider (pricing / subnet / "
        "securitygroup / image / version) is serving while its refresh API "
        "fails; reset to 0 by the next successful refresh",
    ),
    "karpenter_tpu_controller_healthy": (
        "gauge",
        "controller",
        "1 after a clean reconcile; 0 while the controller is "
        "crash-contained in per-controller requeue backoff after raising",
    ),
    "karpenter_pods_time_to_schedule_seconds": (
        "histogram",
        "(none)",
        "pod first-seen-pending -> nominated onto a node/claim, observed "
        "by the provisioning controller on the injected clock; the "
        "simulator's SLO report (sim/report.py) aggregates its samples "
        "into p50/p95/p99 time-to-schedule",
    ),
    "karpenter_sim_events_injected_total": (
        "counter",
        "kind",
        "scenario events the simulator applied (pod_create, pod_delete, "
        "instance_kill, spot_interruption, chaos, az_down/az_up, "
        "image_roll, pool_update)",
    ),
    "karpenter_sim_ticks_total": (
        "counter",
        "phase",
        "simulated ticks executed per phase (run / drain / settle)",
    ),
    "karpenter_sim_pending_pods": (
        "gauge",
        "(none)",
        "pending-pod depth at the end of the last simulated tick; the "
        "report's pending.peak is the max this gauge reached",
    ),
    "karpenter_sim_invariant_violations_total": (
        "counter",
        "invariant",
        "invariant checks that failed (no-double-launch, "
        "registered-eq-launched, budgets, no-leaked-instances, "
        "schedule-deadline, all-pods-scheduled, no-wedged-controller); "
        "any movement fails the run",
    ),
    "karpenter_solver_phase_seconds": (
        "histogram",
        "phase",
        "per-solve wall time of one solver phase (partition / compile / "
        "pad / dispatch / device_block / oracle / decode / other) — "
        "disjoint self-times that sum to the solve's wall clock, observed "
        "by the provisioning controller after every scheduling solve; see "
        "the 'solve latency anatomy' section in the README for how to "
        "read them",
    ),
    "karpenter_solver_compile_cache_hits_total": (
        "counter",
        "consumer",
        "solves served from the TensorScheduler's incremental compile "
        "cache, per consuming controller (provisioner, disruption); "
        "exported as the delta of the scheduler's lifetime counter each "
        "reconcile",
    ),
    "karpenter_solver_compile_cache_misses_total": (
        "counter",
        "consumer",
        "solves that had to run the full host-side compile; a warm "
        "steady-state cluster should see hits dominate — misses every "
        "tick mean something (pods, pools, live nodes) is being mutated "
        "in place",
    ),
    "karpenter_consolidation_eval_batch_size": (
        "histogram",
        "",
        "candidate-subset elements per batched what-if dispatch "
        "(TensorScheduler.evaluate_removals): the single-node scan is one "
        "batch, each drop-one descent level is one batch",
    ),
    "karpenter_consolidation_phase_seconds": (
        "histogram",
        "phase",
        "per-dispatch wall time of one batched-evaluation phase "
        "(partition / compile / pad / dispatch / device_block / decode / "
        "other) — kept separate from karpenter_solver_phase_seconds so "
        "verdict batches don't skew the provisioner's per-solve "
        "percentiles",
    ),
    "karpenter_consolidation_evals_total": (
        "counter",
        "path",
        "consolidation what-if simulations by evaluation path: 'batched' "
        "elements were answered on-device from one shared compile, "
        "'sequential' elements ran the per-subset solver round-trip "
        "(fallback conditions: docs/designs/consolidation-batching.md)",
    ),
    "karpenter_consolidation_verdict_mismatch_total": (
        "counter",
        "",
        "batched verdicts contradicted by the winner's sequential decode "
        "— must stay 0 (the parity suite enforces it); any movement is a "
        "bug in the batched path",
    ),
}


def collect(root: pathlib.Path) -> Dict[str, List[str]]:
    """metric name -> sorted list of emitting modules."""
    out: Dict[str, set] = defaultdict(set)
    self_path = pathlib.Path(__file__).resolve()
    for path in sorted(root.rglob("*.py")):
        if "__pycache__" in path.parts or path.resolve() == self_path:
            continue
        rel = path.relative_to(root.parent).as_posix()
        for name in _METRIC_RE.findall(path.read_text()):
            out[name].add(rel)
    return {k: sorted(v) for k, v in out.items()}


def render(root: pathlib.Path) -> str:
    metrics = collect(root)
    lines = [
        "# Metrics",
        "",
        "GENERATED by `python -m karpenter_tpu.tools.gen_metrics_doc` — do",
        "not edit.  Mirrors the reference's generated metrics reference",
        "(hack/docs/metrics_gen_docs.go, website v0.31 concepts/metrics.md).",
        "",
        f"{len(metrics)} metric families.",
        "",
    ]
    def entry(n: str, mods) -> List[str]:
        out = [f"- `{n}` — {', '.join(f'`{m}`' for m in mods)}"]
        detail = _DETAILS.get(n)
        if detail is not None:
            mtype, labels, when = detail
            out.append(f"  - {mtype}, labels: {labels} — {when}")
        return out

    rest = dict(metrics)
    for prefix, title in _GROUPS:
        members = sorted(n for n in rest if n.startswith(prefix))
        if not members:
            continue
        lines += [f"## {title}", ""]
        for n in members:
            lines += entry(n, rest.pop(n))
        lines.append("")
    if rest:
        lines += ["## Other", ""]
        for n in sorted(rest):
            lines += entry(n, rest[n])
        lines.append("")
    return "\n".join(lines)


def main() -> None:
    pkg = pathlib.Path(__file__).resolve().parent.parent
    doc = pkg.parent / "docs" / "metrics.md"
    doc.write_text(render(pkg))
    print(f"wrote {doc}")


if __name__ == "__main__":
    main()
