"""allocatable-diff: computed vs actual node resources, as CSV.

Re-creation of reference tools/allocatable-diff/main.go:60-140: for every
managed node, compare the instance-type provider's COMPUTED capacity and
allocatable (kubeReserved curve + VM memory overhead, the numbers the
scheduler packs against) with the node's ACTUAL registered status.  Drift
between the two means the packing model is wrong — pods that "fit" on
paper get stuck at the kubelet — so this is the calibration tool for the
vm_memory_overhead_percent setting (main.go's --overhead-percent flag).

Usage (against a live operator or the test Environment):

    from karpenter_tpu.tools.allocatable_diff import diff_rows, write_csv
    rows = diff_rows(operator)
    write_csv(rows, "allocatable-diff.csv")

or ``python -m karpenter_tpu.tools.allocatable_diff --out-file x.csv``
(runs against a fake-cloud environment for demonstration; a real
deployment constructs the operator against its live backend first).
"""

from __future__ import annotations

import csv
from dataclasses import dataclass
from typing import List, Optional

from karpenter_tpu.api import labels as L

# axes and units mirrored from the reference CSV (Mi / milli-cpu / Mi)
_HEADER_TOP = [
    "Instance Type",
    "Expected Capacity", "", "Expected Allocatable", "",
    "Actual Capacity", "", "Actual Allocatable", "",
    "Diff Allocatable", "",
]
_HEADER_SUB = [
    "",
    "Memory (Mi)", "CPU (m)", "Memory (Mi)", "CPU (m)",
    "Memory (Mi)", "CPU (m)", "Memory (Mi)", "CPU (m)",
    "Memory (Mi)", "CPU (m)",
]


@dataclass
class DiffRow:
    node: str
    instance_type: str
    expected_capacity_mem_mi: int
    expected_capacity_cpu_m: int
    expected_alloc_mem_mi: int
    expected_alloc_cpu_m: int
    actual_capacity_mem_mi: int
    actual_capacity_cpu_m: int
    actual_alloc_mem_mi: int
    actual_alloc_cpu_m: int

    @property
    def alloc_mem_diff_mi(self) -> int:
        """expected - actual: positive means the model OVERPROMISES
        (pods that fit on paper won't fit on the machine)."""
        return self.expected_alloc_mem_mi - self.actual_alloc_mem_mi

    @property
    def alloc_cpu_diff_m(self) -> int:
        return self.expected_alloc_cpu_m - self.actual_alloc_cpu_m


@dataclass
class DiffReport:
    rows: List[DiffRow]
    # managed nodes the sweep could NOT model (pool deleted, type gone
    # from the listing): themselves calibration findings, never silent
    skipped: List[str]

    def __iter__(self):
        return iter(self.rows)

    def __len__(self) -> int:
        return len(self.rows)


def _mi(v: float) -> int:
    return int(v / (1024 * 1024))


def _milli(v: float) -> int:
    return int(v * 1000)


def diff_rows(operator) -> "DiffReport":
    """One row per managed node, instance-type sorted (main.go:103-139).
    Nodes whose pool is gone or whose type is missing from the provider's
    current listing are collected in ``skipped`` instead of crashing the
    sweep (the reference log.Fatals; a calibration tool should report the
    rest of the fleet — and a type that left the listing is itself a
    finding)."""
    skipped: List[str] = []
    rows: List[DiffRow] = []
    nodes = [
        n
        for n in operator.kube.nodes.values()
        if n.labels.get(L.LABEL_NODEPOOL) and n.allocatable.get("memory")
    ]
    nodes.sort(key=lambda n: n.labels.get(L.LABEL_INSTANCE_TYPE, ""))
    # one listing per (pool, node-class) pair, reused across that pair's nodes
    listings = {}
    for node in nodes:
        pool = operator.kube.node_pools.get(node.labels.get(L.LABEL_NODEPOOL))
        if pool is None:
            skipped.append(node.name)
            continue
        nc = operator.kube.node_classes.get(pool.node_class_ref)
        key = (pool.name, getattr(nc, "name", None))
        if key not in listings:
            listings[key] = operator.instance_types.list(pool, nc)
        it = next(
            (
                t
                for t in listings[key]
                if t.name == node.labels.get(L.LABEL_INSTANCE_TYPE)
            ),
            None,
        )
        if it is None:
            skipped.append(node.name)
            continue
        alloc = it.allocatable()
        rows.append(
            DiffRow(
                node=node.name,
                instance_type=it.name,
                expected_capacity_mem_mi=_mi(it.capacity.get("memory")),
                expected_capacity_cpu_m=_milli(it.capacity.get("cpu")),
                expected_alloc_mem_mi=_mi(alloc.get("memory")),
                expected_alloc_cpu_m=_milli(alloc.get("cpu")),
                actual_capacity_mem_mi=_mi(node.capacity.get("memory")),
                actual_capacity_cpu_m=_milli(node.capacity.get("cpu")),
                actual_alloc_mem_mi=_mi(node.allocatable.get("memory")),
                actual_alloc_cpu_m=_milli(node.allocatable.get("cpu")),
            )
        )
    return DiffReport(rows=rows, skipped=skipped)


def write_csv(rows: List[DiffRow], path: str) -> None:
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(_HEADER_TOP)
        w.writerow(_HEADER_SUB)
        for r in rows:
            w.writerow(
                [
                    r.instance_type,
                    r.expected_capacity_mem_mi, r.expected_capacity_cpu_m,
                    r.expected_alloc_mem_mi, r.expected_alloc_cpu_m,
                    r.actual_capacity_mem_mi, r.actual_capacity_cpu_m,
                    r.actual_alloc_mem_mi, r.actual_alloc_cpu_m,
                    r.alloc_mem_diff_mi, r.alloc_cpu_diff_m,
                ]
            )


def overpromised(rows: List[DiffRow]) -> List[DiffRow]:
    """Rows where the computed allocatable EXCEEDS the machine's actual —
    the dangerous direction (scheduler packs pods that cannot start)."""
    return [r for r in rows if r.alloc_mem_diff_mi > 0 or r.alloc_cpu_diff_m > 0]


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog="allocatable-diff")
    parser.add_argument("--out-file", default="allocatable-diff.csv")
    parser.add_argument(
        "--overhead-percent", type=float, default=None,
        help="override vm_memory_overhead_percent for the computation",
    )
    args = parser.parse_args(argv)

    # demonstration harness: a fake-cloud environment with a small fleet;
    # real deployments build Operator against their live backend instead
    from karpenter_tpu.api import Pod, Resources, Settings
    from karpenter_tpu.testing import Environment

    settings = Settings()
    if args.overhead_percent is not None:
        settings.vm_memory_overhead_percent = args.overhead_percent
    env = Environment(settings=settings)
    env.default_node_class()
    env.default_node_pool()
    for _ in range(8):
        env.kube.put_pod(Pod(requests=Resources(cpu=2, memory="4Gi")))
    env.settle()
    report = diff_rows(env.operator)
    write_csv(report.rows, args.out_file)
    bad = overpromised(report.rows)
    print(f"{len(report.rows)} nodes written to {args.out_file}; "
          f"{len(bad)} overpromised; {len(report.skipped)} skipped")
    for name in report.skipped:
        print(f"  skipped (unmodelable): {name}")
    return 1 if bad or report.skipped else 0


if __name__ == "__main__":
    raise SystemExit(main())
