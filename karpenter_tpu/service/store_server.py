"""Shared cluster-store server: one durable KubeStore, a fleet of clients.

The reference gets HA for free because durable state lives in the
kube-apiserver and the election Lease is a shared coordination/v1 object;
each controller replica is a thin client.  This server is that apiserver
analogue for the simulation backend: it owns ONE durable `KubeStore`
(wrapped in `VersionedStore` for resourceVersion bookkeeping) and serves
it over the same length-prefixed socket protocol as the solver sidecar
(service/codec.py).  PR 1 made 2-replica HA real; the fleet-scale store
plane (docs/designs/store-scale.md) makes the same server hold up under
thousands of objects feeding many controllers:

- **Negotiated payload codec**: every connection starts as tagged JSON;
  a ``hello`` (RPC) or ``codecs`` list (watch) negotiates the compact
  binary codec ``bin1`` (state/binwire.py) when both ends share the
  schema fingerprint.  An old endpoint that knows neither negotiates
  down to JSON transparently.
- **Delta watch resync**: every broadcast batch gets a monotonic
  ``seq`` and lands in a bounded replay log; a reconnecting watcher
  presents ``since_seq`` and receives only the events it missed,
  falling back to a full snapshot when compaction has passed its seq.
- **Backpressured fan-out**: per-subscriber queues are BOUNDED; a slow
  client's overflow coalesces into one forced-resync marker (replay or
  snapshot on its own stream) instead of growing server memory or
  head-of-line blocking the fast clients.
- **Compaction**: the replay log and the durable cluster-event ledger
  are both capped; trims count into
  ``karpenter_store_compactions_total{log}``.
- **Read replicas**: ``replica_of=(host, port)`` makes this server
  follow a primary over the same watch protocol and serve
  snapshot/watch read traffic with the primary's rv ordering preserved;
  every write method refuses (the leader's CAS space stays
  authoritative on the primary).

Methods (headers ride the negotiated codec; no array blobs):

- ``ping`` / ``stat``                liveness, {rv, seq, event_count}
- ``hello`` {codecs, schema_fp}      payload-codec negotiation
- ``put``    {kind, obj, base_rv}    optimistic-concurrency write
- ``delete`` {kind, key, base_rv}    delete (cascades run server-side)
- ``bind_pod`` / ``evict_pod``       semantic pod verbs (base_rv-fenced)
- ``record_event``                   append a store event
- ``lease_acquire`` / ``lease_renew`` / ``lease_release``
                                     the coordination/v1 Lease CAS
                                     surface, atomic server-side
- ``watch``  {identity, codecs, since_seq}
                                     long-lived: codec ack, then a
                                     ``resync`` frame (snapshot or
                                     replayed events), then pushed
                                     ``events`` frames as mutations land

Every mutation is assigned a monotonically increasing resourceVersion;
``put`` with a stale ``base_rv`` returns ``status: conflict`` with the
current object so the writer can resync instead of clobbering — the
single-writer invariant for competing replicas comes from the Lease, the
rv check fences the deposed leader's stragglers.
"""

from __future__ import annotations

import logging
import os
import socket
import socketserver
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from karpenter_tpu.metrics.registry import Registry
from karpenter_tpu.obs.context import trace_context
from karpenter_tpu.obs.events import EventLedger
from karpenter_tpu.analysis.sanitizer import (
    make_condition,
    make_lock,
    make_rlock,
    note_access,
)
from karpenter_tpu.service.codec import (
    CODEC_BIN,
    CODEC_JSON,
    decode_payload,
    encode_payload,
    recv_frame,
    send_frame,
)
from karpenter_tpu.service.shardrouter import shard_of
from karpenter_tpu.service.watchclient import WatchChannelClient
from karpenter_tpu.state.binwire import (
    Raw,
    SCHEMA_FP,
    decode_value,
    encode_value,
)
from karpenter_tpu.state.kube import KubeStore
from karpenter_tpu.state.storelog import DurableReplayLog, FSYNC_ALWAYS
from karpenter_tpu.state.wire import STORE_KINDS, materialize, to_wire
from karpenter_tpu.utils.trace import Tracer

log = logging.getLogger(__name__)

# bounded-plane defaults, overridable per server (and via the chart's
# store.* values -> main() flags)
REPLAY_LOG_EVENTS = 4096  # events retained for delta resync
WATCH_QUEUE_BATCHES = 256  # per-subscriber queued batches before resync
EVENTS_CAP = 4096  # durable cluster-event ledger bound

_WRITE_METHODS = frozenset(
    {
        "put", "delete", "bind_pod", "evict_pod", "record_event",
        "lease_acquire", "lease_renew", "lease_release",
        "shard_import", "shard_drop",
    }
)


class _Batch:
    """One broadcast unit: the events of one mutation, their seq, and
    the per-codec renderings.  Commit renders ONLY the forms someone
    currently needs (the originator's codec, the live subscribers'
    codecs) — an all-binary plane never builds a JSON tree, and vice
    versa.  Either form is an immutable rv-stamped snapshot of the
    mutation, so the missing one derives lazily from the other (replay
    to a late client of the other codec) without touching live objects
    or the store lock."""

    __slots__ = ("seq", "metas", "_json", "_bins", "_bin_frame")

    def __init__(
        self,
        seq: int,
        metas: List[dict],
        json_events: Optional[List[dict]] = None,
        bin_events: Optional[List[Raw]] = None,
    ):
        self.seq = seq
        self.metas = metas  # rv/kind/verb/key (no payloads)
        self._json = json_events
        self._bins = bin_events
        self._bin_frame: Optional[bytes] = None

    @property
    def max_rv(self) -> int:
        return max((m.get("rv", 0) for m in self.metas), default=0)

    def json_events(self) -> List[dict]:
        if self._json is None:
            out = []
            for raw in self._bins:  # type: ignore[union-attr]
                ev = decode_value(raw.data)
                if "event" in ev:
                    ev["event"] = to_wire(ev["event"])
                elif ev.get("obj") is not None:
                    ev["obj"] = to_wire(ev["obj"])
                out.append(ev)
            self._json = out
        return self._json

    def bin_events(self) -> List[Raw]:
        if self._bins is None:
            out = []
            for ev in self._json:  # type: ignore[union-attr]
                native = dict(ev)
                if "event" in ev:
                    native["event"] = materialize(ev["event"])
                elif ev.get("obj") is not None:
                    native["obj"] = materialize(ev["obj"])
                out.append(Raw(encode_value(native)))
            self._bins = out
        return self._bins

    def events_for(self, codec: str) -> List[object]:
        return (
            list(self.bin_events())
            if codec == CODEC_BIN
            else list(self.json_events())
        )

    def bin_frame_payload(self) -> bytes:
        """The fully-encoded single-batch ``events`` frame, rendered
        once and shipped VERBATIM to every bin subscriber — a designed
        property of the binary protocol: frames are content-addressed
        by seq, so fan-out is a byte copy per connection, not a
        re-serialization (the tagged-JSON path keeps its original
        per-connection rendering — it is the compatibility baseline the
        bench line compares against)."""
        if self._bin_frame is None:
            self._bin_frame = encode_payload(
                {
                    "type": "events",
                    "seq": self.seq,
                    "events": self.bin_events(),
                },
                CODEC_BIN,
            )
        return self._bin_frame


class _Subscriber:
    """A watch client's bounded queue.  ``cond`` shares the store lock:
    offers happen inside ``mutate`` (lock already held), the sender
    thread waits on it and drains outside the lock.  Overflow clears the
    queue and raises the ``pending_resync`` flag — the sender coalesces
    everything the client missed into one resync frame."""

    def __init__(self, identity: str, codec: str, cap: int, lock):
        self.identity = identity
        self.codec = codec
        self.cap = max(1, cap)
        self.cond = make_condition("_Subscriber.cond", lock)
        self.batches: Deque[_Batch] = deque()
        self.delivered_seq = 0
        self.pending_resync = False
        # why the pending resync was forced: "overflow" (this
        # subscriber's bounded queue filled) or "epoch" (the store's
        # continuity broke under it, e.g. a replica's full resync from
        # its primary) — keeps the slow-client metric signal clean
        self.forced_reason = "overflow"
        self.overflows = 0
        self.closed = False

    def offer(self, batch: _Batch) -> None:
        # store lock held by the caller (mutate/commit)
        note_access("_Subscriber.batches")  # lockset witness
        if self.pending_resync:
            return  # already coalesced; the resync frame covers this too
        if len(self.batches) >= self.cap:
            self.batches.clear()
            self.pending_resync = True
            self.forced_reason = "overflow"
            self.overflows += 1
        else:
            self.batches.append(batch)
        self.cond.notify_all()

    def close(self) -> None:
        self.closed = True
        self.cond.notify_all()


class VersionedStore:
    """A KubeStore plus resourceVersion bookkeeping, the seq'd replay
    log, and the backpressured watch broadcast.

    Survives server restarts: constructing a new `StoreServer` over the
    same `VersionedStore` keeps the objects, their rvs, AND the replay
    log, so reconnecting clients delta-resync across the restart (the
    durable half of the store lives here, the serving half in
    `StoreServer`)."""

    def __init__(
        self,
        kube: Optional[KubeStore] = None,
        replay_log_events: int = REPLAY_LOG_EVENTS,
        watch_queue_batches: int = WATCH_QUEUE_BATCHES,
        events_cap: int = EVENTS_CAP,
        durable_log: Optional[DurableReplayLog] = None,
    ):
        self.kube = kube or KubeStore()
        self.lock = make_rlock("VersionedStore.lock")
        self.rv = 0
        self.rvs: Dict[Tuple[str, str], int] = {}
        # per-lease CAS sequence, SEPARATE from the broadcast rv space:
        # silent renewals (no watch event) must not advance `rv`, or
        # other clients could never sync up to the stat rv
        self.lease_seq: Dict[str, int] = {}
        self.event_rv = 0
        self.replay_log_events = replay_log_events
        self.watch_queue_batches = watch_queue_batches
        self.events_cap = events_cap
        # the replay log: recent batches by seq.  `compacted_seq` is the
        # seq of the last batch compaction dropped — a reconnect with
        # since_seq >= compacted_seq replays, anything older snapshots.
        # `epoch` names THIS store's seq space: a fresh VersionedStore
        # (store restart without the durable object) is a new epoch, and
        # a cursor from another epoch must never claim coverage — the
        # new space's seq could have OVERTAKEN the stale cursor, making
        # a bare number look covered while silently skipping the
        # inter-epoch divergence.  Random, but never enters any
        # byte-compared surface (it rides the watch handshake only).
        self.epoch = os.urandom(8).hex()
        self.log_seq = 0
        self.compacted_seq = 0
        self.replay_log: Deque[_Batch] = deque()
        self._log_events = 0
        self.registry = Registry()  # re-bound by the owning StoreServer
        self._subscribers: List[_Subscriber] = []
        self._recorded: List[dict] = []
        self._rec_objs: List[object] = []
        self.kube.watch(self._record)
        # the crash-durable half (state/storelog.py): every commit
        # appends its bin-rendered batch; construction RECOVERS the
        # previous incarnation's state — objects, rvs, lease CAS seqs,
        # epoch, and the replay-log tail — so a restarted store serves
        # DELTA resyncs from disk instead of forcing a snapshot storm
        self.durable_log = durable_log
        if durable_log is not None:
            self._recover_from_log()

    # ------------------------------------------------------------ durability
    def _recover_from_log(self) -> None:
        """Adopt the durable segment's state: checkpoint snapshot first
        (objects + rvs verbatim — NO re-commit, these mutations already
        broadcast in the previous life), then the batch tail, which also
        repopulates the in-memory replay log so pre-restart watch
        cursors stay covered.  Re-adopting the previous EPOCH is the
        point: a recovered store is a continuation of the same seq
        space, not a new one.  A fresh segment writes a genesis
        checkpoint so even the first incarnation's epoch survives."""
        dlog = self.durable_log
        checkpoint, batches = dlog.recover()
        if checkpoint is None and not batches:
            with self.lock:
                self._checkpoint_locked()
            return
        with self.lock:
            if checkpoint is not None:
                snap = checkpoint.get("snapshot") or {}
                self.rvs = {}
                for kind, (_cls, attr, _key_fn) in STORE_KINDS.items():
                    store_dict = getattr(self.kube, attr)
                    store_dict.clear()
                    for key, entry in snap.get("kinds", {}).get(
                        kind, {}
                    ).items():
                        store_dict[key] = materialize(entry["obj"])
                        self.rvs[(kind, key)] = entry["rv"]
                self.rv = checkpoint.get("rv", 0)
                self.event_rv = checkpoint.get("event_rv", 0)
                self.lease_seq = dict(checkpoint.get("lease_seq", {}))
                self.kube.events = [
                    materialize(e)
                    for e in snap.get("events", [])[-self.events_cap:]
                ]
                self.epoch = str(checkpoint.get("epoch") or self.epoch)
                self.log_seq = checkpoint.get("seq", 0)
                self.compacted_seq = self.log_seq
            for rec in batches:
                metas: List[dict] = []
                bins: List[Raw] = []
                for ev in rec.get("events", ()):
                    meta = self._recover_event(ev)
                    if meta is None:
                        continue
                    metas.append(meta)
                    bins.append(Raw(encode_value(ev)))
                batch = _Batch(rec["seq"], metas, None, bins)
                self.replay_log.append(batch)
                self._log_events += len(metas)
                self.log_seq = rec["seq"]
                self.epoch = str(rec.get("epoch") or self.epoch)
            # the in-memory bound still applies to the recovered tail:
            # compaction advances compacted_seq exactly as _commit does
            while (
                self._log_events > self.replay_log_events
                and len(self.replay_log) > 1
            ):
                dropped = self.replay_log.popleft()
                self._log_events -= len(dropped.metas)
                self.compacted_seq = dropped.seq
            dlog.batches_since_checkpoint = len(batches)

    def _recover_event(self, ev) -> Optional[dict]:
        """Apply one recovered batch event to the kube dicts (verbatim,
        like apply_replicated — cascades already materialized in the
        recorded stream).  Returns the event's meta, or None for an
        unrecognized kind (a segment from a newer build)."""
        if not isinstance(ev, dict):
            return None
        if ev.get("kind") == "Event":
            tup = materialize(ev.get("event"))
            if ev.get("event_rv", 0) > self.event_rv:
                self.event_rv = ev["event_rv"]
                self.kube.events.append(tup)
                self._trim_events_locked()
            return {
                "kind": "Event",
                "verb": "append",
                "event_rv": ev.get("event_rv", 0),
            }
        spec = STORE_KINDS.get(ev.get("kind"))
        if spec is None:
            return None
        _cls, attr, _key_fn = spec
        key, rv = ev["key"], ev["rv"]
        store_dict = getattr(self.kube, attr)
        if ev["verb"] == "delete":
            store_dict.pop(key, None)
        else:
            store_dict[key] = materialize(ev["obj"])
        self.rvs[(ev["kind"], key)] = rv
        self.rv = max(self.rv, rv)
        return {
            "rv": rv, "kind": ev["kind"], "verb": ev["verb"], "key": key,
        }

    def _checkpoint_locked(self) -> None:
        """Lock held: rewrite the durable segment as one checkpoint
        record.  The bin snapshot references live objects, so rendering
        must finish before the lock drops — same contract as
        serve_watch's bin resync."""
        self.durable_log.write_checkpoint(
            self.epoch,
            self.log_seq,
            self.rv,
            self.event_rv,
            self.lease_seq,
            self.snapshot(CODEC_BIN),
        )

    def rotate_epoch(self, reason: str = "migration") -> None:
        """Fence every outstanding cursor: new epoch id, replay log
        reset, every subscriber forced onto its own resync.  The
        migration primitive — after an import/drop changed what this
        shard owns, no cursor minted before the change may claim
        coverage across it (a replayed gap would silently miss the
        ownership delta).  Checkpoints the durable log so the NEW epoch
        is what a post-crash recovery re-adopts."""
        with self.lock:
            self.replay_log.clear()
            self._log_events = 0
            self.log_seq += 1
            self.compacted_seq = self.log_seq
            self.epoch = os.urandom(8).hex()
            self.registry.inc(
                "karpenter_store_epoch_rotations_total", {"reason": reason}
            )
            for sub in self._subscribers:
                if not sub.closed:
                    sub.batches.clear()
                    sub.pending_resync = True
                    sub.forced_reason = "epoch"
                    sub.cond.notify_all()
            if self.durable_log is not None:
                self._checkpoint_locked()

    # ------------------------------------------------------------ recording
    def _record(self, kind: str, verb: str, obj) -> None:
        """KubeStore notification hook: capture every mutation a verb
        application produced (bind_pod touches a Pod and maybe a PVC;
        delete_node re-pends its pods) as state-based events.  Only the
        meta + a live object reference are captured here; the payload
        renders once, per needed codec, at commit time under the same
        lock."""
        spec = STORE_KINDS.get(kind)
        if spec is None:
            return
        cls, attr, key_fn = spec
        key = key_fn(obj)
        self.rv += 1
        self.rvs[(kind, key)] = self.rv
        deleted = key not in getattr(self.kube, attr)
        self._recorded.append(
            {
                "rv": self.rv,
                "kind": kind,
                "verb": "delete" if deleted else "put",
                "key": key,
            }
        )
        self._rec_objs.append(None if deleted else obj)

    def mutate(
        self, fn, origin: str = "", origin_codec: str = CODEC_JSON
    ) -> Optional[_Batch]:
        """Run `fn()` (KubeStore verbs) under the lock; collect the
        resulting events, commit them to the replay log, broadcast to
        every subscriber except the originator, and return the batch
        (for the originator's RPC response, rendered in its codec)."""
        with self.lock:
            self._recorded = []
            self._rec_objs = []
            fn()
            metas, objs = self._recorded, self._rec_objs
            self._recorded, self._rec_objs = [], []
            if not metas:
                return None
            return self._commit(metas, objs, origin, origin_codec)

    def _commit(
        self,
        metas: List[dict],
        objs,
        origin: str,
        origin_codec: str = CODEC_JSON,
    ) -> _Batch:
        """Lock held: assign the batch its seq, render, log, broadcast,
        compact.  Live objects are touched ONLY here (they may mutate
        the moment the lock is released); every later consumer reads the
        immutable rendered forms.  Rendering is per-constituency: the
        originator's codec plus whatever the live subscribers speak —
        an all-binary plane never builds a JSON tree."""
        self.log_seq += 1
        # a durable log always needs the bin rendering: the disk record
        # IS the batch's bin events (rendered once here, under the lock
        # where live objects are safe, then reused by the watch fan-out)
        need_bin = (
            origin_codec == CODEC_BIN
            or self.durable_log is not None
            or any(
                s.codec == CODEC_BIN and not s.closed
                for s in self._subscribers
            )
        )
        need_json = origin_codec == CODEC_JSON or any(
            s.codec == CODEC_JSON and not s.closed for s in self._subscribers
        )
        json_events = None
        bin_events = None
        if need_json:
            json_events = []
            for meta, obj in zip(metas, objs):
                ev = dict(meta)
                if meta.get("kind") == "Event":
                    ev["event"] = to_wire(obj)
                else:
                    ev["obj"] = None if obj is None else to_wire(obj)
                json_events.append(ev)
        if need_bin:
            bin_events = []
            for meta, obj in zip(metas, objs):
                native = dict(meta)
                if meta.get("kind") == "Event":
                    native["event"] = obj
                else:
                    native["obj"] = obj
                bin_events.append(Raw(encode_value(native)))
        batch = _Batch(self.log_seq, metas, json_events, bin_events)
        note_access("VersionedStore.replay_log")  # lockset witness
        if self.durable_log is not None:
            self.durable_log.append_batch(
                self.log_seq, self.epoch, batch.bin_events()
            )
            if self.durable_log.checkpoint_due():
                self._checkpoint_locked()
        self.replay_log.append(batch)
        self._log_events += len(metas)
        while (
            self._log_events > self.replay_log_events
            and len(self.replay_log) > 1
        ):
            dropped = self.replay_log.popleft()
            self._log_events -= len(dropped.metas)
            self.compacted_seq = dropped.seq
            self.registry.inc(
                "karpenter_store_compactions_total", {"log": "replay"}
            )
        for sub in self._subscribers:
            if sub.identity != origin:
                sub.offer(batch)
        if self._subscribers:
            self.registry.set(
                "karpenter_store_watch_queue_depth",
                max(len(s.batches) for s in self._subscribers),
            )
        return batch

    def append_cluster_event(
        self,
        kind,
        reason,
        obj_name,
        message="",
        origin: str = "",
        origin_codec: str = CODEC_JSON,
    ) -> int:
        """The durable cluster-event ledger: append, broadcast, and keep
        the ledger bounded (the snapshot ships only what is retained).
        Returns the appended event's event_rv."""
        with self.lock:
            self.kube.record_event(kind, reason, obj_name, message)
            self.event_rv += 1
            tup = tuple(self.kube.events[-1])
            meta = {
                "kind": "Event",
                "verb": "append",
                "event_rv": self.event_rv,
            }
            self._commit([meta], [tup], origin, origin_codec)
            self._trim_events_locked()
            return self.event_rv

    def _trim_events_locked(self) -> None:
        if len(self.kube.events) > self.events_cap:
            del self.kube.events[: len(self.kube.events) - self.events_cap]
            self.registry.inc(
                "karpenter_store_compactions_total", {"log": "events"}
            )

    # ------------------------------------------------------------- snapshot
    def snapshot(self, codec: str = CODEC_JSON) -> dict:
        """Full-state snapshot in the given codec's object form (trees
        for JSON, native objects for bin — MUST be encoded under the
        lock in the bin case, the objects are live)."""
        native = codec == CODEC_BIN
        kinds: Dict[str, dict] = {}
        for kind, (_cls, attr, key_fn) in STORE_KINDS.items():
            kinds[kind] = {
                key_fn(obj): {
                    "rv": self.rvs.get((kind, key_fn(obj)), 0),
                    "obj": obj if native else to_wire(obj),
                }
                for obj in getattr(self.kube, attr).values()
            }
        return {
            "rv": self.rv,
            "seq": self.log_seq,
            "event_rv": self.event_rv,
            "kinds": kinds,
            "events": [
                tuple(e) if native else to_wire(tuple(e))
                for e in self.kube.events
            ],
        }

    def covers(self, since_seq: int, epoch: str = "") -> bool:
        """Whether the replay log can reconstruct everything after
        ``since_seq``.  The cursor must come from THIS epoch (seq spaces
        are per-VersionedStore; a stale cursor from a previous store's
        space proves nothing).  since_seq 0 means "from genesis" — only
        a log that never compacted AND started with this store's birth
        (seq 0) covers that, and a store handed a pre-populated
        KubeStore never does (its initial state predates the log)."""
        if epoch != self.epoch:
            return False
        if since_seq > self.log_seq or since_seq < self.compacted_seq:
            return False
        if since_seq == 0:
            # genesis replay is only complete when the log holds every
            # event since this store's birth — a store handed a
            # pre-populated KubeStore (durable restart) has state that
            # predates the log, so 0 must fall back to a snapshot
            return bool(self.replay_log) and self.replay_log[0].seq == 1
        return True

    def replay_since(self, since_seq: int) -> List[_Batch]:
        return [b for b in self.replay_log if b.seq > since_seq]

    def subscribe(
        self,
        identity: str,
        codec: str = CODEC_JSON,
        since_seq: Optional[int] = None,
        cap: Optional[int] = None,
        epoch: str = "",
    ) -> Tuple[str, object, "_Subscriber"]:
        """Atomically register + build the initial sync: returns
        (mode, payload, sub) where mode is "replay" (payload = batches
        to flatten) or "snapshot" (payload = snapshot dict).  Counting:
        a reconnect (since_seq > 0) counts into
        karpenter_store_resync_total{kind}.  ``cap`` overrides the
        server-wide per-subscriber queue bound (the fleet simulator
        wedges one sink with a tiny cap without touching the healthy
        subscribers')."""
        with self.lock:
            sub = _Subscriber(
                identity, codec, cap or self.watch_queue_batches, self.lock
            )
            since = since_seq or 0
            if since > 0 and self.covers(since, epoch):
                mode: str = "replay"
                payload: object = self.replay_since(since)
            else:
                mode = "snapshot"
                payload = self.snapshot(codec)
            if since > 0:
                self.registry.inc(
                    "karpenter_store_resync_total", {"kind": mode}
                )
            sub.delivered_seq = self.log_seq
            self._subscribers.append(sub)
            self.registry.set(
                "karpenter_store_watch_clients", len(self._subscribers)
            )
            return mode, payload, sub

    def unsubscribe(self, sub: _Subscriber) -> None:
        with self.lock:
            if sub in self._subscribers:
                self._subscribers.remove(sub)
            self.registry.set(
                "karpenter_store_watch_clients", len(self._subscribers)
            )

    # ----------------------------------------------------------- replication
    def apply_replicated(self, events: List[dict]) -> None:
        """Read-replica ingestion: apply the primary's events verbatim —
        (each commit gets a REPLICA-local seq: seq spaces are per-server,
        and the follower tracks the primary's cursor separately) —
        objects land in the kube dicts directly (the cascades already
        materialized in the primary's event stream) and keep the
        PRIMARY's rv numbers, so replica watchers observe the same rv
        ordering the primary's watchers do."""
        with self.lock:
            metas: List[dict] = []
            objs: List[object] = []
            for ev in events:
                if ev.get("kind") == "Event":
                    tup = materialize(ev["event"])
                    if ev.get("event_rv", 0) > self.event_rv:
                        self.event_rv = ev["event_rv"]
                        self.kube.events.append(tup)
                        self._trim_events_locked()
                    metas.append(
                        {
                            "kind": "Event",
                            "verb": "append",
                            "event_rv": ev.get("event_rv", 0),
                        }
                    )
                    objs.append(tup)
                    continue
                spec = STORE_KINDS.get(ev.get("kind"))
                if spec is None:
                    continue
                _cls, attr, _key_fn = spec
                key, rv = ev["key"], ev["rv"]
                store_dict = getattr(self.kube, attr)
                if ev["verb"] == "delete":
                    store_dict.pop(key, None)
                    obj = None
                else:
                    obj = materialize(ev["obj"])
                    store_dict[key] = obj
                self.rvs[(ev["kind"], key)] = rv
                self.rv = max(self.rv, rv)
                metas.append(
                    {
                        "rv": rv,
                        "kind": ev["kind"],
                        "verb": ev["verb"],
                        "key": key,
                    }
                )
                objs.append(obj)
            if metas:
                # replica mirror objects are replaced wholesale per
                # event (never mutated in place), so rendering from them
                # under this lock is exactly as safe as on the primary;
                # bin is the compact default when no one needs trees yet
                self._commit(metas, objs, origin="", origin_codec=CODEC_BIN)

    def apply_replicated_snapshot(self, snap: dict) -> None:
        """Full resync from the primary: adopt its state wholesale.  The
        local replay log's continuity is broken, so it resets and every
        replica watcher is forced onto its own resync path."""
        with self.lock:
            # rvs REPLACED wholesale alongside the objects: a snapshot
            # has no tombstones, so keeping old entries for keys the
            # primary deleted (or stale pre-delete rvs) would leave this
            # mirror's rv map permanently diverged from what it serves
            self.rvs = {}
            for kind, (_cls, attr, _key_fn) in STORE_KINDS.items():
                store_dict = getattr(self.kube, attr)
                store_dict.clear()
                for key, entry in snap["kinds"].get(kind, {}).items():
                    store_dict[key] = materialize(entry["obj"])
                    self.rvs[(kind, key)] = entry["rv"]
            # ASSIGNED like the rvs map above, never maxed: the primary
            # may have restarted into a fresh (lower) rv space, and a
            # replica reporting an inflated rv would make wait_synced
            # against it return before convergence
            self.rv = snap.get("rv", 0)
            self.event_rv = snap.get("event_rv", 0)
            # this replica's --events-cap is an invariant even when the
            # primary's ledger is larger: adopt only the newest tail
            self.kube.events = [
                materialize(e)
                for e in snap.get("events", [])[-self.events_cap :]
            ]
            self.replay_log.clear()
            self._log_events = 0
            self.log_seq += 1
            self.compacted_seq = self.log_seq
            # genuinely a NEW epoch: this mirror adopted a (possibly
            # lower) rv space wholesale, so its own watchers' cursors —
            # seq AND per-key rvs — are meaningless; rotating the epoch
            # id is what makes them find out and reset
            self.epoch = os.urandom(8).hex()
            for sub in self._subscribers:
                if not sub.closed:
                    sub.batches.clear()
                    sub.pending_resync = True
                    # NOT an overflow: the store's own continuity broke
                    sub.forced_reason = "epoch"
                    sub.cond.notify_all()

    # ------------------------------------------------------------- migration
    def export_entries(
        self, self_index: int, new_n: int
    ) -> Dict[str, List[dict]]:
        """Read-only migration scan: every key this shard holds whose
        owner under an ``new_n``-shard topology is NOT this shard,
        grouped by new owner (string keys — the groups ride a JSON
        control-plane frame).  Leases never export: they are pinned to
        ``LEASE_SHARD`` under every topology (service/shardrouter.py),
        so the leadership CAS space never migrates."""
        out: Dict[str, List[dict]] = {}
        with self.lock:
            for kind, (_cls, attr, key_fn) in STORE_KINDS.items():
                if kind == "Lease":
                    continue
                for key, obj in getattr(self.kube, attr).items():
                    owner = shard_of(kind, key, new_n)
                    if owner == self_index:
                        continue
                    out.setdefault(str(owner), []).append(
                        {
                            "kind": kind,
                            "key": key,
                            "rv": self.rvs.get((kind, key), 0),
                            "obj": to_wire(obj),
                        }
                    )
        return out

    def import_entries(self, entries) -> int:
        """Adopt migrated keys VERBATIM — object bytes and per-key rv
        both (the rv travels with the key, so a client whose dirty
        flush carries an old-owner base_rv still fences correctly at
        the new owner).  Ends with an epoch rotation: ownership
        changed, so no pre-import cursor may claim coverage."""
        n = 0
        with self.lock:
            for e in entries:
                spec = STORE_KINDS.get(e.get("kind"))
                if spec is None or e.get("kind") == "Lease":
                    continue
                _cls, attr, _key_fn = spec
                rv = e.get("rv", 0)
                getattr(self.kube, attr)[e["key"]] = materialize(e["obj"])
                self.rvs[(e["kind"], e["key"])] = rv
                # adopt at least the imported rv space's high-water
                # mark: this shard's future commits must stamp rvs
                # ABOVE every imported one, or a client's stale-echo
                # check would drop fresh writes to migrated keys
                self.rv = max(self.rv, rv)
                n += 1
            self.rotate_epoch("migration")
        return n

    def drop_keys(self, keys) -> int:
        """Drop migrated keys WITHOUT verb cascades (delete_node would
        re-pend its pods — but those pods moved WITH their node; the
        ownership transfer is not a semantic delete).  Epoch-rotates
        like import: the fence is what keeps a cursor from spanning
        the ownership change."""
        n = 0
        with self.lock:
            for kind, key in keys:
                spec = STORE_KINDS.get(kind)
                if spec is None:
                    continue
                _cls, attr, _key_fn = spec
                if getattr(self.kube, attr).pop(key, None) is not None:
                    n += 1
                self.rvs.pop((kind, key), None)
            self.rotate_epoch("migration")
        return n

    def close_subscribers(self) -> None:
        with self.lock:
            for sub in self._subscribers:
                sub.close()


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: "StoreServer" = self.server  # type: ignore[assignment]
        server.track_conn(self.request)
        try:
            self._serve(server)
        finally:
            server.untrack_conn(self.request)

    def _serve(self, server: "StoreServer") -> None:
        codec = CODEC_JSON
        while True:
            try:
                payload = recv_frame(self.request)
            except (ConnectionError, OSError):
                return
            except ValueError as exc:
                log.warning("dropping malformed store frame: %s", exc)
                return
            server.count_bytes("received", codec, len(payload) + 8)
            try:
                header = decode_payload(payload, codec)
            except (ValueError, UnicodeDecodeError) as exc:
                log.warning("undecodable %s store frame: %s", codec, exc)
                return
            method = str(header.get("method", "?"))
            if method == "watch":
                # counted like every other RPC (docs/metrics.md lists
                # watch in the per-method series); the span for the
                # snapshot phase is recorded inside serve_watch, where
                # the ctx is still in hand
                server.registry.inc(
                    "karpenter_store_requests_total", {"method": "watch"}
                )
                server.serve_watch(self.request, header)
                return
            # adopt the CLIENT's trace context for the handling span:
            # the server's span log records this RPC under the caller's
            # tick trace ID, stitching the two processes' timelines
            # (state/remote.py ships the ctx; obs/render.py merges)
            ctx = header.get("ctx") or {}
            t0 = time.perf_counter()
            try:
                with trace_context(ctx.get("trace_id", "")), \
                        server.tracer.span(f"store.{method}"):
                    response = server.dispatch(header, codec)
            except Exception as exc:
                log.exception("store request failed")
                response = {"status": "error", "error": str(exc)}
            server.registry.inc(
                "karpenter_store_requests_total", {"method": method}
            )
            server.registry.observe(
                "karpenter_store_request_seconds",
                time.perf_counter() - t0,
                {"method": method},
            )
            try:
                out = encode_payload(response, codec)
                server.count_bytes("sent", codec, len(out) + 8)
                send_frame(self.request, out)
            except (ConnectionError, OSError):
                return
            if (
                method == "hello"
                and response.get("status") == "ok"
                and response.get("codec")
            ):
                # the ack itself rode the old codec; everything after
                # speaks the negotiated one
                codec = response["codec"]


class StoreServer(socketserver.ThreadingTCPServer):
    """Serve the shared store on (host, port); port 0 picks a free port.

    ``codecs`` lists the payload codecs this server negotiates (bin1
    preferred).  ``legacy_protocol=True`` emulates a pre-fleet-scale
    server — no ``hello``, inline-snapshot watches — for the
    mixed-version compatibility tests.  ``replica_of=(host, port)``
    starts this server as a READ REPLICA: a follower thread mirrors the
    primary over the watch protocol and every write method refuses."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        store: Optional[VersionedStore] = None,
        codecs: Tuple[str, ...] = (CODEC_BIN, CODEC_JSON),
        legacy_protocol: bool = False,
        replica_of: Optional[Tuple[str, int]] = None,
        shard_index: int = 0,
    ):
        super().__init__((host, port), _Handler)
        self.store = store or VersionedStore()
        # this server's position in the shard topology (0 for the
        # unsharded single-store deployment): shard_export computes
        # "what do I no longer own?" relative to it
        self.shard_index = shard_index
        self.codecs = tuple(codecs)
        self.legacy_protocol = legacy_protocol
        self.replica_of = replica_of
        self.read_only = replica_of is not None
        self._thread: Optional[threading.Thread] = None
        # the server process's OWN observability surface: request
        # counters + handling spans (recorded under each client's trace
        # ID) + a ledger, all served by --telemetry-port in main().  The
        # tracer stays on — spans are two perf_counter calls per RPC,
        # and a store server without a span log cannot answer "which
        # replica's tick was slow?"
        self.registry = Registry()
        self.tracer = Tracer(enabled=True)
        self.ledger = EventLedger(registry=self.registry)
        self.registry.ledger = self.ledger
        self.store.registry = self.registry
        if self.store.durable_log is not None:
            # the log's counters land on the serving process's surface
            self.store.durable_log.registry = self.registry
        # live connections, so stop() can sever them: a stopped server
        # must not keep answering established RPC sockets from daemon
        # handler threads (a real process exit closes them; the
        # in-process stop must behave the same, or clients talk to a
        # zombie serving pre-stop state)
        self._conns: set = set()
        self._conns_lock = make_lock("StoreServer._conns_lock")
        # follower plumbing (read replicas)
        self._primary_seq = 0
        self._primary_epoch = ""

        self._follow_stop = threading.Event()
        self._follow_sock: Optional[socket.socket] = None
        self._follow_thread: Optional[threading.Thread] = None
        if self.replica_of is not None:
            self._follow_thread = threading.Thread(
                target=self._follow_loop,
                daemon=True,
                name="store-replica-follow",
            )
            self._follow_thread.start()

    # -------------------------------------------------------------- metrics
    def count_bytes(self, direction: str, codec: str, n: int) -> None:
        if direction == "sent":
            self.registry.inc(
                "karpenter_store_bytes_sent_total", {"codec": codec}, by=n
            )
        else:
            self.registry.inc(
                "karpenter_store_bytes_received_total", {"codec": codec}, by=n
            )

    # ------------------------------------------------------------- dispatch
    def _negotiated_codec(self, header: dict) -> str:
        if (
            CODEC_BIN in self.codecs
            and CODEC_BIN in (header.get("codecs") or ())
            and header.get("schema_fp") == SCHEMA_FP
        ):
            return CODEC_BIN
        return CODEC_JSON

    def dispatch(self, header: dict, codec: str = CODEC_JSON) -> dict:
        method = header.get("method")
        store = self.store
        if method == "ping":
            return {"status": "ok"}
        if method == "hello":
            if self.legacy_protocol:
                # the pre-fleet-scale server didn't know hello; the
                # client treats the error as "speak JSON"
                return {"status": "error", "error": "unknown method hello"}
            return {
                "status": "ok",
                "codec": self._negotiated_codec(header),
                "schema_fp": SCHEMA_FP,
                "read_only": self.read_only,
            }
        if method == "stat":
            with store.lock:
                return {
                    "status": "ok",
                    "rv": store.rv,
                    "seq": store.log_seq,
                    "epoch": store.epoch,
                    "event_count": len(store.kube.events),
                    "read_only": self.read_only,
                }
        if self.read_only and method in _WRITE_METHODS:
            return {
                "status": "error",
                "error": "read-only replica: writes go to the primary "
                f"store at {self.replica_of[0]}:{self.replica_of[1]}",
            }
        if method == "put":
            return self._put(header, codec)
        if method == "delete":
            return self._delete(header, codec)
        if method == "bind_pod":
            # store.lock held across fence AND mutate (as in _put): a
            # fence that releases the lock before the mutation is a
            # TOCTOU hole for the stale write it exists to stop
            with store.lock:
                conflict = self._fence(
                    "Pod", header["key"], header.get("base_rv")
                )
                if conflict is not None:
                    return conflict
                batch = store.mutate(
                    lambda: store.kube.bind_pod(
                        header["key"], header["node_name"]
                    ),
                    origin=header.get("identity", ""),
                    origin_codec=codec,
                )
            return {
                "status": "ok",
                "events": batch.events_for(codec) if batch else [],
            }
        if method == "evict_pod":
            with store.lock:
                conflict = self._fence(
                    "Pod", header["key"], header.get("base_rv")
                )
                if conflict is not None:
                    return conflict
                batch = store.mutate(
                    lambda: store.kube.evict_pod(header["key"]),
                    origin=header.get("identity", ""),
                    origin_codec=codec,
                )
            return {
                "status": "ok",
                "events": batch.events_for(codec) if batch else [],
            }
        if method == "record_event":
            event_rv = store.append_cluster_event(
                header["kind"],
                header["reason"],
                header["obj_name"],
                header.get("message", ""),
                origin=header.get("identity", ""),
                origin_codec=codec,
            )
            return {"status": "ok", "event_rv": event_rv}
        if method == "lease_acquire":
            return self._lease_acquire(header, codec)
        if method == "lease_renew":
            return self._lease_renew(header)
        if method == "lease_release":
            return self._lease_release(header, codec)
        if method == "shard_export":
            entries = store.export_entries(
                self.shard_index, int(header.get("new_n", 1))
            )
            return {"status": "ok", "entries": entries}
        if method == "shard_import":
            imported = store.import_entries(header.get("entries", ()))
            return {"status": "ok", "imported": imported,
                    "epoch": store.epoch}
        if method == "shard_drop":
            dropped = store.drop_keys(header.get("keys", ()))
            return {"status": "ok", "dropped": dropped,
                    "epoch": store.epoch}
        return {"status": "error", "error": f"unknown method {method}"}

    def _put(self, header: dict, codec: str = CODEC_JSON) -> dict:
        store = self.store
        kind = header["kind"]
        spec = STORE_KINDS.get(kind)
        if spec is None or kind == "Lease":
            return {"status": "error", "error": f"unwritable kind {kind}"}
        cls, attr, key_fn = spec
        obj = materialize(header["obj"])
        if not isinstance(obj, cls):
            return {"status": "error", "error": f"object is not a {kind}"}
        key = key_fn(obj)
        with store.lock:
            conflict = self._fence(kind, key, header.get("base_rv"))
            if conflict is not None:
                return conflict
            verb = {
                "Pod": store.kube.put_pod,
                "Node": store.kube.put_node,
                "NodeClaim": store.kube.put_node_claim,
                "NodePool": store.kube.put_node_pool,
                "NodeClass": store.kube.put_node_class,
                "PodDisruptionBudget": store.kube.put_pdb,
                "StorageClass": store.kube.put_storage_class,
                "PersistentVolumeClaim": store.kube.put_pvc,
            }[kind]
            batch = store.mutate(
                lambda: verb(obj),
                origin=header.get("identity", ""),
                origin_codec=codec,
            )
            return {
                "status": "ok",
                "events": batch.events_for(codec) if batch else [],
            }

    def _fence(self, kind: str, key: str, base_rv) -> Optional[dict]:
        """Optimistic-concurrency check shared by delete/bind/evict: a
        deposed leader's straggler verb (stale base_rv) gets ``conflict``
        with the current object instead of clobbering — the same fencing
        ``put`` applies."""
        store = self.store
        with store.lock:
            cur = store.rvs.get((kind, key), 0)
            if base_rv is None or base_rv == cur:
                return None
            _cls, attr, _key_fn = STORE_KINDS[kind]
            existing = getattr(store.kube, attr).get(key)
            return {
                "status": "conflict",
                "rv": cur,
                "obj": to_wire(existing) if existing is not None else None,
            }

    def _delete(self, header: dict, codec: str = CODEC_JSON) -> dict:
        store = self.store
        kind, key = header["kind"], header["key"]
        spec = STORE_KINDS.get(kind)
        if spec is None or kind == "Lease":
            return {"status": "error", "error": f"undeletable kind {kind}"}
        _cls, attr, _key_fn = spec
        kube = store.kube

        def apply() -> None:
            if kind == "Pod":
                kube.delete_pod(key)
            elif kind == "Node":
                kube.delete_node(key)
            elif kind == "NodeClaim":
                kube.delete_node_claim(key)
            else:
                obj = getattr(kube, attr).pop(key, None)
                if obj is not None:
                    kube._notify(kind, "delete", obj)

        with store.lock:  # fence + mutate atomically (see bind_pod)
            conflict = self._fence(kind, key, header.get("base_rv"))
            if conflict is not None:
                return conflict
            batch = store.mutate(
                apply,
                origin=header.get("identity", ""),
                origin_codec=codec,
            )
        return {
            "status": "ok",
            "events": batch.events_for(codec) if batch else [],
        }

    # --------------------------------------------------------------- leases
    def _lease_acquire(self, header: dict, codec: str = CODEC_JSON) -> dict:
        store = self.store
        name = header["name"]
        with store.lock:
            acquired = None

            def apply() -> None:
                nonlocal acquired
                acquired = store.kube.try_acquire_lease(
                    name,
                    header["holder"],
                    header["now"],
                    header["duration_s"],
                )
                if acquired:
                    # every successful acquire-or-renew advances the CAS
                    # sequence so a competing renewer's base_rv goes stale
                    store.lease_seq[name] = store.lease_seq.get(name, 0) + 1

            batch = store.mutate(
                apply,
                origin=header.get("identity", ""),
                origin_codec=codec,
            )
            lease = store.kube.leases.get(name)
            return {
                "status": "ok",
                "acquired": bool(acquired),
                "rv": store.lease_seq.get(name, 0),
                # rv of THIS call's broadcast Lease event (fresh acquire
                # only; silent renewals broadcast nothing) — the
                # originator credits exactly this toward synced_rv
                "lease_event_rv": batch.max_rv if batch else 0,
                "lease": to_wire(lease) if lease is not None else None,
            }

    def _lease_renew(self, header: dict) -> dict:
        store = self.store
        name = header["name"]
        with store.lock:
            cur = store.lease_seq.get(name, 0)
            base_rv = header.get("base_rv")
            if base_rv is not None and base_rv != cur:
                # someone else mutated the lease since this renewer last
                # saw it — the renewal loses cleanly (optimistic CAS)
                return {
                    "status": "ok",
                    "renewed": False,
                    "conflict": True,
                    "rv": cur,
                }
            renewed = store.kube.renew_lease(
                name, header["holder"], header["now"]
            )
            if renewed:
                store.lease_seq[name] = cur + 1
            return {
                "status": "ok",
                "renewed": renewed,
                "rv": store.lease_seq.get(name, 0),
            }

    def _lease_release(self, header: dict, codec: str = CODEC_JSON) -> dict:
        store = self.store
        name = header["name"]
        with store.lock:
            lease = store.kube.leases.get(name)
            held = lease is not None and lease.holder == header["holder"]
            batch = store.mutate(
                lambda: store.kube.release_lease(name, header["holder"]),
                origin=header.get("identity", ""),
                origin_codec=codec,
            )
            if held:
                # only a release that actually freed the lease advances
                # the CAS sequence: a retried/stale release from a
                # non-holder is a no-op, and bumping the seq for it would
                # stale-out the REAL holder's next renewal base_rv
                store.lease_seq[name] = store.lease_seq.get(name, 0) + 1
            return {
                "status": "ok",
                "rv": store.lease_seq.get(name, 0),
                "lease_event_rv": batch.max_rv if batch else 0,
            }

    # ---------------------------------------------------------------- watch
    def _events_frame(self, batches: List[_Batch], codec: str) -> dict:
        events = [ev for b in batches for ev in b.events_for(codec)]
        return {"type": "events", "seq": batches[-1].seq, "events": events}

    def _resync_frame(self, mode: str, payload, codec: str) -> dict:
        """The one construction site for ``resync`` frames (part of the
        lint-rule-10 wire vocabulary): ``payload`` is a batch list for
        replay mode, a snapshot dict otherwise."""
        if mode == "replay":
            return {
                "type": "resync",
                "mode": "replay",
                "seq": self.store.log_seq,
                "epoch": self.store.epoch,
                "events": [
                    ev for b in payload for ev in b.events_for(codec)
                ],
            }
        return {
            "type": "resync",
            "mode": "snapshot",
            "seq": self.store.log_seq,
            "epoch": self.store.epoch,
            "snapshot": payload,
        }

    def _frame_payload(self, batches: List[_Batch], codec: str) -> bytes:
        """Encoded events frame for a drained batch run.  The common
        case — an up-to-date subscriber draining exactly one batch —
        ships the batch's content-addressed bin frame bytes, rendered
        once for the whole fan-out."""
        if codec == CODEC_BIN and len(batches) == 1:
            return batches[0].bin_frame_payload()
        return encode_payload(self._events_frame(batches, codec), codec)

    def _resync_payload_locked(self, sub: _Subscriber, codec: str):
        """Store lock held: build the overflow-coalesced resync frame.
        Returns encoded BYTES for bin (a bin snapshot holds live object
        references, so it must be rendered before the lock drops) or
        the frame DICT for JSON (trees are immutable — the expensive
        json.dumps of a large snapshot must NOT stall every writer on
        the store lock; the caller encodes outside)."""
        store = self.store
        self.registry.inc(
            "karpenter_store_resync_total", {"kind": sub.forced_reason}
        )
        if sub.delivered_seq > 0 and store.covers(
            sub.delivered_seq, store.epoch
        ):
            frame = self._resync_frame(
                "replay", store.replay_since(sub.delivered_seq), codec
            )
        else:
            frame = self._resync_frame(
                "snapshot", store.snapshot(codec), codec
            )
        sub.delivered_seq = store.log_seq
        return encode_payload(frame, codec) if codec == CODEC_BIN else frame

    def serve_watch(self, sock, header: dict) -> None:
        identity = header.get("identity", "")
        ctx = header.get("ctx") or {}
        store = self.store
        legacy = self.legacy_protocol or "codecs" not in header
        codec = CODEC_JSON if legacy else self._negotiated_codec(header)
        since_seq = None if legacy else header.get("since_seq")
        client_epoch = "" if legacy else str(header.get("epoch") or "")
        # span only the initial-sync phase (subscribe + snapshot/replay
        # frame) — the expensive, attributable part; the push loop below
        # lives as long as the connection and would make a meaningless
        # span
        with trace_context(ctx.get("trace_id", "")), self.tracer.span(
            "store.watch", identity=identity
        ):
            with store.lock:
                mode, payload, sub = store.subscribe(
                    identity, codec, since_seq, epoch=client_epoch
                )
                if legacy:
                    # JSON trees are immutable: encode outside the lock
                    frames = [{"status": "ok", "snapshot": payload}]
                else:
                    ack = encode_payload(
                        {
                            "status": "ok",
                            "codec": codec,
                            "resync": mode,
                            "seq": store.log_seq,
                            "epoch": store.epoch,
                            "schema_fp": SCHEMA_FP,
                        },
                        CODEC_JSON,
                    )
                    body = self._resync_frame(mode, payload, codec)
                    # only a BIN snapshot must render under the lock
                    # (it references live objects); the JSON form is an
                    # immutable tree, and dumping a large snapshot
                    # inside the lock would stall every writer
                    frames = [
                        ack,
                        encode_payload(body, codec)
                        if codec == CODEC_BIN
                        else body,
                    ]
        try:
            for i, f in enumerate(frames):
                if isinstance(f, dict):  # deferred JSON encode
                    f = encode_payload(f, CODEC_JSON)
                # the ack always rides JSON; everything after, the codec
                self.count_bytes(
                    "sent",
                    CODEC_JSON if (not legacy and i == 0) else codec,
                    len(f) + 8,
                )
                send_frame(sock, f)
            while True:
                pending_dict = None
                with sub.cond:
                    while not (
                        sub.batches or sub.pending_resync or sub.closed
                    ):
                        sub.cond.wait(1.0)
                    if sub.closed:
                        return
                    if sub.pending_resync:
                        if legacy:
                            # the legacy stream cannot express a resync
                            # marker; dropping the connection forces the
                            # old client's snapshot-reconnect path
                            return
                        sub.pending_resync = False
                        out = self._resync_payload_locked(sub, codec)
                        if isinstance(out, dict):  # JSON: encode unlocked
                            pending_dict, out = out, None
                    else:
                        note_access("_Subscriber.batches")
                        batches = list(sub.batches)
                        sub.batches.clear()
                        sub.delivered_seq = batches[-1].seq
                        out = None
                if out is None and pending_dict is not None:
                    out = encode_payload(pending_dict, codec)
                    pending_dict = None
                if out is None:
                    # event frames encode OUTSIDE the lock: trees and
                    # pre-rendered bin payloads are immutable
                    if legacy:
                        # faithful pre-fleet emulation: no seq on the
                        # wire (the old protocol had no seq space)
                        out = encode_payload(
                            {
                                "type": "events",
                                "events": [
                                    ev
                                    for b in batches
                                    for ev in b.events_for(CODEC_JSON)
                                ],
                            },
                            CODEC_JSON,
                        )
                    else:
                        out = self._frame_payload(batches, codec)
                self.count_bytes("sent", codec, len(out) + 8)
                send_frame(sock, out)
        except (ConnectionError, OSError):
            return
        finally:
            store.unsubscribe(sub)

    # ------------------------------------------------------------ replica
    def _follow_loop(self) -> None:
        """Read-replica follower: mirror the primary over the SAME watch
        protocol clients use, tracking the primary's seq space so a
        reconnect delta-resyncs instead of re-snapshotting.  The
        dial/handshake/backoff/resync choreography is the SHARED
        watch-client primitive (service/watchclient.py — one definition
        with RemoteKubeStore's mirror loop); the follower contributes
        the replica handshake and the verbatim-apply frame handler."""
        host, port = self.replica_of  # type: ignore[misc]

        def hello() -> dict:
            return {
                "method": "watch",
                "identity": f"replica@{self.address[1]}",
                "codecs": list(self.codecs),
                "schema_fp": SCHEMA_FP,
                "since_seq": self._primary_seq,
                "epoch": self._primary_epoch,
            }

        def legacy_snapshot(snapshot: dict) -> None:
            self.store.apply_replicated_snapshot(snapshot)
            self._primary_seq = snapshot.get("seq", 0)

        def set_live(sock) -> None:
            self._follow_sock = sock

        WatchChannelClient(
            dial=lambda: socket.create_connection((host, port), timeout=5.0),
            hello=hello,
            tx=send_frame,
            rx=lambda sock, _codec: recv_frame(sock),
            on_epoch=self._note_primary_epoch,
            on_legacy_snapshot=legacy_snapshot,
            on_frame=lambda frame, _initial: self._apply_frame(frame),
            stop=self._follow_stop,
            on_live=set_live,
        ).run()

    def _note_primary_epoch(self, epoch: str) -> None:
        """Adopt the primary's epoch id, zeroing the follow cursor the
        moment a CHANGE is detected — BEFORE any payload applies, so an
        interrupted handshake can never leave a new-epoch label over an
        old-space seq the busy new primary's log might falsely cover."""
        if epoch != self._primary_epoch:
            if self._primary_epoch:
                self._primary_seq = 0
            self._primary_epoch = epoch

    def _apply_frame(self, frame: dict) -> None:
        kind = frame.get("type")
        if kind == "resync" and "epoch" in frame:
            self._note_primary_epoch(str(frame.get("epoch") or ""))
        if kind == "events":
            self.store.apply_replicated(frame.get("events", ()))
            # .get: a legacy primary's frames carry no seq — the cursor
            # stays 0 and every reconnect snapshots, which is correct
            self._primary_seq = frame.get("seq", self._primary_seq)
        elif kind == "resync":
            if frame.get("mode") == "snapshot":
                self.store.apply_replicated_snapshot(frame["snapshot"])
            else:
                self.store.apply_replicated(frame.get("events", ()))
            self._primary_seq = frame.get("seq", self._primary_seq)

    # ------------------------------------------------------------ lifecycle
    @property
    def address(self) -> Tuple[str, int]:
        return self.server_address  # type: ignore[return-value]

    def start_background(self) -> "StoreServer":
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="store-server"
        )
        self._thread.start()
        return self

    def track_conn(self, sock) -> None:
        with self._conns_lock:
            self._conns.add(sock)

    def untrack_conn(self, sock) -> None:
        with self._conns_lock:
            self._conns.discard(sock)

    def stop(self) -> None:
        self._follow_stop.set()
        follow_sock = self._follow_sock
        if follow_sock is not None:
            try:
                follow_sock.close()
            except OSError:
                pass
        self.store.close_subscribers()
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for sock in conns:
            try:
                sock.close()
            except OSError:
                pass
        self.shutdown()
        self.server_close()
        if self._follow_thread is not None:
            self._follow_thread.join(timeout=2.0)
            self._follow_thread = None


def main(argv=None) -> int:
    """``python -m karpenter_tpu store-server`` (also reachable as
    ``python -m karpenter_tpu.service.store_server``)."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m karpenter_tpu store-server",
        description="karpenter-tpu shared cluster-store server",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8082)
    parser.add_argument(
        "--telemetry-port",
        type=int,
        default=8083,
        help="HTTP port for /metrics, /healthz, /events and /trace on "
        "THIS process (0 disables) — the store server's request "
        "counters and its span log, which records every RPC under the "
        "calling replica's trace ID",
    )
    parser.add_argument(
        "--replica-of",
        default="",
        metavar="HOST:PORT",
        help="follow the primary store at HOST:PORT and serve READ "
        "traffic (snapshot/watch/stat) with its rv ordering preserved; "
        "every write method refuses and names the primary",
    )
    parser.add_argument(
        "--replay-log-events",
        type=int,
        default=REPLAY_LOG_EVENTS,
        help="events retained for delta watch resync before compaction",
    )
    parser.add_argument(
        "--watch-queue-batches",
        type=int,
        default=WATCH_QUEUE_BATCHES,
        help="per-subscriber queued batches before a slow client is "
        "coalesced onto a forced resync",
    )
    parser.add_argument(
        "--events-cap",
        type=int,
        default=EVENTS_CAP,
        help="durable cluster-event ledger bound (oldest trimmed)",
    )
    parser.add_argument(
        "--json-only",
        action="store_true",
        help="disable bin1 negotiation (tagged JSON only)",
    )
    parser.add_argument(
        "--log-dir",
        default="",
        help="directory for the crash-durable replay segment; empty "
        "disables durability (a restart forces snapshot resyncs). "
        "A restarted server re-adopts its epoch from the segment and "
        "serves DELTA resyncs from disk",
    )
    parser.add_argument(
        "--log-fsync",
        default=FSYNC_ALWAYS,
        choices=("always", "off"),
        help="fsync policy for the durable replay log: 'always' syncs "
        "every append (crash loses nothing acknowledged), 'off' leaves "
        "flushing to the OS (crash may lose the unsynced tail, which "
        "recovery drops as torn)",
    )
    parser.add_argument(
        "--shard-index",
        type=int,
        default=0,
        help="this server's index in the key-sharded store topology "
        "(0 for the unsharded deployment); shard_export routes moving "
        "keys relative to it",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    replica_of = None
    if args.replica_of:
        rhost, _, rport = args.replica_of.partition(":")
        replica_of = (rhost, int(rport) if rport else 8082)
    durable_log = None
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
        durable_log = DurableReplayLog(
            os.path.join(
                args.log_dir, f"store-shard-{args.shard_index}.log"
            ),
            fsync=args.log_fsync,
        )
    store = VersionedStore(
        replay_log_events=args.replay_log_events,
        watch_queue_batches=args.watch_queue_batches,
        events_cap=args.events_cap,
        durable_log=durable_log,
    )
    server = StoreServer(
        args.host,
        args.port,
        store=store,
        codecs=(CODEC_JSON,) if args.json_only else (CODEC_BIN, CODEC_JSON),
        replica_of=replica_of,
        shard_index=args.shard_index,
    )
    telemetry = None
    if args.telemetry_port:
        from karpenter_tpu.obs.http import start_telemetry

        telemetry = start_telemetry(
            args.telemetry_port,
            server.registry,
            tracer=server.tracer,
            ledger=server.ledger,
        )
        log.info("telemetry on :%d/metrics", args.telemetry_port)
    log.info(
        "cluster store listening on %s:%d%s",
        *server.address,
        f" (read replica of {args.replica_of})" if replica_of else "",
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:  # pragma: no cover - CLI path
        pass
    finally:
        if telemetry is not None:
            telemetry.shutdown()
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
