"""Shared cluster-store server: one durable KubeStore, many replicas.

The reference gets HA for free because durable state lives in the
kube-apiserver and the election Lease is a shared coordination/v1 object;
each controller replica is a thin client.  This server is that apiserver
analogue for the simulation backend: it owns ONE durable `KubeStore`
(wrapped in `VersionedStore` for resourceVersion bookkeeping) and serves
it over the same length-prefixed socket protocol as the solver sidecar
(service/codec.py), so `replicas: 2` behind the store-backed Lease
election becomes real — the Lease CAS and every object write land in one
place, and standby replicas keep their mirrors warm over a watch stream.

Methods (JSON header, no array blobs):

- ``ping``                          liveness
- ``stat``                          {rv, event_count}
- ``put``    {kind, obj, base_rv}   optimistic-concurrency write
- ``delete`` {kind, key, base_rv}   delete (cascades run server-side)
- ``bind_pod`` / ``evict_pod``      semantic pod verbs (base_rv-fenced)
- ``record_event``                  append a store event
- ``lease_acquire`` / ``lease_renew`` / ``lease_release``
                                    the coordination/v1 Lease CAS surface
                                    (utils/leader.py), atomic server-side
- ``watch``  {identity, }           long-lived: full snapshot frame, then
                                    pushed event frames as mutations land

Every mutation is assigned a monotonically increasing resourceVersion;
``put`` with a stale ``base_rv`` returns ``status: conflict`` with the
current object so the writer can resync instead of clobbering — the
single-writer invariant for competing replicas comes from the Lease, the
rv check fences the deposed leader's stragglers.
"""

from __future__ import annotations

import logging
import queue
import socketserver
import threading
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.metrics.registry import Registry
from karpenter_tpu.obs.context import trace_context
from karpenter_tpu.obs.events import EventLedger
from karpenter_tpu.service.codec import decode, encode, recv_frame, send_frame
from karpenter_tpu.state.kube import KubeStore
from karpenter_tpu.state.wire import STORE_KINDS, from_wire, to_wire
from karpenter_tpu.utils.trace import Tracer

log = logging.getLogger(__name__)


class VersionedStore:
    """A KubeStore plus resourceVersion bookkeeping and watch broadcast.

    Survives server restarts: constructing a new `StoreServer` over the
    same `VersionedStore` keeps both the objects and their rvs, so
    reconnecting clients resync consistently (the durable half of the
    store lives here, the serving half in `StoreServer`).
    """

    def __init__(self, kube: Optional[KubeStore] = None):
        self.kube = kube or KubeStore()
        self.lock = threading.RLock()
        self.rv = 0
        self.rvs: Dict[Tuple[str, str], int] = {}
        # per-lease CAS sequence, SEPARATE from the broadcast rv space:
        # silent renewals (no watch event) must not advance `rv`, or
        # other clients could never sync up to the stat rv
        self.lease_seq: Dict[str, int] = {}
        self.event_rv = 0
        self._subscribers: List["_Subscriber"] = []
        self._recorded: List[dict] = []
        self.kube.watch(self._record)

    # ------------------------------------------------------------ recording
    def _record(self, kind: str, verb: str, obj) -> None:
        """KubeStore notification hook: capture every mutation a verb
        application produced (bind_pod touches a Pod and maybe a PVC;
        delete_node re-pends its pods) as state-based events."""
        spec = STORE_KINDS.get(kind)
        if spec is None:
            return
        cls, attr, key_fn = spec
        key = key_fn(obj)
        self.rv += 1
        self.rvs[(kind, key)] = self.rv
        deleted = key not in getattr(self.kube, attr)
        self._recorded.append(
            {
                "rv": self.rv,
                "kind": kind,
                "verb": "delete" if deleted else "put",
                "key": key,
                "obj": None if deleted else to_wire(obj),
            }
        )

    def mutate(self, fn, origin: str = "") -> List[dict]:
        """Run `fn()` (KubeStore verbs) under the lock; collect the
        resulting events, broadcast them to every subscriber except the
        originator, and return them (for the originator's RPC response)."""
        with self.lock:
            self._recorded = []
            fn()
            events = self._recorded
            self._recorded = []
            if events:
                for sub in self._subscribers:
                    if sub.identity != origin:
                        sub.q.put(events)
            return events

    # ------------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        kinds: Dict[str, dict] = {}
        for kind, (_cls, attr, key_fn) in STORE_KINDS.items():
            kinds[kind] = {
                key_fn(obj): {
                    "rv": self.rvs.get((kind, key_fn(obj)), 0),
                    "obj": to_wire(obj),
                }
                for obj in getattr(self.kube, attr).values()
            }
        return {
            "rv": self.rv,
            "event_rv": self.event_rv,
            "kinds": kinds,
            "events": [to_wire(tuple(e)) for e in self.kube.events],
        }

    def subscribe(self, identity: str) -> Tuple[dict, "_Subscriber"]:
        """Atomically snapshot + register, so the stream has no gap."""
        with self.lock:
            snap = self.snapshot()
            sub = _Subscriber(identity)
            self._subscribers.append(sub)
            return snap, sub

    def unsubscribe(self, sub: "_Subscriber") -> None:
        with self.lock:
            if sub in self._subscribers:
                self._subscribers.remove(sub)


class _Subscriber:
    def __init__(self, identity: str):
        self.identity = identity
        self.q: "queue.Queue[Optional[List[dict]]]" = queue.Queue()


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        while True:
            try:
                payload = recv_frame(self.request)
            except (ConnectionError, OSError):
                return
            except ValueError as exc:
                log.warning("dropping malformed store frame: %s", exc)
                return
            header, _ = decode(payload)
            if header.get("method") == "watch":
                # counted like every other RPC (docs/metrics.md lists
                # watch in the per-method series); the span for the
                # snapshot phase is recorded inside serve_watch, where
                # the ctx is still in hand
                self.server.registry.inc(  # type: ignore[attr-defined]
                    "karpenter_store_requests_total", {"method": "watch"}
                )
                self.server.serve_watch(self.request, header)  # type: ignore[attr-defined]
                return
            # adopt the CLIENT's trace context for the handling span:
            # the server's span log records this RPC under the caller's
            # tick trace ID, stitching the two processes' timelines
            # (state/remote.py ships the ctx; obs/render.py merges)
            ctx = header.get("ctx") or {}
            method = str(header.get("method", "?"))
            try:
                with trace_context(ctx.get("trace_id", "")), \
                        self.server.tracer.span(f"store.{method}"):  # type: ignore[attr-defined]
                    response = self.server.dispatch(header)  # type: ignore[attr-defined]
            except Exception as exc:
                log.exception("store request failed")
                response = {"status": "error", "error": str(exc)}
            self.server.registry.inc(  # type: ignore[attr-defined]
                "karpenter_store_requests_total", {"method": method}
            )
            try:
                send_frame(self.request, encode(response, {}))
            except (ConnectionError, OSError):
                return


class StoreServer(socketserver.ThreadingTCPServer):
    """Serve the shared store on (host, port); port 0 picks a free port."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        store: Optional[VersionedStore] = None,
    ):
        super().__init__((host, port), _Handler)
        self.store = store or VersionedStore()
        self._thread: Optional[threading.Thread] = None
        # the server process's OWN observability surface: request
        # counters + handling spans (recorded under each client's trace
        # ID) + a ledger, all served by --telemetry-port in main().  The
        # tracer stays on — spans are two perf_counter calls per RPC,
        # and a store server without a span log cannot answer "which
        # replica's tick was slow?"
        self.registry = Registry()
        self.tracer = Tracer(enabled=True)
        self.ledger = EventLedger(registry=self.registry)
        self.registry.ledger = self.ledger

    # ------------------------------------------------------------- dispatch
    def dispatch(self, header: dict) -> dict:
        method = header.get("method")
        store = self.store
        if method == "ping":
            return {"status": "ok"}
        if method == "stat":
            with store.lock:
                return {
                    "status": "ok",
                    "rv": store.rv,
                    "event_count": len(store.kube.events),
                }
        if method == "put":
            return self._put(header)
        if method == "delete":
            return self._delete(header)
        if method == "bind_pod":
            # store.lock held across fence AND mutate (as in _put): a
            # fence that releases the lock before the mutation is a
            # TOCTOU hole for the stale write it exists to stop
            with store.lock:
                conflict = self._fence(
                    "Pod", header["key"], header.get("base_rv")
                )
                if conflict is not None:
                    return conflict
                events = store.mutate(
                    lambda: store.kube.bind_pod(
                        header["key"], header["node_name"]
                    ),
                    origin=header.get("identity", ""),
                )
            return {"status": "ok", "events": events}
        if method == "evict_pod":
            with store.lock:
                conflict = self._fence(
                    "Pod", header["key"], header.get("base_rv")
                )
                if conflict is not None:
                    return conflict
                events = store.mutate(
                    lambda: store.kube.evict_pod(header["key"]),
                    origin=header.get("identity", ""),
                )
            return {"status": "ok", "events": events}
        if method == "record_event":
            return self._record_event(header)
        if method == "lease_acquire":
            return self._lease_acquire(header)
        if method == "lease_renew":
            return self._lease_renew(header)
        if method == "lease_release":
            return self._lease_release(header)
        return {"status": "error", "error": f"unknown method {method}"}

    def _put(self, header: dict) -> dict:
        store = self.store
        kind = header["kind"]
        spec = STORE_KINDS.get(kind)
        if spec is None or kind == "Lease":
            return {"status": "error", "error": f"unwritable kind {kind}"}
        cls, attr, key_fn = spec
        obj = from_wire(header["obj"])
        if not isinstance(obj, cls):
            return {"status": "error", "error": f"object is not a {kind}"}
        key = key_fn(obj)
        with store.lock:
            conflict = self._fence(kind, key, header.get("base_rv"))
            if conflict is not None:
                return conflict
            verb = {
                "Pod": store.kube.put_pod,
                "Node": store.kube.put_node,
                "NodeClaim": store.kube.put_node_claim,
                "NodePool": store.kube.put_node_pool,
                "NodeClass": store.kube.put_node_class,
                "PodDisruptionBudget": store.kube.put_pdb,
                "StorageClass": store.kube.put_storage_class,
                "PersistentVolumeClaim": store.kube.put_pvc,
            }[kind]
            events = store.mutate(
                lambda: verb(obj), origin=header.get("identity", "")
            )
            return {"status": "ok", "events": events}

    def _fence(self, kind: str, key: str, base_rv) -> Optional[dict]:
        """Optimistic-concurrency check shared by delete/bind/evict: a
        deposed leader's straggler verb (stale base_rv) gets ``conflict``
        with the current object instead of clobbering the new leader's
        state — the same fencing ``put`` applies."""
        store = self.store
        with store.lock:
            cur = store.rvs.get((kind, key), 0)
            if base_rv is None or base_rv == cur:
                return None
            _cls, attr, _key_fn = STORE_KINDS[kind]
            existing = getattr(store.kube, attr).get(key)
            return {
                "status": "conflict",
                "rv": cur,
                "obj": to_wire(existing) if existing is not None else None,
            }

    def _delete(self, header: dict) -> dict:
        store = self.store
        kind, key = header["kind"], header["key"]
        spec = STORE_KINDS.get(kind)
        if spec is None or kind == "Lease":
            return {"status": "error", "error": f"undeletable kind {kind}"}
        _cls, attr, _key_fn = spec
        kube = store.kube

        def apply() -> None:
            if kind == "Pod":
                kube.delete_pod(key)
            elif kind == "Node":
                kube.delete_node(key)
            elif kind == "NodeClaim":
                kube.delete_node_claim(key)
            else:
                obj = getattr(kube, attr).pop(key, None)
                if obj is not None:
                    kube._notify(kind, "delete", obj)

        with store.lock:  # fence + mutate atomically (see bind_pod)
            conflict = self._fence(kind, key, header.get("base_rv"))
            if conflict is not None:
                return conflict
            events = store.mutate(apply, origin=header.get("identity", ""))
        return {"status": "ok", "events": events}

    def _record_event(self, header: dict) -> dict:
        store = self.store
        with store.lock:
            store.kube.record_event(
                header["kind"],
                header["reason"],
                header["obj_name"],
                header.get("message", ""),
            )
            store.event_rv += 1
            ev = {
                "event_rv": store.event_rv,
                "event": to_wire(tuple(store.kube.events[-1])),
            }
            for sub in store._subscribers:
                if sub.identity != header.get("identity", ""):
                    sub.q.put([{"kind": "Event", "verb": "append", **ev}])
            return {"status": "ok", **ev}

    # --------------------------------------------------------------- leases
    def _lease_acquire(self, header: dict) -> dict:
        store = self.store
        name = header["name"]
        with store.lock:
            acquired = None

            def apply() -> None:
                nonlocal acquired
                acquired = store.kube.try_acquire_lease(
                    name,
                    header["holder"],
                    header["now"],
                    header["duration_s"],
                )
                if acquired:
                    # every successful acquire-or-renew advances the CAS
                    # sequence so a competing renewer's base_rv goes stale
                    store.lease_seq[name] = store.lease_seq.get(name, 0) + 1

            events = store.mutate(apply, origin=header.get("identity", ""))
            lease = store.kube.leases.get(name)
            return {
                "status": "ok",
                "acquired": bool(acquired),
                "rv": store.lease_seq.get(name, 0),
                # rv of THIS call's broadcast Lease event (fresh acquire
                # only; silent renewals broadcast nothing) — the
                # originator credits exactly this toward synced_rv
                "lease_event_rv": max((e["rv"] for e in events), default=0),
                "lease": to_wire(lease) if lease is not None else None,
            }

    def _lease_renew(self, header: dict) -> dict:
        store = self.store
        name = header["name"]
        with store.lock:
            cur = store.lease_seq.get(name, 0)
            base_rv = header.get("base_rv")
            if base_rv is not None and base_rv != cur:
                # someone else mutated the lease since this renewer last
                # saw it — the renewal loses cleanly (optimistic CAS)
                return {
                    "status": "ok",
                    "renewed": False,
                    "conflict": True,
                    "rv": cur,
                }
            renewed = store.kube.renew_lease(
                name, header["holder"], header["now"]
            )
            if renewed:
                store.lease_seq[name] = cur + 1
            return {
                "status": "ok",
                "renewed": renewed,
                "rv": store.lease_seq.get(name, 0),
            }

    def _lease_release(self, header: dict) -> dict:
        store = self.store
        name = header["name"]
        with store.lock:
            lease = store.kube.leases.get(name)
            held = lease is not None and lease.holder == header["holder"]
            events = store.mutate(
                lambda: store.kube.release_lease(name, header["holder"]),
                origin=header.get("identity", ""),
            )
            if held:
                # only a release that actually freed the lease advances
                # the CAS sequence: a retried/stale release from a
                # non-holder is a no-op, and bumping the seq for it would
                # stale-out the REAL holder's next renewal base_rv
                store.lease_seq[name] = store.lease_seq.get(name, 0) + 1
            return {
                "status": "ok",
                "rv": store.lease_seq.get(name, 0),
                "lease_event_rv": max((e["rv"] for e in events), default=0),
            }

    # ---------------------------------------------------------------- watch
    def serve_watch(self, sock, header: dict) -> None:
        identity = header.get("identity", "")
        ctx = header.get("ctx") or {}
        # span only the snapshot phase (subscribe + full-state frame) —
        # the expensive, attributable part; the push loop below lives as
        # long as the connection and would make a meaningless span
        with trace_context(ctx.get("trace_id", "")), self.tracer.span(
            "store.watch", identity=identity
        ):
            snap, sub = self.store.subscribe(identity)
        try:
            send_frame(sock, encode({"status": "ok", "snapshot": snap}, {}))
            while True:
                events = sub.q.get()
                if events is None:  # shutdown sentinel
                    return
                send_frame(sock, encode({"type": "events", "events": events}, {}))
        except (ConnectionError, OSError):
            return
        finally:
            self.store.unsubscribe(sub)

    # ------------------------------------------------------------ lifecycle
    @property
    def address(self) -> Tuple[str, int]:
        return self.server_address  # type: ignore[return-value]

    def start_background(self) -> "StoreServer":
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="store-server"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        with self.store.lock:
            for sub in self.store._subscribers:
                sub.q.put(None)
        self.shutdown()
        self.server_close()


def main(argv=None) -> int:
    """``python -m karpenter_tpu store-server`` (also reachable as
    ``python -m karpenter_tpu.service.store_server``)."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m karpenter_tpu store-server",
        description="karpenter-tpu shared cluster-store server",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8082)
    parser.add_argument(
        "--telemetry-port",
        type=int,
        default=8083,
        help="HTTP port for /metrics, /healthz, /events and /trace on "
        "THIS process (0 disables) — the store server's request "
        "counters and its span log, which records every RPC under the "
        "calling replica's trace ID",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    server = StoreServer(args.host, args.port)
    telemetry = None
    if args.telemetry_port:
        from karpenter_tpu.obs.http import start_telemetry

        telemetry = start_telemetry(
            args.telemetry_port,
            server.registry,
            tracer=server.tracer,
            ledger=server.ledger,
        )
        log.info("telemetry on :%d/metrics", args.telemetry_port)
    log.info("cluster store listening on %s:%d", *server.address)
    try:
        server.serve_forever()
    except KeyboardInterrupt:  # pragma: no cover - CLI path
        pass
    finally:
        if telemetry is not None:
            telemetry.shutdown()
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
