"""Multi-tenant solver service: one accelerator mesh serving a fleet.

The reference runs one leader-elected controller process (SURVEY.md §5 —
no distributed backend).  The TPU build splits at the natural boundary:
the controller half (pure Python: providers, reconcilers, constraint
compilation) can live anywhere; the solver half owns the JAX devices and
serves `pack` over a length-prefixed socket protocol (service/codec.py).

Nothing forces one solver process per cluster — the expensive half is
behind a plugin boundary, so ONE SolverService can serve a fleet of
operators (docs/designs/solver-service.md).  Four planes make that safe:

- **per-tenant resident state**: each tenant's solve tensors stay
  device-resident between its solves (ops/resident.TenantResidentPool),
  content-fingerprinted so a re-sent identical array uploads nothing,
  under a global device-bytes budget with cross-tenant LRU eviction;
- **cross-tenant batching**: solves arriving while the device is busy
  (or within the CoalesceWindow) stack into ONE vmapped fleet dispatch
  (ops/packer.fleet_pack_kernel) with per-tenant decode fan-out; a lone
  RPC hitting an idle group falls through to the solo kernel immediately
  and never waits out the window;
- **admission and fairness**: per-tenant in-flight caps and a
  weighted-round-robin drain (batcher/core.WeightedRoundRobin) bound a
  noisy tenant's share; a saturated queue refuses EXPLICITLY with a
  retry-after hint — never silent queuing;
- **tenant-scoped observability**: every karpenter_service_* family
  carries a ``tenant`` label (lint rule 12 enforces it), the ledger
  records tenant-attributed batch/refusal/eviction events, the flight
  recorder snapshots per-dispatch ticks, and ``/debug/tenants`` on the
  telemetry port serves the per-tenant admission/resident state.

Methods:
- ``ping``                      liveness
- ``info``                      device inventory (platform, device count)
- ``pack``  arrays + {k_slots, objective, tenant?, ctx?} -> PackResult
            arrays, or {status: "retry", retry_after_s} under
            backpressure

Legacy posture: ``multi_tenant=False`` (the default, and the chart's
default) serves exactly the single-operator sidecar contract — no
batching, no admission, no resident pool.
"""

from __future__ import annotations

import logging
import socket
import socketserver
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.analysis.sanitizer import make_condition, make_lock
from karpenter_tpu.metrics.registry import Registry
from karpenter_tpu.obs.context import current_trace_id, trace_context
from karpenter_tpu.obs.events import EventLedger
from karpenter_tpu.obs.flight import FlightRecorder
from karpenter_tpu.service.codec import decode, encode, recv_frame, send_frame
from karpenter_tpu.utils.trace import Tracer

log = logging.getLogger(__name__)

PACK_ARG_ORDER = (
    "req", "cnt", "maxper", "slot", "feas", "alloc", "price", "openable",
    "used0", "cfg0", "npods0", "next0", "sig0",
)
PACK_RESULT_FIELDS = ("take", "leftover", "node_cfg", "node_pods", "node_used")
_NEXT0_IDX = PACK_ARG_ORDER.index("next0")

DEFAULT_TENANT = "default"
# fleet-kernel rows per dispatch: the batch axis is padded to a power-of-
# two bucket, so 16 keeps the compile-variant count at five (1,2,4,8,16)
MAX_BATCH = 16
# total queued solves (across every tenant and group) before admission
# refuses outright — the mesh is saturated and honest backpressure beats
# unbounded queueing (reference: never let a queue hide an outage)
SATURATION_QUEUED = 64


def _b_bucket(n: int) -> int:
    """Batch-axis bucket: next power of two (1, 2, 4, 8, 16)."""
    return 1 << max(n - 1, 0).bit_length()


class _Pending:
    """One queued solve awaiting a fleet dispatch."""

    __slots__ = ("tenant", "args", "k_slots", "objective", "future")

    def __init__(self, tenant, args, k_slots, objective):
        self.tenant = tenant
        self.args = args
        self.k_slots = k_slots
        self.objective = objective
        self.future: Future = Future()


class _SolveGroup:
    """Solves that can stack into one fleet dispatch: same padded bucket
    shapes, same (k_slots, objective) statics."""

    __slots__ = ("key", "queues", "window", "busy", "worker", "waited")

    def __init__(self, key, idle_s: float, max_s: float):
        from karpenter_tpu.batcher.core import CoalesceWindow

        self.key = key
        self.queues: Dict[str, deque] = {}
        self.window = CoalesceWindow(idle_s, max_s)
        self.busy = False  # a solo or fleet dispatch is on the device
        self.worker: Optional[threading.Thread] = None
        # True when a queued item arrived while the device was busy: the
        # window exists to coalesce DURING a dispatch, so once the device
        # frees, waiting any longer is pure added latency
        self.waited = False

    def depth(self) -> int:
        return sum(len(q) for q in self.queues.values())


class _TenantStats:
    __slots__ = ("name", "inflight", "solves", "batched", "refused",
                 "last_ts")

    def __init__(self, name: str):
        self.name = name
        self.inflight = 0
        self.solves = 0
        self.batched = 0
        self.refused = 0
        self.last_ts = 0.0


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: "SolverServer" = self.server  # type: ignore[assignment]
        # tracked so stop() can sever this connection: daemon handler
        # threads otherwise outlive shutdown() and keep answering with
        # pre-stop state (the zombie-handler bug the store fixed first)
        server.track_conn(self.request)
        try:
            self._serve(server)
        finally:
            server.untrack_conn(self.request)

    def _serve(self, server: "SolverServer") -> None:
        while True:
            try:
                payload = recv_frame(self.request)
            except (ConnectionError, OSError):
                return
            except ValueError as exc:  # garbage/oversized frame: close clean
                log.warning("dropping malformed frame: %s", exc)
                return
            try:
                response = server.dispatch(payload)
            except Exception as exc:  # report, keep serving
                log.exception("solver request failed")
                response = encode({"status": "error", "error": str(exc)}, {})
            try:
                send_frame(self.request, response)
            except (ConnectionError, OSError):
                return


class SolverServer(socketserver.ThreadingTCPServer):
    """Serve solves on (host, port); port 0 picks a free port.

    ``multi_tenant=True`` turns on the fleet posture: per-tenant resident
    pooling, cross-tenant batching, admission caps, WRR fairness and
    backpressure.  Off (the default), every knob is inert and the wire
    contract is exactly the legacy single-operator sidecar's.
    """

    daemon_threads = True
    allow_reuse_address = True

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        multi_tenant: bool = False,
        batch_idle_s: float = 0.005,
        batch_max_s: float = 0.05,
        inflight_cap: int = 4,
        resident_budget_mb: int = 256,
    ):
        super().__init__((host, port), _Handler)
        self._thread: Optional[threading.Thread] = None
        self.multi_tenant = bool(multi_tenant)
        self.batch_idle_s = float(batch_idle_s)
        self.batch_max_s = float(batch_max_s)
        self.inflight_cap = int(inflight_cap)
        # float MB so a sub-MB budget (tiny meshes, tests) stays exact
        self.resident_budget_mb = float(resident_budget_mb)
        # the serving process's OWN observability surface (the store
        # server's posture): request counters + handling spans recorded
        # under each client's trace ID, a tenant-attributed event ledger,
        # and a flight ring snapshotting hist deltas per dispatch
        self.registry = Registry()
        self.tracer = Tracer(enabled=True)
        self.ledger = EventLedger(registry=self.registry)
        self.registry.ledger = self.ledger
        self.flight = FlightRecorder(
            self.ledger.clock, self.registry, ledger=self.ledger,
            tracer=self.tracer,
        )
        self._flight_seq = 0
        # established handler connections, severed by stop()
        self._conns: set = set()
        self._conns_lock = make_lock("SolverServer._conns_lock")
        # admission plane: ONE condition guards tenants/groups/queues
        self._cv = make_condition("SolverServer._cv")
        self._tenants: Dict[str, _TenantStats] = {}
        self._groups: Dict[tuple, _SolveGroup] = {}
        from karpenter_tpu.batcher.core import WeightedRoundRobin

        self._wrr = WeightedRoundRobin()
        self.tenant_weights: Dict[str, float] = {}
        # per-tenant device-resident arrays, budgeted (ops/resident.py);
        # its own lock — never held together with _cv
        from karpenter_tpu.ops.resident import TenantResidentPool

        self._pool = TenantResidentPool(
            self.resident_budget_mb * (1 << 20) if multi_tenant else 0
        )
        self._pool_lock = make_lock("SolverServer._pool_lock")

    @classmethod
    def from_settings(
        cls, settings, host: str = "127.0.0.1", port: int = 7421
    ) -> "SolverServer":
        """Build from the chart-rendered Settings (api/settings.py):
        the service.multiTenant.* values land here via the configmap."""
        return cls(
            host=host,
            port=port,
            multi_tenant=settings.service_multi_tenant,
            batch_idle_s=settings.service_batch_idle_s,
            batch_max_s=settings.service_batch_max_s,
            inflight_cap=settings.service_tenant_inflight_cap,
            resident_budget_mb=settings.service_resident_budget_mb,
        )

    # ------------------------------------------------------------- dispatch
    def dispatch(self, payload: bytes) -> bytes:
        header, arrays = decode(payload)
        method = str(header.get("method"))
        tenant = str(header.get("tenant") or DEFAULT_TENANT)
        # adopt the CLIENT's trace context for the handling span: the
        # server's span log records this RPC under the caller's tick
        # trace ID, stitching the two processes' timelines (the store
        # server has done this since the telemetry split; the solver's
        # spans used to record under their own IDs, breaking
        # cross-process tick timelines)
        ctx = header.get("ctx") or {}
        t0 = time.perf_counter()
        self.registry.inc(
            "karpenter_service_requests_total",
            {"tenant": tenant, "method": method},
        )
        with trace_context(ctx.get("trace_id", "")), \
                self.tracer.span(f"solver.{method}", tenant=tenant):
            if method == "ping":
                return encode({"status": "ok"}, {})
            if method == "info":
                import jax

                devices = jax.devices()
                return encode(
                    {
                        "status": "ok",
                        "platform": devices[0].platform if devices else "none",
                        "device_count": len(devices),
                        "multi_tenant": self.multi_tenant,
                    },
                    {},
                )
            if method == "pack":
                response = self._pack(tenant, header, arrays)
                # arrival-to-answer latency, queue wait included — the
                # doctor's tenant-starvation rule reads this family's
                # per-tenant flight deltas
                self.registry.observe(
                    "karpenter_service_solve_wait_seconds",
                    time.perf_counter() - t0,
                    {"tenant": tenant},
                )
                return response
            return encode(
                {"status": "error", "error": f"unknown method {method}"}, {}
            )

    def _pack(self, tenant: str, header: dict, arrays: dict) -> bytes:
        missing = [n for n in PACK_ARG_ORDER if n not in arrays]
        if missing:
            return encode(
                {"status": "error", "error": f"missing arrays: {missing}"}, {}
            )
        k_slots = int(header["k_slots"])
        objective = str(header.get("objective", "nodes"))
        args = [arrays[n] for n in PACK_ARG_ORDER]
        # next0 travels as a 0-d array; the kernel wants a scalar
        args[_NEXT0_IDX] = np.int32(args[_NEXT0_IDX])
        if not self.multi_tenant:
            take, leftover, node_cfg, node_used = self._solve_plain(
                args, k_slots, objective
            )
            path = "solo"
        else:
            take, leftover, node_cfg, node_used, path = self._admit_and_solve(
                tenant, args, k_slots, objective
            )
            if path == "retry":
                # the refusal rode back through _admit_and_solve's tuple
                return take  # type: ignore[return-value]
        self.registry.inc(
            "karpenter_service_solves_total",
            {"tenant": tenant, "path": path},
        )
        # node_pods reconstructs exactly from the inputs: npods0 + takes
        node_pods = np.asarray(arrays["npods0"], dtype=np.int32) + take.sum(
            axis=0, dtype=np.int32
        )
        out = (take, leftover, node_cfg, node_pods, node_used)
        return encode(
            {"status": "ok"},
            {name: val for name, val in zip(PACK_RESULT_FIELDS, out)},
        )

    # ----------------------------------------------------- admission plane
    def _refuse(self, tenant: str, reason: str, retry_after_s: float) -> bytes:
        """Explicit backpressure: the caller gets a machine-readable
        retry-after hint, never a silent queue slot."""
        self.registry.inc(
            "karpenter_service_refusals_total",
            {"tenant": tenant, "reason": reason},
        )
        self.ledger.emit(
            "TenantRefused", tenant=tenant, reason=reason,
            retry_after_s=f"{retry_after_s:.3f}",
        )
        return encode(
            {
                "status": "retry",
                "retry_after_s": retry_after_s,
                "reason": reason,
            },
            {},
        )

    def _admit_and_solve(self, tenant, args, k_slots, objective):
        key = (k_slots, objective) + tuple(
            (tuple(np.shape(a)), np.asarray(a).dtype.str)
            for i, a in enumerate(args)
            if i != _NEXT0_IDX
        )
        pend = None
        refusal = None  # (reason, retry_after_s), encoded OUTSIDE _cv
        with self._cv:
            ts = self._tenants.get(tenant)
            if ts is None:
                ts = self._tenants[tenant] = _TenantStats(tenant)
            ts.last_ts = self.ledger.clock.now()
            if ts.inflight >= self.inflight_cap:
                ts.refused += 1
                refusal = ("inflight-cap", self.batch_idle_s)
            elif (
                sum(g.depth() for g in self._groups.values())
                >= SATURATION_QUEUED
            ):
                ts.refused += 1
                refusal = ("saturated", self.batch_max_s)
            else:
                # admission and inflight++ are ONE atomic decision: a
                # split would let two at-cap requests both slip in
                ts.inflight += 1
                self.registry.set(
                    "karpenter_service_inflight", ts.inflight,
                    {"tenant": tenant},
                )
                grp = self._groups.get(key)
                if grp is None:
                    grp = self._groups[key] = _SolveGroup(
                        key, self.batch_idle_s, self.batch_max_s
                    )
                # single-tenant fall-through: an idle group's lone RPC
                # takes the solo kernel NOW, never waiting out the window
                solo = not grp.busy and grp.depth() == 0
                if solo:
                    grp.busy = True
                else:
                    pend = _Pending(tenant, args, k_slots, objective)
                    grp.queues.setdefault(tenant, deque()).append(pend)
                    grp.window.observe(time.monotonic())
                    if grp.busy:
                        grp.waited = True
                    if grp.worker is None:
                        grp.worker = threading.Thread(
                            target=self._group_worker, args=(grp,),
                            daemon=True, name="solver-batch",
                        )
                        grp.worker.start()
                    self._cv.notify_all()
        if refusal is not None:
            return (
                self._refuse(tenant, *refusal), None, None, None, "retry",
            )
        try:
            if solo:
                try:
                    take, leftover, node_cfg, node_used = self._solve_pooled(
                        tenant, args, k_slots, objective
                    )
                    path = "solo"
                finally:
                    with self._cv:
                        grp.busy = False
                        self._cv.notify_all()
            else:
                take, leftover, node_cfg, node_used = pend.future.result()
                path = "batched"
                with self._cv:
                    ts.batched += 1
        finally:
            with self._cv:
                ts.inflight -= 1
                ts.solves += 1
                self.registry.set(
                    "karpenter_service_inflight", ts.inflight,
                    {"tenant": tenant},
                )
        return take, leftover, node_cfg, node_used, path

    # ------------------------------------------------------- solve backends
    def _solve_plain(self, args, k_slots, objective):
        """The legacy single-tenant path: numpy args straight into the
        solo kernel, no pooling, no queueing — byte-for-byte the original
        sidecar behavior."""
        from karpenter_tpu.obs.device import OBSERVATORY
        from karpenter_tpu.ops.packer import fetch_bundled, pack_kernel

        t0 = time.perf_counter()
        # the sidecar owns the devices, so ITS process observatory is
        # where this dispatch's compile/transfer accounting belongs —
        # the wire arrays are numpy, so the seam counts the real upload
        result = OBSERVATORY.dispatch(
            "pack_kernel", pack_kernel, *args,
            k_slots=k_slots, objective=objective,
        )
        out = fetch_bundled(result)
        self._flight_tick(time.perf_counter() - t0, {"path": "solo"})
        return out

    def _pooled_args(self, tenant: str, args) -> list:
        """Swap each wire array for the tenant's device-resident copy
        (content-fingerprint hit: zero transfer; miss: one counted
        upload).  next0 stays a host scalar — uploading a 0-d array
        would cost a round trip to save four bytes."""
        with self._pool_lock:
            dev = []
            for name, a in zip(PACK_ARG_ORDER, args):
                if name == "next0":
                    dev.append(np.int32(a))
                else:
                    dev.append(self._pool.get(tenant, name, np.asarray(a)))
            evicted = list(self._pool.evictions)
            self._pool.evictions.clear()
            tenant_bytes = self._pool.bytes_of(tenant)
            self._pool.report_footprint()
        for victim in evicted:
            self.registry.inc(
                "karpenter_service_resident_evictions_total",
                {"tenant": victim},
            )
            self.registry.set(
                "karpenter_service_resident_bytes", 0, {"tenant": victim}
            )
            self.ledger.emit("TenantEvicted", tenant=victim)
            with self._cv:
                self._wrr.forget(victim)
        self.registry.set(
            "karpenter_service_resident_bytes", tenant_bytes,
            {"tenant": tenant},
        )
        return dev

    def _solve_pooled(self, tenant, args, k_slots, objective):
        """Solo kernel over the tenant's resident arrays."""
        from karpenter_tpu.obs.device import OBSERVATORY
        from karpenter_tpu.ops.packer import fetch_bundled, pack_kernel

        t0 = time.perf_counter()
        dev = self._pooled_args(tenant, args)
        result = OBSERVATORY.dispatch(
            "pack_kernel", pack_kernel, *dev,
            k_slots=k_slots, objective=objective,
        )
        out = fetch_bundled(result)
        self._flight_tick(time.perf_counter() - t0, {"path": "solo"})
        return out

    def _group_worker(self, grp: _SolveGroup) -> None:
        """Drain one group: wait for the device to free and the window to
        close, WRR-pick up to MAX_BATCH queued solves, run ONE fleet
        dispatch, fan the rows out."""
        while True:
            with self._cv:
                while True:
                    if grp.depth() == 0:
                        grp.worker = None
                        if not grp.busy and self._groups.get(grp.key) is grp:
                            del self._groups[grp.key]
                        return
                    now = time.monotonic()
                    if not grp.busy and (
                        grp.waited or grp.window.ready(now)
                    ):
                        break
                    timeout = 0.05
                    if not grp.busy and grp.window.open:
                        timeout = max(grp.window.deadline() - now, 0.0)
                    self._cv.wait(timeout=timeout)
                weights = {
                    t: self.tenant_weights.get(t, 1.0) for t in grp.queues
                }
                batch = self._wrr.drain(grp.queues, MAX_BATCH, weights)
                grp.queues = {t: q for t, q in grp.queues.items() if q}
                grp.busy = True
                # leftovers already waited a full dispatch: drain them
                # the moment the device frees again
                grp.waited = grp.depth() > 0
                grp.window.reset()
                if grp.depth() > 0:
                    grp.window.observe(time.monotonic())
            try:
                self._run_batch([p for _, p in batch])
            finally:
                with self._cv:
                    grp.busy = False
                    self._cv.notify_all()

    def _run_batch(self, batch: List[_Pending]) -> None:
        """ONE vmapped device dispatch for the whole batch; per-tenant
        rows fan back out to the waiting handler threads."""
        from karpenter_tpu.obs.device import OBSERVATORY
        from karpenter_tpu.ops.packer import fleet_pack_kernel, fleet_unbundle

        t0 = time.perf_counter()
        try:
            p0 = batch[0]
            rows_args = [
                self._pooled_args(p.tenant, p.args) for p in batch
            ]
            # pad the batch axis to its bucket by repeating row 0: no
            # fake-problem NaN hazards, no extra upload (same device
            # arrays), and XLA compiles once per (B bucket, shape bucket)
            while len(rows_args) < _b_bucket(len(batch)):
                rows_args.append(rows_args[0])
            cols = tuple(
                tuple(r[i] for r in rows_args)
                for i in range(len(PACK_ARG_ORDER))
            )
            buf = OBSERVATORY.dispatch(
                "fleet_pack_kernel", fleet_pack_kernel, cols,
                k_slots=p0.k_slots, objective=p0.objective,
            )
            Gp, R = np.shape(p0.args[0])
            rows = fleet_unbundle(np.asarray(buf), Gp, p0.k_slots, R)
            for p, row in zip(batch, rows):
                p.future.set_result(row)
            self.ledger.emit(
                "TenantBatch",
                size=len(batch),
                tenants=",".join(sorted({p.tenant for p in batch})),
                k_slots=p0.k_slots,
            )
            self._flight_tick(
                time.perf_counter() - t0,
                {"path": "batched", "size": len(batch)},
            )
        except Exception as exc:  # fan the failure out to every waiter
            for p in batch:
                if not p.future.done():
                    p.future.set_exception(exc)

    def _flight_tick(self, duration_s: float, summary: dict) -> None:
        from karpenter_tpu.obs.device import OBSERVATORY

        with self._cv:
            self._flight_seq += 1
            seq = self._flight_seq
        self.flight.record(
            seq, current_trace_id(), duration_s, summary=summary,
            device=OBSERVATORY.snapshot(),
        )

    # ----------------------------------------------------- debug surfaces
    def tenants_payload(self) -> dict:
        """The /debug/tenants JSON body: per-tenant admission state,
        resident footprint, and that tenant's slice of the recent event
        ledger — "who is this mesh serving and who is it throttling"."""
        with self._cv:
            tenants = {
                t.name: {
                    "inflight": t.inflight,
                    "solves": t.solves,
                    "batched": t.batched,
                    "refused": t.refused,
                    "last_ts": t.last_ts,
                    "weight": self.tenant_weights.get(t.name, 1.0),
                }
                for t in self._tenants.values()
            }
            groups = [
                {
                    "k_slots": g.key[0],
                    "objective": g.key[1],
                    "queued": g.depth(),
                    "busy": g.busy,
                }
                for g in self._groups.values()
            ]
        with self._pool_lock:
            resident = self._pool.footprint()
            budget = self._pool.budget_bytes
        for name, nbytes in resident.items():
            tenants.setdefault(name, {})["resident_bytes"] = nbytes
        events: Dict[str, list] = {}
        for ev in self.ledger.recent(500):
            t = ev.attrs.get("tenant")
            if t:
                events.setdefault(t, []).append(ev.to_dict())
        for name, evs in events.items():
            tenants.setdefault(name, {})["events"] = evs[-20:]
        return {
            "multi_tenant": self.multi_tenant,
            "inflight_cap": self.inflight_cap,
            "resident_budget_bytes": budget,
            "tenants": tenants,
            "groups": groups,
        }

    # ------------------------------------------------------------ lifecycle
    @property
    def address(self) -> Tuple[str, int]:
        return self.server_address  # type: ignore[return-value]

    def start_background(self) -> "SolverServer":
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="solver-server"
        )
        self._thread.start()
        return self

    def track_conn(self, sock) -> None:
        with self._conns_lock:
            self._conns.add(sock)

    def untrack_conn(self, sock) -> None:
        with self._conns_lock:
            self._conns.discard(sock)

    def stop(self) -> None:
        # sever established handler connections FIRST: shutdown() only
        # stops the accept loop, and the per-connection daemon threads
        # would otherwise keep answering with pre-stop state (the
        # zombie-handler class the store server fixed)
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self.shutdown()
        self.server_close()


# the subsystem name (docs/designs/solver-service.md); the class kept its
# original name for the wire-era importers
SolverService = SolverServer


def main(argv=None) -> int:  # pragma: no cover - CLI entry
    import argparse

    parser = argparse.ArgumentParser(description="karpenter-tpu solver sidecar")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=7421)
    parser.add_argument(
        "--telemetry-port",
        type=int,
        default=0,
        help="HTTP port for /metrics, /healthz, /events, /trace, "
        "/debug/flight, /debug/device and /debug/tenants on THIS "
        "process (0 disables)",
    )
    parser.add_argument(
        "--settings-file",
        default="",
        help="chart-rendered settings.json (api/settings.py); the "
        "service.multiTenant.* values arrive here",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.settings_file:
        from karpenter_tpu.api.settings import Settings

        settings = Settings.from_file(args.settings_file)
        server = SolverServer.from_settings(
            settings, host=args.host, port=args.port
        )
    else:
        server = SolverServer(args.host, args.port)
    if args.telemetry_port:
        from karpenter_tpu.obs.device import OBSERVATORY
        from karpenter_tpu.obs.http import start_telemetry

        start_telemetry(
            args.telemetry_port,
            server.registry,
            tracer=server.tracer,
            ledger=server.ledger,
            flight=server.flight,
            device=OBSERVATORY,
            tenants=server.tenants_payload,
        )
        log.info("telemetry on :%d", args.telemetry_port)
    log.info(
        "solver sidecar listening on %s:%d (multi_tenant=%s)",
        *server.address, server.multi_tenant,
    )
    server.serve_forever()
    return 0


if __name__ == "__main__":  # pragma: no cover
    main()
