"""Solver sidecar server: owns the accelerator, serves packing solves.

The reference runs one leader-elected controller process (SURVEY.md §5 —
no distributed backend).  The TPU build splits at the natural boundary:
the controller half (pure Python: providers, reconcilers, constraint
compilation) can live anywhere; the solver half owns the JAX devices and
serves `pack` over a length-prefixed socket protocol (service/codec.py).
One sidecar serves many controllers; the kernel is stateless per solve so
requests parallelize freely across its thread pool.

Methods:
- ``ping``                      liveness
- ``info``                      device inventory (platform, device count)
- ``pack``  arrays + {k_slots, objective} -> PackResult arrays
"""

from __future__ import annotations

import logging
import socketserver
import threading
from typing import Optional, Tuple

import numpy as np

from karpenter_tpu.service.codec import decode, encode, recv_frame, send_frame

log = logging.getLogger(__name__)

PACK_ARG_ORDER = (
    "req", "cnt", "maxper", "slot", "feas", "alloc", "price", "openable",
    "used0", "cfg0", "npods0", "next0", "sig0",
)
PACK_RESULT_FIELDS = ("take", "leftover", "node_cfg", "node_pods", "node_used")
_NEXT0_IDX = PACK_ARG_ORDER.index("next0")


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        while True:
            try:
                payload = recv_frame(self.request)
            except (ConnectionError, OSError):
                return
            except ValueError as exc:  # garbage/oversized frame: close clean
                log.warning("dropping malformed frame: %s", exc)
                return
            try:
                response = self.server.dispatch(payload)  # type: ignore[attr-defined]
            except Exception as exc:  # report, keep serving
                log.exception("solver request failed")
                response = encode({"status": "error", "error": str(exc)}, {})
            try:
                send_frame(self.request, response)
            except (ConnectionError, OSError):
                return


class SolverServer(socketserver.ThreadingTCPServer):
    """Serve solves on (host, port); port 0 picks a free port."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        super().__init__((host, port), _Handler)
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- dispatch
    def dispatch(self, payload: bytes) -> bytes:
        header, arrays = decode(payload)
        method = header.get("method")
        if method == "ping":
            return encode({"status": "ok"}, {})
        if method == "info":
            import jax

            devices = jax.devices()
            return encode(
                {
                    "status": "ok",
                    "platform": devices[0].platform if devices else "none",
                    "device_count": len(devices),
                },
                {},
            )
        if method == "pack":
            return self._pack(header, arrays)
        return encode({"status": "error", "error": f"unknown method {method}"}, {})

    def _pack(self, header: dict, arrays: dict) -> bytes:
        from karpenter_tpu.obs.device import OBSERVATORY
        from karpenter_tpu.ops.packer import fetch_bundled, pack_kernel

        missing = [n for n in PACK_ARG_ORDER if n not in arrays]
        if missing:
            return encode(
                {"status": "error", "error": f"missing arrays: {missing}"}, {}
            )
        args = [arrays[n] for n in PACK_ARG_ORDER]
        # next0 travels as a 0-d array; the kernel wants a scalar
        args[_NEXT0_IDX] = np.int32(args[_NEXT0_IDX])
        # the sidecar owns the devices, so ITS process observatory is
        # where this dispatch's compile/transfer accounting belongs —
        # the wire arrays are numpy, so the seam counts the real upload
        result = OBSERVATORY.dispatch(
            "pack_kernel", pack_kernel,
            *args,
            k_slots=int(header["k_slots"]),
            objective=header.get("objective", "nodes"),
        )
        # ONE device read (the sidecar's TPU link pays a round trip per
        # fetched array, like the in-process solver's fetch); node_pods
        # reconstructs exactly from the inputs: npods0 + per-slot takes
        take, leftover, node_cfg, node_used = fetch_bundled(result)
        node_pods = np.asarray(arrays["npods0"], dtype=np.int32) + take.sum(
            axis=0, dtype=np.int32
        )
        out = (take, leftover, node_cfg, node_pods, node_used)
        return encode(
            {"status": "ok"},
            {name: val for name, val in zip(PACK_RESULT_FIELDS, out)},
        )

    # ------------------------------------------------------------ lifecycle
    @property
    def address(self) -> Tuple[str, int]:
        return self.server_address  # type: ignore[return-value]

    def start_background(self) -> "SolverServer":
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="solver-server"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.shutdown()
        self.server_close()


def main() -> None:  # pragma: no cover - CLI entry
    import argparse

    parser = argparse.ArgumentParser(description="karpenter-tpu solver sidecar")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=7421)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    server = SolverServer(args.host, args.port)
    log.info("solver sidecar listening on %s:%d", *server.address)
    server.serve_forever()


if __name__ == "__main__":  # pragma: no cover
    main()
