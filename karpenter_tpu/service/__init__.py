"""Solver sidecar: the distributed boundary between the controller half
and the accelerator half (SURVEY.md §5 north-star).  With
``multi_tenant`` on, the same process is the fleet-serving SolverService
(docs/designs/solver-service.md)."""

from karpenter_tpu.service.client import (
    RemoteSolver,
    SolverBusyError,
    SolverUnavailableError,
)
from karpenter_tpu.service.server import SolverServer, SolverService

__all__ = [
    "RemoteSolver",
    "SolverBusyError",
    "SolverServer",
    "SolverService",
    "SolverUnavailableError",
]
