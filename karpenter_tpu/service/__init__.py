"""Solver sidecar: the distributed boundary between the controller half
and the accelerator half (SURVEY.md §5 north-star)."""

from karpenter_tpu.service.client import RemoteSolver, SolverUnavailableError
from karpenter_tpu.service.server import SolverServer

__all__ = ["RemoteSolver", "SolverServer", "SolverUnavailableError"]
