"""The shared watch-CLIENT half of the store plane's watch protocol.

Two consumers speak the client side of ``watch`` (docs/designs/
store-scale.md): ``RemoteKubeStore._watch_loop`` (an operator's mirror)
and the read replica's follower (``StoreServer._follow_loop``).  Before
this module each carried its own copy of the dial / handshake / backoff
/ resync choreography — the duplication named as headroom in CHANGES
PR 12.  The choreography is subtle enough to deserve one definition:

- dial, present the handshake (codecs / schema_fp / since_seq / epoch —
  computed FRESH per attempt, because the cursor and epoch move between
  reconnects),
- adopt the ack's epoch BEFORE any payload applies (an interrupted
  handshake must never leave a new-epoch label over an old-space seq),
- handle a legacy server's inline-snapshot ack, else read the first
  sync frame under the negotiated codec,
- switch to BLOCKING reads for the steady frame loop (a short recv
  timeout could fire mid-frame and desync the stream — the consumed
  prefix is lost and the next read parses payload bytes as a length
  header; close() on the exposed live socket interrupts the recv
  instead),
- on ANY of the reconnect-worthy errors — including KeyError: a frame
  missing an expected key is a malformed or down-version peer, and must
  reconnect-and-resync, never silently kill the thread — back off
  exponentially and re-dial.

What stays with the caller: what the handshake says, how frames apply,
and the socket/byte accounting (the mirror counts wire bytes per codec;
the follower does not) — the ``tx``/``rx`` hooks carry those
differences.
"""

from __future__ import annotations

import struct
from typing import Callable, Optional

from karpenter_tpu.service.codec import CODEC_JSON, decode_payload, encode_payload

# errors that mean "reconnect and resync", never "die": transport drops,
# malformed payloads (ValueError from the codec layer), missing frame
# keys from a down-version peer (KeyError), torn length prefixes
# (struct.error)
RECONNECT_ERRORS = (ConnectionError, OSError, ValueError, KeyError, struct.error)


class WatchChannelClient:
    """One watch-protocol client loop over caller-supplied transport.

    ``run()`` blocks until ``stop`` is set; it is the body the caller's
    daemon thread executes.  Hooks:

    - ``dial()`` → connected socket (timeouts set for the handshake)
    - ``hello()`` → the watch-request dict for THIS attempt
    - ``tx(sock, payload)`` / ``rx(sock, codec)`` → framed bytes out/in
    - ``on_epoch(epoch)`` → adopt/reset cursors at DETECTION time
    - ``on_legacy_snapshot(snapshot)`` → a pre-negotiation server's
      inline-snapshot ack
    - ``on_frame(frame, initial)`` → apply one pushed frame (``initial``
      marks the handshake's first sync frame)
    - ``on_live(sock_or_none)`` → expose/clear the blocking socket so
      ``close()`` elsewhere can interrupt the recv
    - ``pace(delay_s)`` → wait out one reconnect backoff; returns True
      to stop the loop.  Production's default waits the exponential
      backoff on the stop event (wall clock); the fleet simulator
      injects a deterministic pacer so scripted disconnects reconnect
      on SIMULATED time and record/replay traces stay byte-identical.
    """

    def __init__(
        self,
        *,
        dial: Callable,
        hello: Callable[[], dict],
        tx: Callable,
        rx: Callable,
        on_epoch: Callable[[str], None],
        on_legacy_snapshot: Callable[[dict], None],
        on_frame: Callable[[dict, bool], None],
        stop,  # threading.Event
        on_live: Optional[Callable] = None,
        backoff_s: float = 0.05,
        backoff_max: float = 1.0,
        pace: Optional[Callable[[float], bool]] = None,
    ):
        self.dial = dial
        self.hello = hello
        self.tx = tx
        self.rx = rx
        self.on_epoch = on_epoch
        self.on_legacy_snapshot = on_legacy_snapshot
        self.on_frame = on_frame
        self.stop = stop
        self.on_live = on_live or (lambda _sock: None)
        self.backoff_s = backoff_s
        self.backoff_max = backoff_max
        # the reconnect-backoff seam: all waiting routes through ONE
        # injectable callable (stop.wait keeps production's wall-clock
        # exponential backoff AND stays responsive to close())
        self.pace = pace or self.stop.wait

    def run(self) -> None:
        backoff = self.backoff_s
        while not self.stop.is_set():
            sock = None
            try:
                sock = self.dial()
                self.tx(sock, encode_payload(self.hello(), CODEC_JSON))
                ack = decode_payload(self.rx(sock, CODEC_JSON), CODEC_JSON)
                self.on_epoch(str(ack.get("epoch") or ""))
                if "snapshot" in ack:  # legacy server: inline snapshot
                    codec = CODEC_JSON
                    self.on_legacy_snapshot(ack["snapshot"])
                else:
                    codec = ack.get("codec", CODEC_JSON)
                    self.on_frame(
                        decode_payload(self.rx(sock, codec), codec), True
                    )
                backoff = self.backoff_s
                sock.settimeout(None)  # blocking steady-state reads
                self.on_live(sock)
                while not self.stop.is_set():
                    self.on_frame(
                        decode_payload(self.rx(sock, codec), codec), False
                    )
            except RECONNECT_ERRORS:
                if self.pace(backoff):
                    break
                backoff = min(backoff * 2, self.backoff_max)
            finally:
                self.on_live(None)
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
