"""Solver sidecar client: `pack` over the wire, drop-in for run_pack.

`RemoteSolver.pack_problem(prob, ...)` matches `ops.packer.run_pack`'s
signature/result shape, so `TensorScheduler(pack_fn=remote.pack_problem)`
moves the device half of every solve into the sidecar without touching
the controller code.
"""

from __future__ import annotations

import socket
import threading
from typing import NamedTuple, Optional, Tuple

import numpy as np

from karpenter_tpu.obs.context import current_trace_id
from karpenter_tpu.ops.packer import pad_problem
from karpenter_tpu.ops.tensorize import CompiledProblem
from karpenter_tpu.service.codec import decode, encode, recv_frame, send_frame
from karpenter_tpu.service.server import PACK_ARG_ORDER, PACK_RESULT_FIELDS
from karpenter_tpu.analysis.sanitizer import make_lock, note_blocking


class RemotePackResult(NamedTuple):
    take: np.ndarray
    leftover: np.ndarray
    node_cfg: np.ndarray
    node_pods: np.ndarray
    node_used: np.ndarray


class SolverUnavailableError(ConnectionError):
    pass


class SolverBusyError(RuntimeError):
    """The service refused the solve under backpressure (explicit
    RETRY-AFTER, never silent queuing — docs/designs/solver-service.md).
    The caller keeps last tick's plan and retries after `retry_after_s`."""

    def __init__(self, reason: str, retry_after_s: float):
        super().__init__(
            f"solver busy ({reason}); retry after {retry_after_s:.3f}s"
        )
        self.reason = reason
        self.retry_after_s = float(retry_after_s)


class RemoteSolver:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 7421,
        connect_timeout: float = 10.0,
        request_timeout: float = 300.0,
        tenant: str = "",
    ):
        # request_timeout must cover a cold solve: the sidecar's first pack
        # at a new bucket shape jit-compiles (~20-40s on a TPU backend)
        self.host = host
        self.port = port
        self.connect_timeout = connect_timeout
        self.request_timeout = request_timeout
        # identity on a shared (multi-tenant) SolverService: names this
        # client's resident pool, admission quota and metrics slice;
        # empty means the server's "default" tenant (legacy sidecar)
        self.tenant = tenant
        self._sock: Optional[socket.socket] = None
        self._lock = make_lock("RemoteSolver._lock")

    # ------------------------------------------------------------- transport
    def _connect(self) -> socket.socket:
        if self._sock is None:
            try:
                self._sock = socket.create_connection(
                    (self.host, self.port), timeout=self.connect_timeout
                )
                self._sock.settimeout(self.request_timeout)
            except OSError as exc:
                raise SolverUnavailableError(
                    f"solver sidecar at {self.host}:{self.port}: {exc}"
                ) from exc
        return self._sock

    def _call(self, meta: dict, arrays: dict) -> Tuple[dict, dict]:
        note_blocking("_rpc")  # runtime blocking witness (sanitizer.py)
        if self.tenant:
            meta = dict(meta, tenant=self.tenant)
        # ship the caller's trace ID so the server's handling span lands
        # on this tick's cross-process timeline (store client idiom)
        trace_id = current_trace_id()
        if trace_id:
            meta = dict(meta, ctx={"trace_id": trace_id})
        with self._lock:  # one in-flight request per connection
            sock = self._connect()
            try:
                send_frame(sock, encode(meta, arrays))
                header, out = decode(recv_frame(sock))
            except (ConnectionError, OSError) as exc:
                self.close()
                raise SolverUnavailableError(str(exc)) from exc
        if header.get("status") == "retry":
            raise SolverBusyError(
                str(header.get("reason", "busy")),
                float(header.get("retry_after_s", 0.05)),
            )
        if header.get("status") != "ok":
            raise RuntimeError(f"solver error: {header.get('error')}")
        return header, out

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    # --------------------------------------------------------------- methods
    def ping(self) -> bool:
        self._call({"method": "ping"}, {})
        return True

    def info(self) -> dict:
        header, _ = self._call({"method": "info"}, {})
        return {k: v for k, v in header.items() if k != "status"}

    def pack_problem(
        self, prob: CompiledProblem, k_slots: int = 0, objective: str = "nodes"
    ) -> RemotePackResult:
        """run_pack over the wire: pad locally, solve in the sidecar."""
        args, kp = pad_problem(prob, k_slots)
        arrays = {
            name: np.asarray(val) for name, val in zip(PACK_ARG_ORDER, args)
        }
        _, out = self._call(
            {"method": "pack", "k_slots": kp, "objective": objective}, arrays
        )
        return RemotePackResult(*(out[f] for f in PACK_RESULT_FIELDS))
