"""Key-space partitioning for the sharded store plane.

One store process scales reads (PR 12's replicas) but every write still
funnels through one primary.  Sharding partitions the KEY SPACE instead:
N independent `StoreServer` primaries, each owning the keys that hash to
it, so writes scale with N while each shard keeps the whole PR 12
machinery (bin1 codec, delta resyncs, bounded fan-out, durable replay
log) unchanged.

`ShardRouter` is the one definition of ownership — client
(`RemoteKubeStore` fans writes to owners and merges the shards' watch
streams) and migration coordinator both route through it:

- keys hash with blake2b (stable across processes and runs — routing is
  part of the deterministic surface; Python's salted ``hash()`` is not),
- **Leases are pinned to shard 0**: leadership CAS must be atomic in ONE
  place; a lease that could land on different shards under different
  topologies would let two leaders each "win" on their own shard,
- cluster events route by the object name they describe, so one
  object's event ordering stays within one shard's event_rv space.

`ShardCoordinator` drives topology changes (shard add/remove) with the
epoch fence: for every shard whose ownership shrinks, export the moving
keys (grouped by new owner), import them at their new owners, then drop
them at the source — import BEFORE drop, so a crash mid-migration
duplicates keys (reconciled by the fence) rather than losing them.
Both ``shard_import`` and ``shard_drop`` rotate the shard's epoch, so
every watch cursor minted before the migration is refused coverage and
forced onto a fresh resync — a cursor can never silently claim to span
a migration (docs/designs/store-scale.md, "Migration fence").
"""

from __future__ import annotations

import hashlib
import socket
from typing import Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.metrics.registry import Registry
from karpenter_tpu.service.codec import (
    CODEC_JSON,
    decode_payload,
    encode_payload,
    recv_frame,
    send_frame,
)

# the shard that owns every Lease, under EVERY topology
LEASE_SHARD = 0


def shard_of(kind: str, key: str, n: int) -> int:
    """The owner shard index for (kind, key) under an n-shard topology.
    Module-level and pure so server, client, and coordinator provably
    share one routing function."""
    if n <= 1:
        return 0
    if kind == "Lease":
        return LEASE_SHARD
    digest = hashlib.blake2b(
        f"{kind}/{key}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big") % n


class ShardRouter:
    """Ownership under ONE topology.  Immutable — a topology change is
    a new router (clients swap routers atomically under their mirror
    lock, so no routing decision straddles two topologies)."""

    def __init__(self, n: int):
        self.n = max(1, n)

    def owner(self, kind: str, key: str) -> int:
        return shard_of(kind, key, self.n)


class ShardCoordinator:
    """Drives a reshard across live `StoreServer` shards over one-shot
    RPC sockets (tagged JSON — migration is a control-plane operation;
    the data plane's bin1 negotiation is irrelevant at this rate).

    ``reshard(old_addresses, new_addresses)`` moves every key whose
    owner changes, with per-shard begin/commit counters
    (``karpenter_store_shard_migration_begun_total`` /
    ``..._committed_total``) whose imbalance is the doctor's
    stuck-migration signal."""

    def __init__(
        self,
        registry: Optional[Registry] = None,
        connect_timeout: float = 5.0,
    ):
        self.registry = registry or Registry()
        self.connect_timeout = connect_timeout

    # ------------------------------------------------------------- transport
    def _call(self, address: Tuple[str, int], header: dict) -> dict:
        with socket.create_connection(
            address, timeout=self.connect_timeout
        ) as sock:
            sock.settimeout(self.connect_timeout)
            send_frame(sock, encode_payload(header, CODEC_JSON))
            response = decode_payload(recv_frame(sock), CODEC_JSON)
        if response.get("status") != "ok":
            raise RuntimeError(
                f"shard rpc {header.get('method')} to {address} failed: "
                f"{response.get('error')}"
            )
        return response

    # ------------------------------------------------------------- migration
    def reshard(
        self,
        old_addresses: Sequence[Tuple[str, int]],
        new_addresses: Sequence[Tuple[str, int]],
    ) -> Dict[str, int]:
        """Migrate from the old topology to the new one.  Every OLD
        shard exports the keys it no longer owns under the new hash,
        grouped by new owner; each group imports at its new owner, then
        the source drops the moved keys.  Returns migration stats."""
        new_n = len(new_addresses)
        moved = 0
        shards_migrated = 0
        for index, address in enumerate(old_addresses):
            self.registry.inc(
                "karpenter_store_shard_migration_begun_total",
                {"shard": str(index)},
            )
            export = self._call(
                address,
                {"method": "shard_export", "new_n": new_n},
            )
            entries_by_owner: Dict[str, List[dict]] = export.get(
                "entries", {}
            )
            dropped: List[List[str]] = []
            # IMPORT before DROP: a crash between the two duplicates
            # the moved keys (old owner still serves them under its old
            # epoch; the fence forces every client onto a resync that
            # re-routes), never loses them
            for owner_str, entries in sorted(entries_by_owner.items()):
                owner = int(owner_str)
                if owner == index or not entries:
                    continue
                self._call(
                    new_addresses[owner],
                    {"method": "shard_import", "entries": entries},
                )
                dropped.extend([e["kind"], e["key"]] for e in entries)
                moved += len(entries)
            if dropped:
                self._call(
                    address, {"method": "shard_drop", "keys": dropped}
                )
            self.registry.inc(
                "karpenter_store_shard_migration_committed_total",
                {"shard": str(index)},
            )
            shards_migrated += 1
        return {
            "moved_keys": moved,
            "shards_migrated": shards_migrated,
            "new_n": new_n,
        }
