"""Wire codec for the solver service: JSON header + raw array blob.

Frame layout (all integers big-endian):

    [4B total header length][JSON header][binary blob]

The JSON header carries the method/status, scalar params, and an array
manifest ``[{name, dtype, shape, offset, nbytes}]`` indexing into the
blob.  Arrays travel as raw C-order bytes — no pickling (the sidecar must
never execute peer-controlled payloads), no base64 inflation.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Dict, Tuple

import numpy as np

from karpenter_tpu.analysis.sanitizer import note_blocking
from karpenter_tpu.state.binwire import (
    BIN_VERSION,
    decode_value,
    encode_value,
)

MAX_FRAME = 1 << 30  # 1 GiB sanity bound


def encode(meta: dict, arrays: Dict[str, np.ndarray]) -> bytes:
    manifest = []
    blob_parts = []
    offset = 0
    for name, arr in arrays.items():
        arr = np.asarray(arr)
        shape = list(arr.shape)  # before ascontiguousarray (it promotes 0-d)
        raw = np.ascontiguousarray(arr).tobytes()
        manifest.append(
            {
                "name": name,
                "dtype": arr.dtype.str,
                "shape": shape,
                "offset": offset,
                "nbytes": len(raw),
            }
        )
        blob_parts.append(raw)
        offset += len(raw)
    header = dict(meta)
    header["arrays"] = manifest
    hbytes = json.dumps(header).encode()
    return struct.pack(">I", len(hbytes)) + hbytes + b"".join(blob_parts)


def decode(payload: bytes) -> Tuple[dict, Dict[str, np.ndarray]]:
    # bounds-check the length prefix BEFORE trusting it: a zero-length
    # or truncated payload (torn disk tail, half-written socket frame)
    # must surface as the one malformed-frame error type every reader
    # already handles (ValueError), never a stray struct.error from the
    # unpack or a JSONDecodeError from a short header slice
    if len(payload) < 4:
        raise ValueError(
            f"truncated frame: {len(payload)} bytes, need a 4-byte "
            "header length"
        )
    (hlen,) = struct.unpack(">I", payload[:4])
    if 4 + hlen > len(payload):
        raise ValueError(
            f"truncated frame: header declares {hlen} bytes, "
            f"{len(payload) - 4} present"
        )
    try:
        header = json.loads(payload[4 : 4 + hlen].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise ValueError(f"malformed frame header: {exc}") from exc
    blob = payload[4 + hlen :]
    arrays: Dict[str, np.ndarray] = {}
    for m in header.pop("arrays", []):
        raw = blob[m["offset"] : m["offset"] + m["nbytes"]]
        arrays[m["name"]] = np.frombuffer(raw, dtype=np.dtype(m["dtype"])).reshape(
            m["shape"]
        )
    return header, arrays


# ----------------------------------------------------- negotiated payloads
#
# The store protocol (service/store_server.py + state/remote.py) frames
# the SAME length-prefixed payloads but negotiates the payload codec at
# connect (`hello`): "json" is the tagged-JSON header format above (the
# compatibility baseline every endpoint speaks), "bin1" is the compact
# binary value codec (state/binwire.py) — magic byte + codec version +
# one encoded value, so a peer can reject an unknown version instead of
# misparsing it.  Arrays never ride store frames; the solver protocol
# keeps calling encode/decode directly.

CODEC_JSON = "json"
CODEC_BIN = "bin1"
_BIN_MAGIC = 0xB5


def encode_payload(header: dict, codec: str = CODEC_JSON) -> bytes:
    # payload-sized encode: sanctioned under VersionedStore.lock only
    # (bin snapshots reference live objects — the serve_watch contract);
    # any other lock held here is a runtime finding
    note_blocking("encode_payload")
    if codec == CODEC_BIN:
        return bytes((_BIN_MAGIC, BIN_VERSION)) + encode_value(header)
    return encode(header, {})


def decode_payload(payload: bytes, codec: str = CODEC_JSON) -> dict:
    if codec == CODEC_BIN:
        if len(payload) < 2 or payload[0] != _BIN_MAGIC:
            raise ValueError("not a bin1 payload (bad magic)")
        if payload[1] != BIN_VERSION:
            raise ValueError(f"unsupported bin1 version: {payload[1]}")
        try:
            return decode_value(payload, 2)
        except (IndexError, TypeError, struct.error) as exc:
            # a truncated/corrupt payload must surface as the one
            # malformed-frame error type callers already handle, not
            # kill a watch thread with a stray IndexError (or the
            # TypeError cls(**kw) raises when a corrupt frame elides a
            # REQUIRED dataclass field)
            raise ValueError(f"malformed bin1 payload: {exc}") from exc
    header, _ = decode(payload)
    return header


# ------------------------------------------------------------ socket I/O


def send_frame(sock: socket.socket, payload: bytes) -> None:
    # runtime blocking witness (analysis/sanitizer.py): socket frame I/O
    # under a held lock is the convoy class the static lock-blocking
    # rule fences; sanitized runs OBSERVE it here.  No-op in production.
    note_blocking("send_frame")
    sock.sendall(struct.pack(">Q", len(payload)) + payload)


def recv_frame(sock: socket.socket) -> bytes:
    note_blocking("recv_frame")
    size_raw = _recv_exact(sock, 8)
    (size,) = struct.unpack(">Q", size_raw)
    if size > MAX_FRAME:
        raise ValueError(f"frame too large: {size}")
    return _recv_exact(sock, size)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    remaining = n
    while remaining:
        chunk = sock.recv(min(remaining, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)
