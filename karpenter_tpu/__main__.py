"""Deployable controller entry point: ``python -m karpenter_tpu``.

The analogue of the reference's controller binary
(cmd/controller/main.go:33-70): resolve Settings (file > env > defaults),
build the Operator (DI root: caches, providers, CloudProvider facade,
controllers), optionally point the provisioner's solver at a remote
sidecar (service/server.py), expose the metrics dump over HTTP, and run
the reconcile loop until SIGINT/SIGTERM.

The cloud backend is pluggable; this process wires the in-repo simulation
backend (cloud/fake/backend.py) — a real deployment substitutes its cloud
by constructing the Operator with a different backend, exactly as the
reference swaps fake and AWS session clients.
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import sys

from karpenter_tpu.api import Settings
from karpenter_tpu.cloud.fake.backend import FakeCloud
from karpenter_tpu.metrics.registry import REGISTRY
from karpenter_tpu.operator import Operator
from karpenter_tpu.state.kube import KubeStore

log = logging.getLogger("karpenter_tpu")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "obs":
        # trace renderer: span dumps / recorded sim traces -> Chrome-trace
        # (Perfetto-loadable) JSON + a terminal top-N self-time table
        # (obs/render.py, docs/designs/observability.md)
        from karpenter_tpu.obs.render import main as obs_main

        return obs_main(argv[1:])
    if argv and argv[0] == "doctor":
        # diagnosis CLI: correlate a flight-recorder dump (or a live
        # /debug/flight endpoint) into phases-vs-baseline, the event
        # timeline around the breach, and rule-based suspected causes
        # (obs/doctor.py, docs/designs/observability.md)
        from karpenter_tpu.obs.doctor import main as doctor_main

        return doctor_main(argv[1:])
    if argv and argv[0] == "sim":
        # deterministic cluster simulator: drive the real Operator through
        # a declarative scenario, record/replay traces, emit an SLO report
        # (sim/cli.py, docs/designs/simulation.md)
        from karpenter_tpu.sim.cli import main as sim_main

        return sim_main(argv[1:], allow_reexec=True)
    if argv and argv[0] == "lint":
        # whole-program static analysis: the rule engine + the
        # lock-discipline / determinism-reachability / tracer-safety
        # analyzers over the package's parsed AST (analysis/,
        # docs/designs/static-analysis.md).  Exit 0 clean, 1 findings,
        # 2 internal error.
        from karpenter_tpu.analysis.cli import main as lint_main

        return lint_main(argv[1:])
    if argv and argv[0] == "store-server":
        # shared cluster-store server mode: own the one durable KubeStore
        # that --store-address controllers (and their Lease election)
        # share — the kube-apiserver analogue (service/store_server.py)
        from karpenter_tpu.service.store_server import main as store_main

        return store_main(argv[1:])
    parser = argparse.ArgumentParser(prog="python -m karpenter_tpu")
    parser.add_argument(
        "--settings-file",
        help="JSON settings file (the karpenter-global-settings configmap "
        "analogue); KARPENTER_* env vars apply when omitted",
    )
    parser.add_argument(
        "--interval", type=float, default=1.0, help="reconcile interval (s)"
    )
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=8080,
        help="HTTP port for the telemetry surface (0 disables): /metrics "
        "(Prometheus exposition), /healthz, /events (the cluster event "
        "ledger, ?since_seq=N&limit=M cursor), /trace (the span ring, "
        "renderable via `python -m karpenter_tpu obs`), /debug/flight "
        "(the flight recorder ring, diagnosable via `python -m "
        "karpenter_tpu doctor`), /debug/device (the device "
        "observatory's live compile/transfer/resident snapshot)",
    )
    parser.add_argument(
        "--events-log",
        default="",
        help="JSONL file the cluster event ledger appends to "
        "(PodNominated, NodeDisrupted{reason}, RetryBackoff, ...); the "
        "ring at /events is bounded, this sink is not",
    )
    parser.add_argument(
        "--solver-address",
        default="",
        help="host:port of a solver sidecar (service/server.py); the "
        "in-process kernel is used when omitted",
    )
    parser.add_argument(
        "--store-address",
        default="",
        help="host:port of a shared cluster-store server "
        "(`python -m karpenter_tpu store-server`); this process becomes a "
        "store CLIENT (state/remote.py) so multiple replicas share one "
        "durable state and the Lease election is real.  A comma-separated "
        "list names a SHARDED store topology (docs/designs/store-scale.md "
        "§sharding): keys partition across the listed servers in order, "
        "Leases pin to the first.  The in-process store is used when "
        "omitted — then each replica simulates an independent cluster and "
        "replicas MUST be 1",
    )
    parser.add_argument(
        "--leader-elect",
        action=argparse.BooleanOptionalAction,
        default=True,
        help="take the store-backed Lease before reconciling; non-leaders "
        "idle-watch (the chart runs two replicas on this basis). The "
        "election coordinates replicas SHARING the durable store — pass "
        "--store-address so the Lease lives in the shared store server; "
        "without it the bundled simulation backend's store is in-process, "
        "so simulator replicas are independent clusters and each leads "
        "its own",
    )
    parser.add_argument(
        "--demo-pods",
        type=int,
        default=0,
        metavar="N",
        help="seed the bundled simulation backend with a default "
        "NodeClass/NodePool and N small pending pods at boot — a "
        "self-contained demo/smoke workload so a freshly booted process "
        "actually provisions (the entrypoint e2e scrapes /debug/device "
        "on this basis); no effect on a real cloud backend deployment",
    )
    parser.add_argument(
        "--dump-settings", action="store_true",
        help="print the resolved settings and exit",
    )
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )

    if args.settings_file:
        settings = Settings.from_file(args.settings_file)
    else:
        settings = Settings.from_env()
    settings.validate()
    if args.dump_settings:
        print(json.dumps(settings.__dict__, default=str, indent=2))
        return 0

    # runtime concurrency sanitizer: enabled BEFORE any store/provider
    # construction so every seam-built lock is wrapped into the witness
    # (docs/designs/static-analysis.md §runtime sanitizer).  Production
    # default off; on, the process carries the lock-order/lockset
    # recorder, the operator arms the deadlock watchdog when
    # lock_watchdog_stall_s > 0, and shutdown leaves a witness artifact.
    sanitizer_mod = None
    if settings.enable_lock_sanitizer:
        from karpenter_tpu.analysis import sanitizer as sanitizer_mod

        sanitizer_mod.enable("operator")
        log.info("lock sanitizer enabled (witness on shutdown)")

    from karpenter_tpu.cloud.fake.backend import generate_catalog
    from karpenter_tpu.utils.clock import Clock

    import os
    import socket

    identity = f"{socket.gethostname()}-{os.getpid()}"
    cloud = FakeCloud(
        Clock(), shapes=generate_catalog()
    ).with_default_topology()
    if args.store_address:
        from karpenter_tpu.state.remote import RemoteKubeStore

        addresses = []
        for addr in args.store_address.split(","):
            host, _, port = addr.strip().partition(":")
            addresses.append((host, int(port) if port else 8082))
        # the operator's default registry: the client half of the store
        # plane (karpenter_store_rpc_seconds, byte counters, StoreResync
        # events) lands on this process's /metrics and flight recorder
        kube = RemoteKubeStore(
            addresses[0][0],
            addresses[0][1],
            identity=identity,
            codec=settings.store_codec,
            registry=REGISTRY,
            events_cap=settings.store_events_cap,
            # 2+ addresses name a sharded topology: keys partition across
            # the servers in listed order, Leases pin to the first
            shards=addresses if len(addresses) > 1 else None,
        )
        log.info(
            "shared cluster store at %s (%d shard%s)",
            args.store_address,
            len(addresses),
            "" if len(addresses) == 1 else "s",
        )
    else:
        kube = KubeStore()
    elector = None
    if args.leader_elect:
        from karpenter_tpu.utils.leader import LeaderElector

        elector = LeaderElector(kube, cloud.clock, identity=identity)
    operator = Operator(cloud, kube, settings=settings, elector=elector)

    if args.solver_address:
        from karpenter_tpu.service.client import RemoteSolver

        host, _, port = args.solver_address.partition(":")
        # default port matches service/server.py's listener
        remote = RemoteSolver(host, int(port)) if port else RemoteSolver(host)
        operator.provisioner.scheduler.pack_fn = remote.pack_problem
        log.info("solver sidecar at %s", args.solver_address)

    if args.events_log:
        operator.ledger.set_sink(args.events_log)
        log.info("event ledger sink at %s", args.events_log)

    if args.demo_pods:
        from karpenter_tpu.api import NodeClass, NodePool, Pod, Resources
        from karpenter_tpu.api.objects import SelectorTerm

        kube.put_node_class(
            NodeClass(
                name="default",
                subnet_selector_terms=[SelectorTerm.of(Name="*")],
                security_group_selector_terms=[SelectorTerm.of(Name="*")],
            )
        )
        kube.put_node_pool(NodePool(name="default", node_class_ref="default"))
        for i in range(args.demo_pods):
            kube.put_pod(
                Pod(
                    name=f"demo-{i}",
                    requests=Resources(cpu=0.25, memory=512 * 2**20),
                )
            )
        log.info("seeded demo workload: %d pending pods", args.demo_pods)

    server = None
    if args.metrics_port:
        from karpenter_tpu.obs.device import OBSERVATORY
        from karpenter_tpu.obs.http import start_telemetry

        server = start_telemetry(
            args.metrics_port,
            REGISTRY,
            tracer=operator.tracer,
            ledger=operator.ledger,
            flight=operator.flight,
            device=OBSERVATORY,
        )
        log.info("metrics on :%d/metrics", args.metrics_port)

    def _stop(_sig, _frame):
        log.info("shutting down")
        operator.stop()

    def _flight_dump(_sig, _frame):
        # only set a flag: the handler runs on the main thread, and
        # dumping takes non-reentrant locks the interrupted frame may
        # hold.  The dump lands at the end of the current/next tick,
        # in flight_dir when configured, the working directory otherwise
        operator.request_flight_dump("sigusr1")

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    if hasattr(signal, "SIGUSR1"):
        signal.signal(signal.SIGUSR1, _flight_dump)
    log.info(
        "karpenter-tpu controller running (cluster=%s, interval=%.1fs)",
        settings.cluster_name,
        args.interval,
    )
    operator.run(interval_s=args.interval)
    if elector is not None:
        # graceful handoff: free the Lease so the standby takes over
        # immediately instead of waiting out the expiry
        elector.release()
    if hasattr(kube, "close"):  # store client: stop the watch stream
        kube.close()
    if server is not None:
        server.shutdown()
    if sanitizer_mod is not None:
        import os

        san = sanitizer_mod.disable()
        witness = san.witness()
        directory = settings.flight_dir or "."
        os.makedirs(directory, exist_ok=True)
        path = witness.dump(os.path.join(directory, "witness.json"))
        log.info(
            "lock witness %s -> %s (%d finding(s), %d edge(s))",
            witness.fingerprint, path, len(witness.findings),
            len(witness.edges),
        )
    if operator.tracer.enabled:
        # pprof-style hot-path table on shutdown (settings.md:18's
        # ENABLE_PROFILING analogue); a JSON snapshot lands next to the
        # XLA timeline when profile_dir is configured
        print(operator.tracer.report())
        if settings.profile_dir:
            import os

            os.makedirs(settings.profile_dir, exist_ok=True)
            operator.tracer.dump(
                os.path.join(settings.profile_dir, "spans.json")
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
