"""Cluster event ledger: typed, ring-buffered decision records.

The reference leans on Kubernetes Events to answer "why did Karpenter do
that?" after the fact; this ledger is that surface for the reproduction,
with a stronger determinism contract: every entry is a pure function of
the injected clock and the controllers' (seeded) decisions, so the
simulator records the ledger into its JSONL trace and replays it
byte-identically (sim/runner.py, tests/test_obs.py).

Event types (emitted at the existing decision sites):

- ``PodNominated``    provisioning: a pod was steered onto a node/claim
- ``NodeLaunched``    provisioning: a NodeClaim launched successfully
- ``NodeDisrupted``   disruption/interruption: a node was marked for
                      deletion, ``reason`` carries the mechanism
                      (expired, drifted/…, emptiness, consolidation/…,
                      interruption/…)
- ``RetryBackoff``    cloud retry layer: a classified failure is being
                      retried after backoff
- ``CircuitOpen``     cloud retry layer: an API's breaker opened
- ``StaleServed``     a degraded provider served last-good data
- ``VerdictFallback`` a consolidation what-if the batched path could not
                      answer resolved through the sequential solver
- ``CatalogRolled``   a provider's catalog cache was invalidated (image
                      roll); compile storms downstream start here
- ``SLOBreach``       the SLO engine (obs/slo.py): a rule's fast AND
                      slow burn-rate windows exceeded budget
- ``SLORecovered``    the SLO engine: a breached rule's fast window
                      dropped back under budget
- ``AnomalyDetected`` streaming anomaly detection (obs/detect.py): a
                      phase-latency sample blew past its rolling robust
                      baseline, attrs carry the attribution
- ``DeviceRecompile`` device observatory (obs/device.py): a jit entry
                      point recompiled on a WARM tick (it already had
                      dispatches in an earlier tick) — a fresh padded
                      bucket, an axis change, a donation falling
                      through; attrs carry fn + compile seconds

Every event stamps the current trace ID (obs/context.py), so the ledger
joins the span timeline on the same key.  Emission also bumps
``karpenter_events_total{type}`` on the owning registry, which is how
the /metrics endpoint and the sim SLO report count the ledger without
reading it.  An optional JSONL sink mirrors events to disk for
production operators (``--events-log``).
"""

from __future__ import annotations

import json
import logging
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.obs.context import current_trace_id
from karpenter_tpu.utils.clock import Clock
from karpenter_tpu.analysis.sanitizer import make_lock

log = logging.getLogger(__name__)

POD_NOMINATED = "PodNominated"
NODE_LAUNCHED = "NodeLaunched"
NODE_DISRUPTED = "NodeDisrupted"
RETRY_BACKOFF = "RetryBackoff"
CIRCUIT_OPEN = "CircuitOpen"
STALE_SERVED = "StaleServed"
VERDICT_FALLBACK = "VerdictFallback"
CATALOG_ROLLED = "CatalogRolled"
SLO_BREACH = "SLOBreach"
SLO_RECOVERED = "SLORecovered"
ANOMALY_DETECTED = "AnomalyDetected"
DEVICE_RECOMPILE = "DeviceRecompile"

EVENT_TYPES = (
    POD_NOMINATED,
    NODE_LAUNCHED,
    NODE_DISRUPTED,
    RETRY_BACKOFF,
    CIRCUIT_OPEN,
    STALE_SERVED,
    VERDICT_FALLBACK,
    CATALOG_ROLLED,
    SLO_BREACH,
    SLO_RECOVERED,
    ANOMALY_DETECTED,
    DEVICE_RECOMPILE,
)

# bounded history: several hundred ticks of decisions on a busy cluster
RING_SIZE = 4096


@dataclass
class ObsEvent:
    seq: int  # monotonic per ledger, never reused
    ts: float  # injected-clock time (deterministic under FakeClock)
    type: str
    trace_id: str
    attrs: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "seq": self.seq,
            "ts": self.ts,
            "type": self.type,
            "trace_id": self.trace_id,
            "attrs": dict(self.attrs),
        }


class EventLedger:
    """Thread-safe ring of ObsEvents.  Cheap enough to stay on: one lock
    acquisition and a deque append per decision (decisions are orders of
    magnitude rarer than metric observations)."""

    def __init__(
        self,
        clock: Optional[Clock] = None,
        registry=None,
        capacity: int = RING_SIZE,
        sink_path: Optional[str] = None,
    ):
        self.clock = clock or Clock()
        self.registry = registry
        self._lock = make_lock("EventLedger._lock")
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0
        self._sink = open(sink_path, "a") if sink_path else None

    def set_sink(self, path: str) -> None:
        """Mirror every future event to a JSONL file (production
        operators; the simulator records through its trace instead)."""
        with self._lock:
            if self._sink is not None:
                self._sink.close()
            self._sink = open(path, "a")

    # --------------------------------------------------------------- emitting
    def emit(self, type_: str, **attrs) -> ObsEvent:
        """Record one event: stamps the injected clock and the current
        trace ID, bumps ``karpenter_events_total{type}``.  Attribute
        values are stringified (the ledger is a wire-safe JSON surface)."""
        with self._lock:
            self._seq += 1
            ev = ObsEvent(
                seq=self._seq,
                ts=self.clock.now(),
                type=type_,
                trace_id=current_trace_id(),
                attrs={k: str(v) for k, v in attrs.items()},
            )
            self._ring.append(ev)
            if self._sink is not None:
                self._sink.write(
                    json.dumps(ev.to_dict(), sort_keys=True) + "\n"
                )
                self._sink.flush()
        if self.registry is not None:
            self.registry.inc("karpenter_events_total", {"type": type_})
        return ev

    # ---------------------------------------------------------------- reading
    def recent(self, limit: int = 500) -> List[ObsEvent]:
        with self._lock:
            return list(self._ring)[-limit:]

    def read(
        self, since_seq: int, limit: Optional[int] = None
    ) -> Tuple[List[ObsEvent], int]:
        """(events with seq > since_seq still in the ring, dropped count):
        ``dropped`` counts events that matched the cursor but were already
        evicted — the loss a poller must see to know its cursor fell
        behind the ring (the `/events?since_seq=` contract, obs/http.py).
        ``limit`` caps the returned slice from the OLD end, so a catching-
        up poller pages forward without skipping."""
        with self._lock:
            dropped = (
                self._ring[0].seq - since_seq - 1
                if self._ring and self._ring[0].seq > since_seq + 1
                else max(0, self._seq - since_seq) if not self._ring else 0
            )
            events = [ev for ev in self._ring if ev.seq > since_seq]
        if limit is not None:
            events = events[: max(0, limit)]
        return events, dropped

    def drain(self, since_seq: int) -> List[ObsEvent]:
        """Events with seq > since_seq still in the ring (the simulator
        polls this once per tick to record the ledger into its trace).
        A poll interval that emitted more than the ring's capacity has
        already evicted the oldest events — that loss is LOUD, never
        silent: a sim trace/report undercounting vs
        ``karpenter_events_total`` must be explainable."""
        events, lost = self.read(since_seq)
        if lost > 0:
            log.warning(
                "event ledger overflowed between drains: %d event(s) "
                "evicted before being read (ring capacity %d)",
                lost, self._ring.maxlen,
            )
        return events

    def counts(self) -> Dict[str, int]:
        """Per-type counts over the RING (bounded); the registry counter
        `karpenter_events_total{type}` is the unbounded census."""
        out: Dict[str, int] = {}
        with self._lock:
            for ev in self._ring:
                out[ev.type] = out.get(ev.type, 0) + 1
        return out

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None
