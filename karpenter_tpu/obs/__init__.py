"""Observability plane: correlated decision traces, the cluster event
ledger, the live telemetry endpoint, and the trace renderer.

- ``obs.context``: the per-tick trace ID and its propagation rules — the
  operator mints one ID per reconcile tick, spans and ledger events stamp
  it automatically, and RPC clients ship it across the wire so a server's
  handling spans land on the same timeline (docs/designs/observability.md).
- ``obs.events``: the typed, ring-buffered cluster event ledger
  (PodNominated, NodeLaunched, NodeDisrupted{reason}, RetryBackoff,
  CircuitOpen, StaleServed, VerdictFallback) — deterministic under a
  FakeClock so the simulator records and replays it byte-identically.
- ``obs.http``: the stdlib telemetry server exposing /metrics (real
  Prometheus exposition), /healthz, /events, and /trace on the operator
  and store-server processes.
- ``obs.render``: ``python -m karpenter_tpu obs`` — span rings and sim
  traces rendered as Chrome-trace (Perfetto-loadable) JSON plus a
  terminal top-N self-time table.

Deliberately import-light: submodules are imported where used, so
``utils/trace.py`` can depend on ``obs.context`` without cycles.
"""
