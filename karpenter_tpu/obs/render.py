"""Trace renderer: span rings and sim traces -> Chrome-trace JSON.

``python -m karpenter_tpu obs INPUT`` converts any of

- a span dump (``Tracer.dump`` JSON, also served live at ``/trace``) —
  every recorded span becomes a duration event, one timeline row per
  trace ID, so "where did the tick go" reads as a flame slice;
- a recorded sim trace (the JSONL the scenario runner writes) — ticks
  become duration events on a ``sim`` row, injected scenario events and
  cluster-ledger events become instant markers, and the per-tick digest
  becomes counter tracks (pending pods, nodes, running instances); or
- a flight-recorder dump (obs/flight.py JSONL, dumped on SLOBreach /
  crash / SIGUSR1 or fetched from ``/debug/flight``) — ticks become
  duration events (wall durations on the injected-clock timeline),
  ledger events become instant markers, per-tick spans nest under their
  tick, and the cluster summary becomes counter tracks — so a breach
  artifact opens directly in Perfetto

into Chrome-trace (Perfetto / chrome://tracing loadable) JSON, plus a
terminal top-N SELF-time table — the ``pprof -top`` analogue, computed
by subtracting each span path's direct children from its inclusive
total.  The renderer is read-only tooling: a CI artifact (a crashed
run's trace, a span dump from a live /trace scrape) is enough input.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

_US = 1_000_000  # chrome-trace timestamps are microseconds


# ------------------------------------------------------------- span dumps
def chrome_from_spans(payload: dict) -> dict:
    """Tracer.dump payload -> chrome-trace dict.  Spans are placed on
    one thread row per trace ID (unattributed spans share a row), with
    start times normalized to the earliest recorded span."""
    recent = payload.get("recent", [])
    starts = [s.get("start_s", 0.0) for s in recent]
    base = min(starts) if starts else 0.0
    tids: Dict[str, int] = {}
    events: List[dict] = []
    for s in recent:
        trace_id = s.get("trace_id", "") or "(untraced)"
        tid = tids.setdefault(trace_id, len(tids) + 1)
        events.append(
            {
                "name": s["path"],
                "ph": "X",
                "ts": round((s.get("start_s", 0.0) - base) * _US, 3),
                "dur": round(s.get("duration_s", 0.0) * _US, 3),
                "pid": 1,
                "tid": tid,
                "args": {"trace_id": trace_id, **s.get("meta", {})},
            }
        )
    events += [
        {
            "name": "thread_name",
            "ph": "M",
            "pid": 1,
            "tid": tid,
            "args": {"name": trace_id},
        }
        for trace_id, tid in tids.items()
    ]
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def self_times(stats: Dict[str, dict]) -> List[Tuple[str, float, int]]:
    """(path, self_seconds, count) rows, self-time descending: each
    path's inclusive total minus its DIRECT children's totals (dotted
    span paths encode the nesting)."""
    rows = []
    for path, st in stats.items():
        child_total = sum(
            other["total_s"]
            for other_path, other in stats.items()
            if other_path.startswith(path + ".")
            and "." not in other_path[len(path) + 1 :]
        )
        rows.append(
            (path, max(st["total_s"] - child_total, 0.0), st["count"])
        )
    rows.sort(key=lambda r: -r[1])
    return rows


def top_table(stats: Dict[str, dict], n: int = 20) -> str:
    """Terminal top-N self-time table (the text-mode pprof -top)."""
    rows = self_times(stats)[:n]
    out = [f"{'span':48s} {'count':>8s} {'self_ms':>10s} {'self_avg_ms':>12s}"]
    for path, self_s, count in rows:
        avg = self_s / count if count else 0.0
        out.append(
            f"{path:48s} {count:8d} {self_s * 1000:10.1f} {avg * 1000:12.3f}"
        )
    return "\n".join(out)


# -------------------------------------------------------------- sim traces
def chrome_from_sim_trace(lines: List[dict]) -> dict:
    """Recorded sim-trace lines -> chrome-trace dict.

    Tick boundaries come from the ``tick`` lines' dt sequence; the
    absolute base is recovered from the first digest (`now` minus its
    tick's dt), so ledger events — which carry absolute simulated
    timestamps — land inside their ticks."""
    ticks: Dict[int, Tuple[float, str]] = {}
    order: List[int] = []
    for ln in lines:
        if ln.get("t") == "tick":
            ticks[ln["tick"]] = (ln["dt"], ln.get("phase", "run"))
            order.append(ln["tick"])
    first_dig = next((ln for ln in lines if ln.get("t") == "dig"), None)
    base = 0.0
    if first_dig is not None and order:
        base = first_dig["now"] - ticks[order[0]][0]
    starts: Dict[int, float] = {}
    cur = base
    for tick in order:
        starts[tick] = cur
        cur += ticks[tick][0]

    def ts(t: float) -> float:
        return round((t - base) * _US, 3)

    events: List[dict] = []
    meta = next((ln for ln in lines if ln.get("t") == "meta"), {})
    for tick in order:
        dt, phase = ticks[tick]
        events.append(
            {
                "name": f"tick {tick} ({phase})",
                "ph": "X",
                "ts": ts(starts[tick]),
                "dur": round(dt * _US, 3),
                "pid": 1,
                "tid": 1,
                "args": {"tick": tick, "phase": phase},
            }
        )
    for ln in lines:
        t = ln.get("t")
        if t == "ev":
            events.append(
                {
                    "name": ln["kind"],
                    "ph": "i",
                    "s": "t",
                    "ts": ts(starts.get(ln["tick"], base)),
                    "pid": 1,
                    "tid": 2,
                    "args": dict(ln.get("data", {})),
                }
            )
        elif t == "led":
            events.append(
                {
                    "name": ln["type"],
                    "ph": "i",
                    "s": "t",
                    "ts": ts(ln.get("ts", starts.get(ln["tick"], base))),
                    "pid": 1,
                    "tid": 3,
                    "args": {
                        "trace_id": ln.get("trace_id", ""),
                        **ln.get("attrs", {}),
                    },
                }
            )
        elif t == "dig":
            for counter in ("pending", "nodes", "running"):
                events.append(
                    {
                        "name": counter,
                        "ph": "C",
                        "ts": ts(ln["now"]),
                        "pid": 1,
                        "tid": 0,
                        "args": {counter: ln.get(counter, 0)},
                    }
                )
    events += [
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
         "args": {"name": "ticks"}},
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": 2,
         "args": {"name": "injected events"}},
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": 3,
         "args": {"name": "cluster ledger"}},
        {"name": "process_name", "ph": "M", "pid": 1,
         "args": {"name": f"sim {meta.get('scenario', '?')} "
                          f"seed={meta.get('seed', '?')}"}},
    ]
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def sim_event_counts(lines: List[dict]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for ln in lines:
        if ln.get("t") == "led":
            out[ln["type"]] = out.get(ln["type"], 0) + 1
    return out


# ------------------------------------------------------------ flight dumps
def chrome_from_flight(flight: dict) -> dict:
    """Flight-recorder dump (obs/flight.py) -> chrome-trace dict.  Ticks
    are duration events placed at their injected-clock timestamps with
    their WALL durations (a 1s-cadence loop whose ticks take ~10ms reads
    as sparse slices — correct: the gaps are idle time); ledger events
    are instants on their own row, per-tick spans nest on a third row,
    and the pending/nodes/running summary becomes counter tracks."""
    ticks = flight["ticks"]
    base = ticks[0]["ts"] if ticks else 0.0

    def ts(t: float) -> float:
        return round((t - base) * _US, 3)

    events: List[dict] = []
    for tick in ticks:
        start = tick["ts"] - tick.get("dur_s", 0.0)
        events.append(
            {
                "name": f"tick {tick['seq']}",
                "ph": "X",
                "ts": ts(start),
                "dur": round(tick.get("dur_s", 0.0) * _US, 3),
                "pid": 1,
                "tid": 1,
                "args": {
                    "trace_id": tick.get("trace_id", ""),
                    **tick.get("summary", {}),
                },
            }
        )
        for ev in tick.get("events", []):
            events.append(
                {
                    "name": ev.get("type", "?"),
                    "ph": "i",
                    "s": "t",
                    "ts": ts(ev.get("ts", tick["ts"])),
                    "pid": 1,
                    "tid": 2,
                    "args": {
                        "trace_id": ev.get("trace_id", ""),
                        **ev.get("attrs", {}),
                    },
                }
            )
        # spans carry perf_counter starts, not clock time: re-anchor them
        # inside their tick proportionally to their own earliest start
        spans = tick.get("spans", [])
        if spans:
            s0 = min(s.get("start_s", 0.0) for s in spans)
            for s in spans:
                events.append(
                    {
                        "name": s["path"],
                        "ph": "X",
                        "ts": ts(start + (s.get("start_s", 0.0) - s0)),
                        "dur": round(s.get("duration_s", 0.0) * _US, 3),
                        "pid": 1,
                        "tid": 3,
                        "args": dict(s.get("meta", {})),
                    }
                )
        for counter in ("pending", "nodes", "running"):
            if counter in tick.get("summary", {}):
                events.append(
                    {
                        "name": counter,
                        "ph": "C",
                        "ts": ts(tick["ts"]),
                        "pid": 1,
                        "tid": 0,
                        "args": {counter: tick["summary"][counter]},
                    }
                )
        # device observatory section -> counter tracks: the per-tick
        # upload bytes and compile counts sit on the timeline next to
        # the tick durations, so a recompile storm or transfer spike is
        # visible at the same glance as the phase slices
        dev = tick.get("device") or {}
        for counter in (
            "transfer_bytes", "compiles", "warm_recompiles",
            "resident_bytes",
        ):
            if counter in dev:
                events.append(
                    {
                        "name": f"device.{counter}",
                        "ph": "C",
                        "ts": ts(tick["ts"]),
                        "pid": 1,
                        "tid": 0,
                        "args": {counter: dev[counter]},
                    }
                )
    events += [
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
         "args": {"name": "ticks"}},
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": 2,
         "args": {"name": "cluster ledger"}},
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": 3,
         "args": {"name": "spans"}},
        {"name": "process_name", "ph": "M", "pid": 1,
         "args": {"name": f"flight ({flight['meta'].get('trigger', '?')})"}},
    ]
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def flight_event_counts(flight: dict) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for tick in flight["ticks"]:
        for ev in tick.get("events", []):
            out[ev["type"]] = out.get(ev["type"], 0) + 1
    return out


# --------------------------------------------------------------------- CLI
def _load(path: str) -> Tuple[str, object]:
    """Autodetect the input kind: ('sim', jsonl lines) for a scenario
    trace (first line has ``"t": "meta"``), ('flight', flight dict) for
    a flight-recorder dump (first line has ``"t": "flight"``),
    ('spans', payload) for a Tracer dump / a /trace scrape."""
    with open(path) as f:
        text = f.read()
    first = text.lstrip().split("\n", 1)[0]
    try:
        head = json.loads(first)
    except json.JSONDecodeError:
        head = None
    if isinstance(head, dict) and head.get("t") == "meta":
        return "sim", [json.loads(ln) for ln in text.splitlines() if ln.strip()]
    if isinstance(head, dict) and head.get("t") == "flight":
        from karpenter_tpu.obs.flight import read_flight

        return "flight", read_flight(text)
    payload = json.loads(text)
    if isinstance(payload, dict) and (
        "stats" in payload or "recent" in payload
    ):
        return "spans", payload
    raise ValueError(
        f"{path}: not a sim trace (JSONL with a meta line), a flight dump "
        "(JSONL with a flight header), or a span dump (JSON with "
        "stats/recent)"
    )


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m karpenter_tpu obs",
        description="render a span dump or a recorded sim trace as "
        "Chrome-trace (Perfetto-loadable) JSON + a top-N self-time table",
    )
    parser.add_argument(
        "input",
        help="a sim trace JSONL (sim-<scenario>-seed<N>.jsonl), a flight-"
        "recorder dump (flight-<trace>.jsonl / a /debug/flight fetch), or "
        "a span dump JSON (Tracer.dump / a /trace scrape)",
    )
    parser.add_argument(
        "--out",
        default="",
        help="chrome-trace output path (default: INPUT + .chrome.json)",
    )
    parser.add_argument(
        "--top", type=int, default=20, help="rows in the self-time table"
    )
    args = parser.parse_args(argv)

    kind, data = _load(args.input)
    if kind == "sim":
        chrome = chrome_from_sim_trace(data)
        counts = sim_event_counts(data)
        if counts:
            print("cluster events recorded in the trace:")
            for type_, n in sorted(counts.items()):
                print(f"  {type_:20s} {n:6d}")
        else:
            print("no cluster-ledger lines in this trace")
    elif kind == "flight":
        chrome = chrome_from_flight(data)
        counts = flight_event_counts(data)
        if counts:
            print("cluster events recorded in the flight dump:")
            for type_, n in sorted(counts.items()):
                print(f"  {type_:20s} {n:6d}")
        else:
            print("no cluster events in this flight dump")
        print(
            "diagnose it: python -m karpenter_tpu doctor "
            f"{args.input}", file=sys.stderr,
        )
    else:
        chrome = chrome_from_spans(data)
        stats = data.get("stats", {})
        if stats:
            print(top_table(stats, args.top))

    out_path = args.out or (args.input + ".chrome.json")
    with open(out_path, "w") as f:
        json.dump(chrome, f, sort_keys=True)
    print(
        f"chrome trace -> {out_path} "
        f"({len(chrome['traceEvents'])} events); load it in "
        "https://ui.perfetto.dev or chrome://tracing",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
