"""Device observatory: compile, transfer, and resident-memory telemetry
for the on-device hot path (docs/designs/observability.md §device).

PRs 8–9 moved the tick's time and memory past the dispatch boundary —
resident cluster tensors on donated buffers, the consolidation search in
two vmapped dispatches — and the host-side observability plane (traces,
ledger, SLO engine, flight recorder) was blind to everything behind it:
a recompile storm, a transfer-byte spike, or a resident-footprint leak
showed up only as an unexplained ``device_block`` phase regression.
This module is the missing layer.  It owns exactly three seams:

- :meth:`DeviceObservatory.dispatch` — EVERY jit entry point (the pack
  kernels, the verdict/population kernels, the resident delta step, the
  mesh/pallas variants) is invoked through this seam.  It counts the
  dispatch, attributes the host-array bytes handed across the device
  boundary (implicit uploads: a numpy argument to a jit call IS a
  transfer), derives a shape/static signature for deterministic
  would-compile accounting, detects actual recompiles via the jit cache
  size, times them, and records a trace-ID-stamped ``device.<fn>`` span
  so device dispatches appear on the tick timeline next to host phases.
- :meth:`DeviceObservatory.put` — every EXPLICIT ``jax.device_put``
  (catalog constants, the resident seed upload, the removal-base pin)
  goes through this counted put; lint rule 9 (tests/test_lint.py)
  fences raw ``device_put`` call sites so transfer accounting cannot
  silently rot.
- the resident hooks (:meth:`set_resident_footprint`,
  :meth:`count_resident_update`) — ``ops/resident.py`` reports its live
  device-buffer footprint per consumer and whether an update reused
  donated buffers (``donated``), re-seeded from scratch (``seed``), or
  was a pure no-change hit (``noop``).

Two accounting planes, deliberately distinct:

- **Process totals** feed the operator's diagnosis tail: the per-tick
  delta is exported into the registry as the ``karpenter_device_*``
  families (:func:`export_device_metrics`), snapshotted into the flight
  recorder's ``device`` section, served live at ``/debug/device``, and
  warm-tick recompiles — a compile of a function that already had
  dispatches in an EARLIER tick — surface as ``DeviceRecompile`` ledger
  events the doctor correlates.  Compile DURATIONS here are wall clock
  (the jit call returns only after trace+compile; execution itself stays
  async), which is exactly what an operator debugging a slow tick wants.
- **Scopes** (:meth:`begin_scope`) feed the simulator and the bench:
  per-run counters with *deterministic* compile accounting — a scope
  counts DISTINCT DISPATCH SIGNATURES (shape/dtype/static-arg tuples),
  i.e. how many compilations a cold process would need for the run,
  because actual jit-cache growth depends on what earlier runs in the
  same process already compiled and may never enter a byte-compared
  report.  Scope sections carry counts and bytes only — never seconds.

The observatory is process-global (like TRACER): ops-layer code holds no
registry, and emission into a registry happens only at the export seam.
With ``enabled = False`` every seam degrades to a passthrough — the
twin-run test proves observatory on/off changes zero scheduling actions.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.utils.trace import TRACER
from karpenter_tpu.analysis.sanitizer import make_lock, note_access


# str(np.dtype) costs microseconds and dispatch signatures sit on the
# fast path's per-admission budget; dtype objects are interned, so the
# names memoize cleanly
_DTYPE_NAMES: Dict = {}


def _dtype_name(dt) -> str:
    name = _DTYPE_NAMES.get(dt)
    if name is None:
        name = str(dt)
        _DTYPE_NAMES[dt] = name
    return name


def _sig_part(v) -> tuple:
    """One argument's contribution to a dispatch signature: arrays by
    (shape, dtype) — values are data, not trace constants — everything
    else (static kwargs like k_slots/objective) by value or type name."""
    shape = getattr(v, "shape", None)
    if shape is not None:
        dt = getattr(v, "dtype", None)
        return ("a", tuple(shape), _dtype_name(dt) if dt is not None else "")
    if isinstance(v, (int, float, str, bool, type(None))):
        return ("s", v)
    return ("t", type(v).__name__)


def dispatch_signature(args: tuple, kwargs: dict) -> tuple:
    return tuple(_sig_part(a) for a in args) + tuple(
        (k, _sig_part(kwargs[k])) for k in sorted(kwargs)
    )


def _transfer_nbytes(args: tuple, kwargs: dict) -> int:
    """Host-array bytes a dispatch hands across the device boundary.
    Device-resident (jax) arrays count zero — that is the whole point of
    the resident layer — and scalars are noise, not payload."""
    n = 0
    for a in args:
        if isinstance(a, np.ndarray):
            n += int(a.nbytes)
    for v in kwargs.values():
        if isinstance(v, np.ndarray):
            n += int(v.nbytes)
    return n


def _leaf_nbytes(value) -> int:
    """nbytes over the simple pytrees the put seam sees (an array, or a
    tuple/list of arrays)."""
    if isinstance(value, (tuple, list)):
        return sum(_leaf_nbytes(v) for v in value)
    nbytes = getattr(value, "nbytes", None)
    return int(nbytes) if nbytes is not None else 0


def _jit_cache_size(fn) -> Optional[int]:
    """Compiled-variant count of a jitted callable, None when the
    attribute is unavailable (custom callables, older jax)."""
    try:
        return fn._cache_size()
    except Exception:
        return None


class DeviceScope:
    """One accounting window: the process totals, a sim run, or a bench
    measurement window.  All fields are counts/bytes except
    ``compile_s`` (wall seconds, excluded from deterministic sections)."""

    __slots__ = (
        "dispatches", "compiles", "compile_s", "warm_recompiles",
        "shapes", "transfer_bytes", "resident_updates", "resident_bytes",
    )

    def __init__(self):
        self.dispatches: Dict[str, int] = {}
        self.compiles: Dict[str, int] = {}  # actual jit-cache growth
        self.compile_s: Dict[str, float] = {}  # wall seconds (totals only)
        self.warm_recompiles: Dict[str, int] = {}
        self.shapes: Dict[str, set] = {}  # fn -> distinct dispatch sigs
        self.transfer_bytes: Dict[str, int] = {}  # site -> bytes
        self.resident_updates: Dict[str, int] = {}  # donated/seed/noop
        self.resident_bytes: Dict[str, int] = {}  # consumer -> live bytes

    def unique_shapes(self) -> Dict[str, int]:
        return {fn: len(s) for fn, s in sorted(self.shapes.items())}

    def device_section(self, resident: Optional[Dict[str, int]] = None) -> dict:
        """The DETERMINISTIC per-scope summary (sim report contract):
        compile/transfer/resident counts and bytes only — no wall clock.
        ``compiles`` is the would-compile count: distinct dispatch
        signatures seen by this scope, i.e. the compilations a cold
        process would need for exactly this run — actual jit-cache
        growth depends on process history and may not enter a
        byte-compared report.  ``resident`` is the caller's footprint
        mapping: the sim passes its OWN environment's cache footprint,
        because the observatory's process-wide view merges every live
        cache (a previous run's not-yet-collected Environment would
        leak into a byte-compared report); without it the section
        carries whatever the caller stored on the scope (empty by
        default)."""
        if resident is None:
            resident = self.resident_bytes
        return {
            "compiles": self.unique_shapes(),
            "dispatches": dict(sorted(self.dispatches.items())),
            "transfer_bytes": dict(sorted(self.transfer_bytes.items())),
            "resident": {
                "bytes": dict(sorted(resident.items())),
                "updates": dict(sorted(self.resident_updates.items())),
            },
        }


class DeviceObservatory:
    def __init__(self):
        self.enabled = True
        self._lock = make_lock("DeviceObservatory._lock")
        self.total = DeviceScope()
        self._scopes: List[DeviceScope] = []
        # warm-tick bookkeeping: the operator bumps the tick; a compile
        # of a function whose FIRST dispatch happened in an earlier tick
        # is a warm recompile (a fresh padded bucket, a donation falling
        # through, an axis change) — the signal behind DeviceRecompile
        self._tick = 0
        self._first_tick: Dict[str, int] = {}
        # compile events not yet drained by export: (fn, seconds, warm)
        self._pending_compiles: List[Tuple[str, float, bool]] = []
        # per-owner resident footprints (one ResidentCache per scheduler:
        # the provisioner's and the deprovisioner's both report; the
        # consumer-level view sums across owners).  Weak keys: a cache
        # dying with its Environment must not pin it — or leave a stale
        # footprint — forever.
        self._resident_sources: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )
        # totals snapshot at the top of the current tick (flight section)
        self._tick_base: dict = self._base_snapshot()

    # ------------------------------------------------------------- scopes
    def begin_scope(self) -> DeviceScope:
        scope = DeviceScope()
        with self._lock:
            self._scopes.append(scope)
        return scope

    def end_scope(self, scope: DeviceScope) -> DeviceScope:
        with self._lock:
            if scope in self._scopes:
                self._scopes.remove(scope)
        return scope

    def _all_scopes(self) -> List[DeviceScope]:
        return [self.total] + self._scopes

    # ------------------------------------------------------------- seams
    def dispatch(self, name: str, fn, *args, **kwargs):
        """Invoke a jit entry point through the counted seam (see module
        docstring).  Returns whatever ``fn`` returns; with the
        observatory disabled this is a bare passthrough."""
        if not self.enabled:
            return fn(*args, **kwargs)
        nbytes = _transfer_nbytes(args, kwargs)
        sig = dispatch_signature(args, kwargs)
        before = _jit_cache_size(fn)
        t0 = time.perf_counter()
        with TRACER.span(f"device.{name}", bytes=nbytes):
            out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        with self._lock:
            fresh_sig = sig not in self.total.shapes.get(name, ())
            after = _jit_cache_size(fn)
            if before is not None and after is not None:
                compiled = max(0, after - before)
            else:
                # no cache introspection: a never-seen signature is the
                # best available compile proxy
                compiled = 1 if fresh_sig else 0
            warm = bool(compiled) and (
                self._first_tick.get(name, self._tick) < self._tick
            )
            self._first_tick.setdefault(name, self._tick)
            for sc in self._all_scopes():
                sc.dispatches[name] = sc.dispatches.get(name, 0) + 1
                sc.shapes.setdefault(name, set()).add(sig)
                if nbytes:
                    sc.transfer_bytes[name] = (
                        sc.transfer_bytes.get(name, 0) + nbytes
                    )
                if compiled:
                    sc.compiles[name] = sc.compiles.get(name, 0) + compiled
                    sc.compile_s[name] = sc.compile_s.get(name, 0.0) + dt
                    if warm:
                        sc.warm_recompiles[name] = (
                            sc.warm_recompiles.get(name, 0) + 1
                        )
            if compiled:
                self._pending_compiles.append((name, dt, warm))
        return out

    def put(self, site: str, value, sharding=None):
        """The ONE counted ``jax.device_put``: every explicit upload
        routes through here (lint rule 9 fences the raw call sites), so
        ``karpenter_device_transfer_bytes_total{site}`` covers the whole
        host->device surface, not just jit-argument uploads."""
        import jax

        dev = (
            jax.device_put(value, sharding)
            if sharding is not None
            else jax.device_put(value)
        )
        if self.enabled:
            self.count_transfer(site, _leaf_nbytes(value))
        return dev

    def count_transfer(self, site: str, nbytes: int) -> None:
        if not self.enabled or nbytes <= 0:
            return
        with self._lock:
            for sc in self._all_scopes():
                sc.transfer_bytes[site] = (
                    sc.transfer_bytes.get(site, 0) + nbytes
                )

    # ----------------------------------------------------------- resident
    def set_resident_footprint(
        self, owner, footprint: Dict[str, int]
    ) -> None:
        """Replace ONE owner's live device-buffer footprint (consumer ->
        bytes) — each ResidentCache reports after every seed/evict.
        Owners are weak-referenced and the merge is computed at READ
        time (:meth:`resident_footprint`), so a cache dying with its
        scheduler drops out of the reported footprint on its own —
        recording the merge at write time would leave a collected
        cache's bytes lingering until some OTHER cache next reported
        (steady warm clusters never rebuild, so possibly forever)."""
        if not self.enabled:
            return
        with self._lock:
            note_access("DeviceObservatory._resident_sources")
            self._resident_sources[owner] = dict(footprint)

    def _merged_resident(self) -> Dict[str, int]:
        """Consumer -> bytes summed over the LIVE owners (call under the
        lock; WeakKeyDictionary iteration is GC-safe)."""
        merged: Dict[str, int] = {}
        for fp in self._resident_sources.values():
            for consumer, v in fp.items():
                merged[consumer] = merged.get(consumer, 0) + v
        return merged

    def resident_footprint(self) -> Dict[str, int]:
        with self._lock:
            note_access("DeviceObservatory._resident_sources",
                        write=False)
            return self._merged_resident()

    def count_resident_update(self, kind: str) -> None:
        """kind: 'donated' (scatter delta reused donated buffers),
        'seed' (fresh full-tensor upload), 'noop' (refresh hit with no
        tensor change)."""
        if not self.enabled:
            return
        with self._lock:
            for sc in self._all_scopes():
                sc.resident_updates[kind] = (
                    sc.resident_updates.get(kind, 0) + 1
                )

    # --------------------------------------------------------------- ticks
    def _base_snapshot(self) -> dict:
        t = self.total
        return {
            "compiles": sum(t.compiles.values()),
            "warm_recompiles": sum(t.warm_recompiles.values()),
            "dispatches": sum(t.dispatches.values()),
            "transfer_bytes": sum(t.transfer_bytes.values()),
            "resident_bytes": sum(self._merged_resident().values()),
        }

    def begin_tick(self, seq: int) -> None:
        """Mark a reconcile-tick boundary (the operator, right after
        minting the tick's trace ID): compiles from here on are warm for
        any function already dispatched in an earlier tick, and the
        flight recorder's ``device`` section deltas against this point."""
        with self._lock:
            self._tick = seq
            self._tick_base = self._base_snapshot()

    def tick_section(self) -> dict:
        """The flight recorder's per-tick ``device`` section: what the
        device layer did THIS tick (deltas vs the begin_tick snapshot)
        plus the current and per-tick-delta resident footprint."""
        with self._lock:
            cur = self._base_snapshot()
            base = self._tick_base
            return {
                "compiles": cur["compiles"] - base["compiles"],
                "warm_recompiles": (
                    cur["warm_recompiles"] - base["warm_recompiles"]
                ),
                "dispatches": cur["dispatches"] - base["dispatches"],
                "transfer_bytes": (
                    cur["transfer_bytes"] - base["transfer_bytes"]
                ),
                "resident_bytes": cur["resident_bytes"],
                "resident_delta_bytes": (
                    cur["resident_bytes"] - base["resident_bytes"]
                ),
            }

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> dict:
        """The full live picture (the /debug/device payload): process
        totals per function/site, warm-recompile counts, compile wall
        seconds, and the resident footprint."""
        with self._lock:
            t = self.total
            resident = self._merged_resident()
            return {
                "enabled": self.enabled,
                "tick": self._tick,
                "compiles": dict(sorted(t.compiles.items())),
                "compile_seconds": {
                    fn: round(s, 6)
                    for fn, s in sorted(t.compile_s.items())
                },
                "warm_recompiles": dict(sorted(t.warm_recompiles.items())),
                "unique_shapes": t.unique_shapes(),
                "dispatches": dict(sorted(t.dispatches.items())),
                "transfer_bytes": dict(sorted(t.transfer_bytes.items())),
                "resident": {
                    "bytes": dict(sorted(resident.items())),
                    "bytes_total": sum(resident.values()),
                    "updates": dict(sorted(t.resident_updates.items())),
                },
            }


# the process observatory every seam records into (the TRACER pattern:
# ops-layer code holds no registry; emission happens at the export seam)
OBSERVATORY = DeviceObservatory()


def export_device_metrics(
    registry, obs: DeviceObservatory, exported: Optional[dict]
) -> Tuple[dict, List[dict]]:
    """Mirror the observatory's monotonic totals into the registry's
    ``karpenter_device_*`` families by DELTA — the same contract as
    ``export_compile_cache_counters`` (the caller keeps the state it last
    exported, so the registry series stay well-formed monotonic counters).
    Drains the pending compile events into the
    ``karpenter_device_compile_seconds{fn}`` histogram and returns the
    warm-recompile attributions (fn + compile seconds) for the caller to
    turn into ``DeviceRecompile`` ledger events — emission stays with the
    caller because ledger events enter byte-compared sim traces and
    jit-cache state is process history, not run behavior."""
    exported = exported or {}
    with obs._lock:
        t = obs.total
        totals = {
            "compiles": dict(t.compiles),
            "warm": dict(t.warm_recompiles),
            "dispatches": dict(t.dispatches),
            "transfer": dict(t.transfer_bytes),
            "updates": dict(t.resident_updates),
        }
        resident = obs._merged_resident()
        pending = obs._pending_compiles
        obs._pending_compiles = []

    def _inc(metric: str, label: str, key: str) -> Dict[str, float]:
        prev = exported.get(key, {})
        cur = totals[key]
        for name, v in cur.items():
            d = v - prev.get(name, 0)
            if d > 0:
                registry.inc(metric, {label: name}, by=d)
        return dict(cur)

    new = {
        "compiles": _inc("karpenter_device_compiles_total", "fn", "compiles"),
        "warm": _inc(
            "karpenter_device_warm_recompiles_total", "fn", "warm"
        ),
        "dispatches": _inc(
            "karpenter_device_dispatches_total", "fn", "dispatches"
        ),
        "transfer": _inc(
            "karpenter_device_transfer_bytes_total", "site", "transfer"
        ),
        "updates": _inc(
            "karpenter_device_resident_updates_total", "kind", "updates"
        ),
    }
    for fn, dt, _warm in pending:
        registry.observe(
            "karpenter_device_compile_seconds", dt, {"fn": fn}
        )
    # gauge family: set current consumers, unset vanished ones (an
    # evicted resident state's bytes must not linger as a stale series)
    for consumer in exported.get("resident", {}):
        if consumer not in resident:
            registry.unset(
                "karpenter_device_resident_bytes", {"consumer": consumer}
            )
    for consumer, v in resident.items():
        registry.set(
            "karpenter_device_resident_bytes", float(v),
            {"consumer": consumer},
        )
    new["resident"] = resident
    warm_events = [
        {"fn": fn, "compile_s": round(dt, 6)}
        for fn, dt, warm in pending
        if warm
    ]
    return new, warm_events
