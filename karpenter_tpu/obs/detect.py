"""Streaming anomaly detection over the per-phase latency series.

The phase histograms (``karpenter_solver_phase_seconds``,
``karpenter_consolidation_phase_seconds``, tick durations) say where time
went; this detector says when that changed.  Once per reconcile tick the
operator calls :meth:`AnomalyDetector.scan`, which walks the samples each
watched series gained since the last scan and compares every new
observation against a rolling ROBUST baseline of that series — median and
MAD (median absolute deviation), so a single earlier spike cannot inflate
the baseline the way a mean/stddev would.

A sample is anomalous when all of these hold (belt and suspenders — phase
latencies are noisy at the sub-millisecond floor):

- its robust z-score ``(v - median) / (1.4826 * MAD)`` exceeds
  ``z_threshold`` (MAD of 0 on a flat baseline falls back to a fraction
  of the median so a step change still scores),
- it exceeds ``min_abs_s`` absolutely (microsecond jitter never pages),
- it exceeds twice the median (the magnitude a human would call a blowup),
- the baseline holds at least ``min_baseline`` samples (cold series are
  unjudgeable),
- the series is outside its per-series cooldown (injected clock), so a
  sustained regression reads as one attributed event per cooldown window,
  not a firehose.

Detections emit ``AnomalyDetected`` ledger events carrying the
attribution the ISSUE asks for — which series/phase, baseline vs
observed, magnitude — so "catalog roll → compile storm → dispatch p99
blowup" is a ledger fact, and bump
``karpenter_anomaly_detected_total{series,phase}``.

The detector itself reads no wall clock (cooldowns ride the injected
Clock; determinism given a deterministic observation stream), but the
latency VALUES it watches are host wall time — so the simulator disables
it (``ScenarioRunner`` determinism knob) the same way it pins
launch concurrency: byte-identical traces cannot include judgments about
host speed.
"""

from __future__ import annotations

import statistics
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from karpenter_tpu.metrics.registry import Registry
from karpenter_tpu.utils.clock import Clock

# the latency families worth watching: solver phases, consolidation
# batch phases, whole-tick durations
WATCHED_FAMILIES = (
    "karpenter_solver_phase_seconds",
    "karpenter_consolidation_phase_seconds",
    "karpenter_consolidation_search_phase_seconds",
    "karpenter_reconcile_tick_duration_seconds",
    # device observatory: a compile-time blowup (recompile storm, a jit
    # suddenly retracing every tick) judges exactly like a phase blowup
    "karpenter_device_compile_seconds",
    # store plane: the client half's per-RPC latency (state/remote.py)
    # — a store server falling over shows up here first, per method
    "karpenter_store_rpc_seconds",
    # admission path split (controllers/provisioning.py): the fast
    # path's pod->nomination latency blowing up — or the batch series
    # absorbing traffic the fast path used to take — judges exactly
    # like a phase blowup, attributed per path label
    "karpenter_admission_latency_seconds",
    # solver service: per-tenant solve-wait blowing up (backpressure,
    # a noisy neighbor monopolizing the batch window) judges like a
    # phase blowup, attributed per tenant label
    "karpenter_service_solve_wait_seconds",
)

_MAD_SCALE = 1.4826  # MAD -> stddev-equivalent under normality


def robust_baseline(samples) -> Tuple[float, float]:
    """(median, scale) of a sample window: scale is the MAD-derived
    stddev equivalent, floored at 10% of the median so a perfectly flat
    baseline (MAD 0) still yields a finite z for a step change."""
    med = statistics.median(samples)
    mad = statistics.median(abs(x - med) for x in samples)
    return med, max(_MAD_SCALE * mad, 0.1 * abs(med), 1e-9)


class AnomalyDetector:
    def __init__(
        self,
        registry: Registry,
        clock: Clock,
        enabled: bool = True,
        window: int = 64,
        z_threshold: float = 6.0,
        min_abs_s: float = 0.01,
        min_baseline: int = 8,
        cooldown_s: float = 60.0,
    ):
        self.registry = registry
        self.clock = clock
        self.enabled = enabled
        self.window = window
        self.z_threshold = z_threshold
        self.min_abs_s = min_abs_s
        self.min_baseline = min_baseline
        self.cooldown_s = cooldown_s
        self._consumed: Dict[Tuple[str, Tuple], int] = {}
        self._baselines: Dict[Tuple[str, Tuple], Deque[float]] = {}
        self._last_emit: Dict[Tuple[str, Tuple], float] = {}

    def scan(self) -> List[dict]:
        """Judge every sample the watched series gained since the last
        scan; returns the detections (also emitted as ledger events)."""
        if not self.enabled:
            return []
        now = self.clock.now()
        out: List[dict] = []
        for name in WATCHED_FAMILIES:
            for labels, hist in self.registry.histograms.get(name, {}).items():
                key = (name, labels)
                seen = self._consumed.get(key, 0)
                fresh_n = hist.count - seen
                self._consumed[key] = hist.count
                if fresh_n <= 0:
                    continue
                # the sample window may have evicted very old entries;
                # everything still present and newer than `seen` is fresh
                samples = list(hist.samples)
                fresh = samples[-min(fresh_n, len(samples)):]
                baseline = self._baselines.setdefault(
                    key, deque(maxlen=self.window)
                )
                phase = labels[0][1] if labels else ""
                for v in fresh:
                    det = self._judge(key, name, phase, baseline, v, now)
                    if det is not None:
                        out.append(det)
                    baseline.append(v)
        return out

    def _judge(
        self, key, name: str, phase: str, baseline, v: float, now: float
    ) -> Optional[dict]:
        if len(baseline) < self.min_baseline:
            return None
        med, scale = robust_baseline(baseline)
        z = (v - med) / scale
        if z < self.z_threshold or v < self.min_abs_s or v < 2.0 * med:
            return None
        last = self._last_emit.get(key)
        if last is not None and now - last < self.cooldown_s:
            return None
        self._last_emit[key] = now
        magnitude = v / med if med > 0 else float(round(z, 1))
        det = {
            "series": name,
            "phase": phase,
            "baseline_s": round(med, 6),
            "observed_s": round(v, 6),
            "magnitude": round(magnitude, 2),
            "z": round(z, 2),
        }
        self.registry.inc(
            "karpenter_anomaly_detected_total",
            {"series": name, "phase": phase},
        )
        self.registry.event("AnomalyDetected", **det)
        return det
