"""Live telemetry endpoint: /metrics, /healthz, /events, /trace.

The reference serves ~50 Prometheus series plus pprof handlers on its
metrics port (website v0.31 concepts/metrics.md, settings.md:18); this is
that surface for the reproduction, mounted on BOTH the operator process
(`python -m karpenter_tpu --metrics-port`) and the store server
(`store-server --telemetry-port`):

- ``/metrics``  real Prometheus exposition (HELP/TYPE headers from the
                shared metric catalog, cumulative histogram buckets) —
                scrapeable by an actual Prometheus server;
- ``/healthz``  liveness (``ok``) — the chart's probe target;
- ``/events``   the cluster event ledger's recent ring as JSON — the
                "why did that node go away?" surface;
- ``/trace``    the span tracer's aggregates + recent spans as JSON —
                feedable to ``python -m karpenter_tpu obs`` for a
                Perfetto-loadable timeline.

Every request bumps ``karpenter_telemetry_scrapes_total{endpoint}`` so
the scrape cadence is itself observable (a stalled scraper is an
outage-in-waiting).  Stdlib-only by design: the container bakes no
client libraries, and a ThreadingHTTPServer is plenty for one scraper
plus a human.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from karpenter_tpu.metrics.registry import Registry, exposition


def _trace_payload(tracer) -> dict:
    return {
        "stats": {
            path: {"count": st.count, "total_s": st.total_s, "max_s": st.max_s}
            for path, st in tracer.stats().items()
        },
        "recent": [
            {
                "path": s.path,
                "start_s": s.start_s,
                "duration_s": s.duration_s,
                "trace_id": s.trace_id,
                "meta": s.meta,
            }
            for s in tracer.recent(500)
        ],
    }


def start_telemetry(
    port: int,
    registry: Registry,
    tracer=None,
    ledger=None,
    host: str = "",
) -> ThreadingHTTPServer:
    """Serve the telemetry surface on (host, port) in a daemon thread;
    port 0 binds a free port (tests).  Returns the server (its
    ``server_address[1]`` is the bound port; ``shutdown()`` stops it)."""

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (http.server API)
            path = self.path.split("?", 1)[0]
            if path not in ("/metrics", "/healthz", "/events", "/trace"):
                self.send_response(404)
                self.end_headers()
                return
            # counting BEFORE rendering: the scrape that reads the
            # counter sees itself, so the series is never 0 on a
            # scraped process
            registry.inc(
                "karpenter_telemetry_scrapes_total",
                {"endpoint": path.strip("/")},
            )
            if path == "/metrics":
                body = exposition(registry).encode()
                ctype = "text/plain; version=0.0.4"
            elif path == "/healthz":
                body = b"ok"
                ctype = "text/plain"
            elif path == "/events":
                events = (
                    [ev.to_dict() for ev in ledger.recent(500)]
                    if ledger is not None
                    else []
                )
                body = json.dumps(events, sort_keys=True).encode()
                ctype = "application/json"
            else:  # /trace
                payload = (
                    _trace_payload(tracer) if tracer is not None else {}
                )
                body = json.dumps(payload, sort_keys=True).encode()
                ctype = "application/json"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # quiet access log
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(
        target=server.serve_forever, daemon=True, name=f"telemetry-{port}"
    ).start()
    return server
