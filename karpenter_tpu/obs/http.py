"""Live telemetry endpoint: /metrics, /healthz, /events, /trace.

The reference serves ~50 Prometheus series plus pprof handlers on its
metrics port (website v0.31 concepts/metrics.md, settings.md:18); this is
that surface for the reproduction, mounted on BOTH the operator process
(`python -m karpenter_tpu --metrics-port`) and the store server
(`store-server --telemetry-port`):

- ``/metrics``  real Prometheus exposition (HELP/TYPE headers from the
                shared metric catalog, cumulative histogram buckets) —
                scrapeable by an actual Prometheus server;
- ``/healthz``  liveness (``ok``) — the chart's probe target;
- ``/events``   the cluster event ledger as JSON, with cursor support:
                ``?since_seq=N&limit=M`` pages forward from a poller's
                last seen sequence number, and the payload's ``dropped``
                count says how many events aged out of the ring before
                the cursor caught up — a poller can fall behind, but
                never silently.  ``ring_counts`` are per-type counts over
                the bounded ring; ``total_counts`` mirror the cumulative
                ``karpenter_events_total`` census (the two diverge once
                the ring overflows — by design);
- ``/trace``    the span tracer's aggregates + recent spans as JSON —
                feedable to ``python -m karpenter_tpu obs`` for a
                Perfetto-loadable timeline;
- ``/debug/flight``  the flight recorder's ring (obs/flight.py) as
                JSONL — the same artifact a breach dumps to disk, for
                ``python -m karpenter_tpu doctor http://host:port``;
- ``/debug/device``  the device observatory's live snapshot
                (obs/device.py): compiles / warm recompiles / compile
                seconds per jit entry point, transfer bytes per site,
                and the resident device-buffer footprint per consumer —
                "what lives on the device and what crossed the link";
- ``/debug/tenants``  the solver service's per-tenant admission state
                (service/server.py tenants_payload): in-flight counts,
                solve/batch/refusal tallies, resident footprints vs the
                device-bytes budget, and tenant-scoped ledger slices —
                "who is on the mesh and what are they costing".

Every request bumps ``karpenter_telemetry_scrapes_total{endpoint}`` so
the scrape cadence is itself observable (a stalled scraper is an
outage-in-waiting).  Stdlib-only by design: the container bakes no
client libraries, and a ThreadingHTTPServer is plenty for one scraper
plus a human.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from karpenter_tpu.metrics.registry import Registry, exposition


def _trace_payload(tracer) -> dict:
    return {
        "stats": {
            path: {"count": st.count, "total_s": st.total_s, "max_s": st.max_s}
            for path, st in tracer.stats().items()
        },
        "recent": [
            {
                "path": s.path,
                "start_s": s.start_s,
                "duration_s": s.duration_s,
                "trace_id": s.trace_id,
                "meta": s.meta,
            }
            for s in tracer.recent(500)
        ],
    }


def _int_param(params: dict, name: str, default: int) -> int:
    try:
        return int(params.get(name, [default])[0])
    except (TypeError, ValueError):
        return default


def events_payload(ledger, registry: Registry, params: dict) -> dict:
    """The /events JSON body.  ``since_seq``/``limit`` page the ring
    forward (oldest first); WITHOUT a cursor the newest ``limit`` events
    are served — a bare curl must show what just happened, not the
    oldest survivors of a full ring.  ``last_seq`` is the cursor for the
    next poll.  ``dropped``
    counts events the cursor missed because they aged out of the
    4096-entry ring — without it a slow poller silently undercounts.
    ``ring_counts`` (the old ambiguous ``counts``) covers only what the
    ring still holds; ``total_counts`` is the cumulative
    ``karpenter_events_total`` census from the registry."""
    since_seq = _int_param(params, "since_seq", 0)
    limit = _int_param(params, "limit", 500)
    if ledger is None:
        events, dropped = [], 0
    elif "since_seq" in params:
        # cursor mode: page forward from the poller's last seen seq,
        # oldest first, with the dropped count for ring overflow
        events, dropped = ledger.read(since_seq, limit)
    else:
        # no cursor: the human-curl case — serve the NEWEST events, the
        # "why did that node go away?" surface
        events, dropped = ledger.recent(limit), 0
    with registry._lock:
        # copy under the lock: the operator thread inserts a NEW label
        # key the instant a first-of-its-type event fires — exactly the
        # moment a poller is most likely to be reading this
        census = dict(registry.counters.get("karpenter_events_total", {}))
    total_counts = {
        labels[0][1] if labels else "": int(v)
        for labels, v in census.items()
    }
    return {
        "events": [ev.to_dict() for ev in events],
        "last_seq": events[-1].seq if events else since_seq,
        "dropped": dropped,
        "ring_counts": ledger.counts() if ledger is not None else {},
        "total_counts": dict(sorted(total_counts.items())),
    }


def start_telemetry(
    port: int,
    registry: Registry,
    tracer=None,
    ledger=None,
    flight=None,
    device=None,
    tenants=None,
    host: str = "",
) -> ThreadingHTTPServer:
    """Serve the telemetry surface on (host, port) in a daemon thread;
    port 0 binds a free port (tests).  Returns the server (its
    ``server_address[1]`` is the bound port; ``shutdown()`` stops it)."""

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (http.server API)
            path, _, query = self.path.partition("?")
            known = (
                "/metrics", "/healthz", "/events", "/trace",
                "/debug/flight", "/debug/device", "/debug/tenants",
            )
            if path not in known:
                self.send_response(404)
                self.end_headers()
                return
            # counting BEFORE rendering: the scrape that reads the
            # counter sees itself, so the series is never 0 on a
            # scraped process
            registry.inc(
                "karpenter_telemetry_scrapes_total",
                {"endpoint": path.strip("/")},
            )
            if path == "/metrics":
                body = exposition(registry).encode()
                ctype = "text/plain; version=0.0.4"
            elif path == "/healthz":
                body = b"ok"
                ctype = "text/plain"
            elif path == "/events":
                payload = events_payload(
                    ledger, registry, urllib.parse.parse_qs(query)
                )
                body = json.dumps(payload, sort_keys=True).encode()
                ctype = "application/json"
            elif path == "/debug/flight":
                lines = (
                    flight.dump_lines(trigger="http")
                    if flight is not None
                    else []
                )
                if flight is not None:
                    # dump_lines itself never counts (FlightRecorder.dump
                    # counts after a successful disk write); the served
                    # dump counts here so the documented {trigger="http"}
                    # series exists
                    registry.inc(
                        "karpenter_flight_dumps_total", {"trigger": "http"}
                    )
                body = ("\n".join(lines) + "\n").encode() if lines else b""
                ctype = "application/x-ndjson"
            elif path == "/debug/device":
                payload = (
                    device.snapshot() if device is not None else {}
                )
                body = json.dumps(payload, sort_keys=True).encode()
                ctype = "application/json"
            elif path == "/debug/tenants":
                # ``tenants`` is a callable (the solver service's
                # tenants_payload) so every scrape sees live state
                payload = tenants() if tenants is not None else {}
                body = json.dumps(payload, sort_keys=True).encode()
                ctype = "application/json"
            else:  # /trace
                payload = (
                    _trace_payload(tracer) if tracer is not None else {}
                )
                body = json.dumps(payload, sort_keys=True).encode()
                ctype = "application/json"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # quiet access log
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(
        target=server.serve_forever, daemon=True, name=f"telemetry-{port}"
    ).start()
    return server
