"""Trace-context propagation: one ID per reconcile tick, everywhere.

The operator mints a trace ID at the top of every reconcile tick
(`mint_trace_id`) and installs it as the PROCESS default (`set_tick`).
Everything that happens on behalf of that tick — controller reconcile
spans, solver phases, cloud retry attempts, ledger events, store RPCs —
reads `current_trace_id()` and stamps it, so one ID follows a pod from
arrival through nomination, launch, and the remote store write.

Two scopes, cheapest-possible reads:

- the **tick default** is a module global: the reconcile loop is
  single-threaded per operator, and worker threads spawned mid-tick
  (launch fan-out, interruption workers) inherit the tick's identity by
  reading the same global — exactly the correlation we want;
- `trace_context(tid)` installs a **thread-local override** for code
  that acts on behalf of a DIFFERENT timeline than the process's current
  tick: the store server handling a client's RPC adopts the CLIENT's
  trace ID for the duration of the dispatch, which is what stitches the
  two processes into one timeline.

IDs are deterministic by construction (`<identity-or-tick>-<seq>`): the
simulator's ledger and trace lines must be byte-identical across replays,
so nothing wall-clock or random may enter an ID.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator

_local = threading.local()
_tick_id = ""


def mint_trace_id(seq: int, identity: str = "") -> str:
    """Deterministic per-tick trace ID.  `identity` distinguishes
    operators in multi-replica setups (the elector identity); the
    simulator's single operator has none, so sim IDs are `tick-NNNNNN`."""
    return f"{identity or 'tick'}-{seq:06d}"


def set_tick(trace_id: str) -> None:
    """Install the process-default trace ID (the operator, once per
    reconcile tick)."""
    global _tick_id
    _tick_id = trace_id


def current_trace_id() -> str:
    """The active trace ID: a thread-local override if one is installed
    (RPC servers adopting a client's context), else the tick default."""
    return getattr(_local, "trace_id", None) or _tick_id


@contextlib.contextmanager
def trace_context(trace_id: str) -> Iterator[None]:
    """Thread-local trace-ID override for the block (restores the prior
    override on exit).  An empty ID is a no-op installer: the block keeps
    reading the tick default."""
    prev = getattr(_local, "trace_id", None)
    _local.trace_id = trace_id or prev
    try:
        yield
    finally:
        _local.trace_id = prev
