"""Declarative SLO rule engine with multi-window burn-rate evaluation.

Karpenter's production contract is metrics-driven alerting — the
reference's docs tell operators to page on pending-pod age and disruption
rate (website v0.31 concepts/metrics.md) — but PR 6's telemetry plane
only *published* signals; nothing consumed them.  This engine closes the
loop: the Operator evaluates a rule set once per reconcile tick, on the
injected Clock, against the metrics registry the controllers already
write, and raises/clears alerts deterministically.

Mechanics (the SRE-workbook multi-window burn-rate shape, discretized to
reconcile ticks):

- a rule names a **signal** (a registered read over the registry:
  ``tick_duration_p99``, ``pending_pod_age_max``, ``circuits_open``, ...),
  a **threshold** with a comparison direction, and a **budget** — the
  fraction of time the signal is allowed to violate the threshold;
- each evaluation appends (now, violating?) to the rule's history and
  computes the violating time over a **fast** and a **slow** window;
  ``burn = (violating / window span) / budget`` (a budget of 0 means
  zero tolerance: any violation saturates the burn at BURN_CAP);
- a rule **breaches** when BOTH windows burn at >= 1 (the fast window
  pages, the slow window confirms it is not a blip) and **recovers**
  when the fast window drops back under 1;
- transitions emit ``SLOBreach`` / ``SLORecovered`` ledger events
  (stamped with the tick's trace ID like every other decision) and bump
  ``karpenter_slo_breaches_total{rule}``; every evaluation exports
  ``karpenter_slo_status{rule}`` and
  ``karpenter_slo_burn_rate{rule,window}``.

Everything is a pure function of the injected clock and the registry, so
the simulator evaluates scenario-declared rules and replays the breach/
recovery ledger lines byte-identically (tests/test_diagnosis.py).  Rules
are configured through ``Settings.slo_rules`` (and the chart's settings
values): per-rule overrides of threshold/budget/windows/enabled merged
over the defaults below, or entirely new rules naming a signal.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from karpenter_tpu.metrics.registry import Registry
from karpenter_tpu.utils.clock import Clock

# burn saturation for zero-budget rules (any violation of a must-stay-0
# signal is an instant page; infinity would not round-trip through gauges)
BURN_CAP = 1000.0


# ----------------------------------------------------------------- signals
def _gauge_family_max(registry: Registry, name: str) -> Optional[float]:
    series = registry.gauges.get(name)
    if not series:
        return None
    return max(series.values())


_TICK_P99_MIN_SAMPLES = 30
_TICK_P99_WINDOW = 64


def _tick_duration_p99(registry: Registry) -> Optional[float]:
    """p99 of the last 64 tick durations, after a 30-tick startup grace:
    a paging signal must describe the cluster NOW, and the first ticks'
    JAX compiles (seconds, by design) would otherwise pin the lifetime
    p99 above any sane threshold for hours."""
    from karpenter_tpu.metrics.registry import _nearest_rank

    h = registry.histograms.get(
        "karpenter_reconcile_tick_duration_seconds", {}
    ).get(())
    if h is None or h.count < _TICK_P99_MIN_SAMPLES:
        return None
    window = list(h.samples)[-_TICK_P99_WINDOW:]
    return _nearest_rank(sorted(window), 0.99)


def _pending_pod_age_max(registry: Registry) -> Optional[float]:
    return registry.gauge("karpenter_pods_pending_age_seconds")


def _verdict_mismatches(registry: Registry) -> Optional[float]:
    return registry.counter("karpenter_consolidation_verdict_mismatch_total")


def _circuits_open(registry: Registry) -> Optional[float]:
    """Count of cloud APIs whose circuit breaker is OPEN (state 2) right
    now; HALF_OPEN probes count as recovering, not violating."""
    series = registry.gauges.get("karpenter_cloud_api_circuit_state")
    if series is None:
        return 0.0
    return float(sum(1 for v in series.values() if v >= 2.0))


def _compile_cache_hit_rate(registry: Registry) -> Optional[float]:
    """Lifetime hit rate across consumers; None until the sample is big
    enough to mean anything (a cold process always starts with misses)."""
    hits = sum(
        registry.counters.get(
            "karpenter_solver_compile_cache_hits_total", {}
        ).values()
    )
    misses = sum(
        registry.counters.get(
            "karpenter_solver_compile_cache_misses_total", {}
        ).values()
    )
    total = hits + misses
    if total < 20:
        return None
    return hits / total


def _provider_staleness_max(registry: Registry) -> Optional[float]:
    return _gauge_family_max(registry, "karpenter_provider_cache_stale_seconds")


SIGNALS: Dict[str, Callable[[Registry], Optional[float]]] = {
    "tick_duration_p99": _tick_duration_p99,
    "pending_pod_age_max": _pending_pod_age_max,
    "verdict_mismatches": _verdict_mismatches,
    "circuits_open": _circuits_open,
    "compile_cache_hit_rate": _compile_cache_hit_rate,
    "provider_staleness_max": _provider_staleness_max,
}


# ------------------------------------------------------------------- rules
@dataclass
class SLORule:
    """One declarative rule: signal OP threshold may hold for at most
    ``budget`` of the time, judged over a fast (paging) and a slow
    (confirming) window."""

    name: str
    signal: str  # key into SIGNALS
    threshold: float
    op: str = ">"  # violation when `signal op threshold` ('<' for floors)
    budget: float = 0.1
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    enabled: bool = True
    description: str = ""

    def violated(self, value: float) -> bool:
        if self.op == ">":
            return value > self.threshold
        if self.op == "<":
            return value < self.threshold
        raise ValueError(f"rule {self.name}: unknown op {self.op!r}")


# defaults: the production signal set the ISSUE names.  Budgets/windows
# are deliberately conservative — alerts should be rare and credible.
DEFAULT_RULES: Dict[str, dict] = {
    "tick-duration-p99": dict(
        signal="tick_duration_p99", threshold=1.0, op=">", budget=0.1,
        fast_window_s=60.0, slow_window_s=300.0,
        description="reconcile tick p99 wall time must stay under 1s",
    ),
    "pending-pod-age": dict(
        signal="pending_pod_age_max", threshold=300.0, op=">", budget=0.1,
        fast_window_s=60.0, slow_window_s=300.0,
        description="no pod may sit pending un-nominated for 5 minutes",
    ),
    "verdict-mismatch": dict(
        signal="verdict_mismatches", threshold=0.0, op=">", budget=0.0,
        fast_window_s=60.0, slow_window_s=300.0,
        description="batched consolidation verdicts must never disagree "
        "with the sequential oracle",
    ),
    "cloud-circuit-open": dict(
        signal="circuits_open", threshold=0.0, op=">", budget=0.05,
        fast_window_s=60.0, slow_window_s=300.0,
        description="cloud-API circuit breakers may be open at most 5% "
        "of the time",
    ),
    "compile-cache-hit-rate": dict(
        signal="compile_cache_hit_rate", threshold=0.5, op="<", budget=0.25,
        fast_window_s=120.0, slow_window_s=600.0,
        description="a warm cluster's solver compile cache should mostly "
        "hit; a sustained miss storm means in-place mutation or catalog "
        "churn",
    ),
    "provider-staleness": dict(
        signal="provider_staleness_max", threshold=600.0, op=">", budget=0.1,
        fast_window_s=120.0, slow_window_s=600.0,
        description="degraded providers may serve last-good data, but not "
        "10-minute-old data for long",
    ),
}


def default_rules(settings=None) -> List[SLORule]:
    """The default rule set with ``settings.slo_rules`` overrides merged
    in: ``{rule-name: {threshold|budget|fast_window_s|slow_window_s|
    enabled|op|signal|description: ...}}``.  Overriding an unknown rule
    name CREATES a rule and must therefore carry ``signal``; naming an
    unknown signal is an error either way."""
    overrides: Dict[str, dict] = dict(getattr(settings, "slo_rules", {}) or {})
    rules: List[SLORule] = []
    for name, kw in DEFAULT_RULES.items():
        merged = {**kw, **overrides.pop(name, {})}
        rules.append(SLORule(name=name, **merged))
    for name, kw in sorted(overrides.items()):
        if "signal" not in kw:
            raise ValueError(
                f"slo rule {name!r} is not a default rule, so its override "
                "must name a signal"
            )
        kw = dict(kw)
        if "threshold" not in kw:
            raise ValueError(f"slo rule {name!r} needs a threshold")
        rules.append(SLORule(name=name, **kw))
    for rule in rules:
        if rule.signal not in SIGNALS:
            raise ValueError(
                f"slo rule {rule.name!r}: unknown signal {rule.signal!r} "
                f"(have {sorted(SIGNALS)})"
            )
        if not (0.0 <= rule.budget <= 1.0):
            raise ValueError(f"slo rule {rule.name!r}: budget must be in [0,1]")
        if rule.fast_window_s <= 0 or rule.slow_window_s < rule.fast_window_s:
            raise ValueError(
                f"slo rule {rule.name!r}: need slow_window_s >= "
                "fast_window_s > 0"
            )
    return rules


# ------------------------------------------------------------------ engine
@dataclass
class _RuleState:
    # (ts, dt_covered, violating) samples, oldest first, pruned to the
    # slow window; dt is the interval since the previous evaluation, so
    # jittered tick cadences weight correctly
    history: List[Tuple[float, float, bool]] = field(default_factory=list)
    last_eval: Optional[float] = None
    breached: bool = False
    breached_at: float = 0.0
    breaches: int = 0
    recoveries: int = 0
    breached_total_s: float = 0.0


class SLOEngine:
    """Evaluates a rule set once per reconcile tick.  Deterministic by
    construction: state advances only on `evaluate()`, timestamps come
    from the injected clock, signals read the registry."""

    def __init__(
        self,
        registry: Registry,
        clock: Clock,
        rules: Optional[List[SLORule]] = None,
    ):
        self.registry = registry
        self.clock = clock
        self.rules: List[SLORule] = list(rules or [])
        self._states: Dict[str, _RuleState] = {}

    def replace_rules(self, rules: List[SLORule]) -> None:
        """Swap the rule set and drop accumulated state (the simulator
        installs scenario-declared rules on a fresh operator)."""
        self.rules = list(rules)
        self._states.clear()

    # ------------------------------------------------------------- evaluate
    def _burn(
        self, rule: SLORule, state: _RuleState, now: float, window_s: float
    ) -> float:
        """Burn = (violating time / WINDOW SPAN) / budget.  Normalizing
        by the window, not by covered history, is what lets the slow
        window actually confirm: a freshly (re)started engine has seen
        only seconds of history, and dividing by that sliver would
        saturate both windows on the first violating tick — paging
        instantly on what may be a blip, exactly what multi-window burn
        rates exist to prevent.  Short history therefore UNDER-counts,
        which errs toward credible alerts."""
        lo = now - window_s
        violating = 0.0
        for ts, dt, bad in state.history:
            if not bad:
                continue
            # the sample's interval is (ts - dt, ts]; clip to the window
            overlap = min(ts, now) - max(ts - dt, lo)
            if overlap > 0.0:
                violating += overlap
        if rule.budget <= 0.0:
            # zero tolerance: any violating time in the window — or a
            # zero-duration first sample violating right now — pages
            last = state.history[-1] if state.history else None
            instant_bad = last is not None and last[2] and last[0] >= lo
            return BURN_CAP if (violating > 0.0 or instant_bad) else 0.0
        return min(BURN_CAP, violating / window_s / rule.budget)

    def evaluate(self) -> List[str]:
        """One evaluation pass over every enabled rule; returns the names
        of rules that NEWLY breached this pass (the operator's flight
        recorder dumps on a non-empty return)."""
        now = self.clock.now()
        newly_breached: List[str] = []
        for rule in self.rules:
            if not rule.enabled:
                continue
            value = SIGNALS[rule.signal](self.registry)
            state = self._states.setdefault(rule.name, _RuleState())
            if value is None:
                # no data yet: the rule cannot be judged; advance the
                # eval mark so a later first sample doesn't claim hours
                state.last_eval = now
                continue
            bad = rule.violated(value)
            dt = now - state.last_eval if state.last_eval is not None else 0.0
            state.history.append((now, max(0.0, dt), bad))
            state.last_eval = now
            lo = now - rule.slow_window_s
            while state.history and state.history[0][0] < lo:
                state.history.pop(0)
            fast = self._burn(rule, state, now, rule.fast_window_s)
            slow = self._burn(rule, state, now, rule.slow_window_s)
            if state.breached:
                state.breached_total_s += max(0.0, dt)
            self.registry.set(
                "karpenter_slo_burn_rate", round(fast, 6),
                {"rule": rule.name, "window": "fast"},
            )
            self.registry.set(
                "karpenter_slo_burn_rate", round(slow, 6),
                {"rule": rule.name, "window": "slow"},
            )
            if not state.breached and fast >= 1.0 and slow >= 1.0:
                state.breached = True
                state.breached_at = now
                state.breaches += 1
                newly_breached.append(rule.name)
                self.registry.inc(
                    "karpenter_slo_breaches_total", {"rule": rule.name}
                )
                self.registry.event(
                    "SLOBreach",
                    rule=rule.name,
                    signal=rule.signal,
                    value=round(value, 6),
                    threshold=rule.threshold,
                    burn_fast=round(fast, 6),
                    burn_slow=round(slow, 6),
                )
            elif state.breached and fast < 1.0:
                state.breached = False
                state.recoveries += 1
                self.registry.event(
                    "SLORecovered",
                    rule=rule.name,
                    signal=rule.signal,
                    value=round(value, 6),
                    breached_s=round(now - state.breached_at, 6),
                )
            self.registry.set(
                "karpenter_slo_status",
                1.0 if state.breached else 0.0,
                {"rule": rule.name},
            )
        return newly_breached

    # --------------------------------------------------------------- report
    def report(self) -> dict:
        """Deterministic per-rule summary for the sim's SLO report: breach
        and recovery counts, final status, total time spent breached."""
        rules = {}
        for rule in self.rules:
            state = self._states.get(rule.name, _RuleState())
            rules[rule.name] = {
                "breaches": state.breaches,
                "recoveries": state.recoveries,
                "status": "breached" if state.breached else "ok",
                "breached_s": round(state.breached_total_s, 6),
            }
        return {"rules": rules}
