"""Always-on flight recorder: the last N ticks' full context, on a ring.

When an SLO burns or a controller crashes, the dashboards show *that* it
happened; the flight recorder preserves *what was going on* — per tick:

- the tick's identity (seq, trace ID, injected-clock timestamp, wall
  duration),
- a cluster summary (pending / nodes / claims / running instances),
- the ledger slice: every decision event emitted during the tick (with
  a ``dropped`` count if the ring overflowed between records),
- the span slice: spans stamped with the tick's trace ID (empty unless
  profiling is enabled — the recorder itself never turns the tracer on),
- metric deltas: every counter that moved this tick, and per-series
  (count, sum) deltas for the latency histograms — which is exactly the
  per-phase self-time spent THIS tick, the series ``doctor`` baselines.

The ring is bounded (``Settings.flight_ticks``) and recording costs one
registry snapshot diff per tick — cheap enough to stay always-on, like
the event ledger.  Dumps are JSONL (header line ``{"t": "flight"}``,
then one ``{"t": "ftick"}`` line per tick) written on SLOBreach,
controller crash, or SIGUSR1, served live at ``/debug/flight``
(obs/http.py), rendered by ``python -m karpenter_tpu obs`` into
Perfetto-loadable Chrome-trace JSON (obs/render.py), and diagnosed by
``python -m karpenter_tpu doctor`` (obs/doctor.py).
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.metrics.registry import Registry
from karpenter_tpu.utils.clock import Clock
from karpenter_tpu.analysis.sanitizer import make_lock

FLIGHT_VERSION = 1

# default ring depth: ~a minute of 1s ticks, enough to bracket a breach
DEFAULT_TICKS = 64

# histogram families whose per-tick (count, sum) deltas are recorded —
# the per-phase latency anatomy doctor baselines
DELTA_HISTOGRAMS = (
    "karpenter_solver_phase_seconds",
    "karpenter_consolidation_phase_seconds",
    "karpenter_consolidation_search_phase_seconds",
    "karpenter_reconcile_tick_duration_seconds",
    "karpenter_provisioner_scheduling_duration_seconds",
    # device observatory (obs/device.py): per-tick compile time and the
    # resident scatter sizes the doctor's transfer rule normalizes by
    "karpenter_device_compile_seconds",
    "karpenter_solver_resident_delta_rows",
    # store plane (docs/designs/store-scale.md): the operator's per-RPC
    # store latency, so a flight dump brackets store slowness next to
    # the solver phases it stalls
    "karpenter_store_rpc_seconds",
    # solver service (docs/designs/solver-service.md): per-tenant
    # solve-wait — the doctor's tenant-starvation rule reads these
    # tenant-labeled deltas out of a service flight dump
    "karpenter_service_solve_wait_seconds",
)


def _series_key(name: str, labels: Tuple) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in labels)
    return f"{name}{{{inner}}}"


class FlightRecorder:
    def __init__(
        self,
        clock: Clock,
        registry: Registry,
        ledger=None,
        tracer=None,
        capacity: int = DEFAULT_TICKS,
    ):
        self.clock = clock
        self.registry = registry
        self.ledger = ledger
        self.tracer = tracer
        self._lock = make_lock("FlightRecorder._lock")
        self._ring: deque = deque(maxlen=max(1, capacity))
        self._led_cursor = 0
        self._counters: Dict[Tuple[str, Tuple], float] = {}
        self._hists: Dict[Tuple[str, Tuple], Tuple[int, float]] = {}

    # -------------------------------------------------------------- capture
    def _counter_deltas(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        with self.registry._lock:
            for name, series in self.registry.counters.items():
                for labels, v in series.items():
                    key = (name, labels)
                    prev = self._counters.get(key, 0.0)
                    if v != prev:
                        out[_series_key(name, labels)] = round(v - prev, 9)
                        self._counters[key] = v
        return out

    def _hist_deltas(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        with self.registry._lock:
            for name in DELTA_HISTOGRAMS:
                for labels, h in self.registry.histograms.get(name, {}).items():
                    key = (name, labels)
                    pc, ps = self._hists.get(key, (0, 0.0))
                    if h.count != pc:
                        out[_series_key(name, labels)] = {
                            "count": h.count - pc,
                            "sum_s": round(h.total - ps, 9),
                        }
                        self._hists[key] = (h.count, h.total)
        return out

    def record(
        self,
        seq: int,
        trace_id: str,
        duration_s: float,
        summary: Optional[dict] = None,
        device: Optional[dict] = None,
    ) -> dict:
        """Capture one tick's context into the ring (the operator calls
        this at the end of every reconcile tick).  ``device`` is the
        observatory's per-tick section (obs/device.py tick_section):
        compiles / warm recompiles / transfer bytes this tick plus the
        current resident footprint — what the doctor's device rules
        read."""
        events: List[dict] = []
        dropped = 0
        if self.ledger is not None:
            evs, dropped = self.ledger.read(self._led_cursor)
            if evs:
                self._led_cursor = evs[-1].seq
            events = [ev.to_dict() for ev in evs]
        spans: List[dict] = []
        if self.tracer is not None and trace_id:
            spans = [
                {
                    "path": s.path,
                    "start_s": s.start_s,
                    "duration_s": s.duration_s,
                    "meta": s.meta,
                }
                for s in self.tracer.recent(4096)
                if s.trace_id == trace_id
            ]
        entry = {
            "t": "ftick",
            "seq": seq,
            "trace_id": trace_id,
            "ts": self.clock.now(),
            "dur_s": round(duration_s, 9),
            "summary": dict(summary or {}),
            "events": events,
            "dropped_events": dropped,
            "spans": spans,
            "counters": self._counter_deltas(),
            "hists": self._hist_deltas(),
            "device": dict(device or {}),
        }
        with self._lock:
            self._ring.append(entry)
        return entry

    # ----------------------------------------------------------------- dump
    def dump_lines(self, trigger: str = "manual") -> List[str]:
        with self._lock:
            ticks = list(self._ring)
        header = {
            "t": "flight",
            "v": FLIGHT_VERSION,
            "trigger": trigger,
            "ticks": len(ticks),
            "dumped_ts": self.clock.now(),
        }
        return [json.dumps(header, sort_keys=True)] + [
            json.dumps(t, sort_keys=True) for t in ticks
        ]

    def dump(self, path: str, trigger: str = "manual") -> str:
        """Write the ring as JSONL; returns the path.  Counted per
        trigger so a dump storm is itself observable."""
        with open(path, "w") as f:
            f.write("\n".join(self.dump_lines(trigger)) + "\n")
        self.registry.inc(
            "karpenter_flight_dumps_total", {"trigger": trigger}
        )
        return path


# ------------------------------------------------------------------ loading
def read_flight(text: str) -> dict:
    """Parse a flight dump (JSONL text) -> {"meta": header, "ticks": [...]}.
    Raises ValueError on anything that is not a flight dump."""
    lines = [json.loads(ln) for ln in text.splitlines() if ln.strip()]
    if not lines or lines[0].get("t") != "flight":
        raise ValueError("not a flight dump (no {'t': 'flight'} header line)")
    return {
        "meta": lines[0],
        "ticks": [ln for ln in lines[1:] if ln.get("t") == "ftick"],
    }


def load_flight(path: str) -> dict:
    with open(path) as f:
        return read_flight(f.read())
