"""``python -m karpenter_tpu doctor`` — from telemetry to diagnosis.

Input: a flight-recorder dump (obs/flight.py JSONL) or a live process
(``http://host:port`` — fetches ``/debug/flight``).  Output: a terminal
diagnosis that answers "why was that tick slow / why did that SLO burn"
without a human staring at dashboards:

1. **phases vs rolling baseline** — per-phase self-time per tick comes
   from the dump's histogram deltas; the last ticks are compared against
   the median of the earlier ones, and regressing phases are named;
2. **event timeline bracketing the breach** — the ledger slice around
   the first ``SLOBreach`` (or the tail of the dump when nothing
   breached), one line per decision event;
3. **rule-based suspected causes** — deterministic correlations over the
   dump: "CircuitOpen on CreateFleet preceded the provisioning stall",
   "compile-cache misses spiked after the catalog roll", restated
   ``AnomalyDetected`` attributions, and (with ``--bench``) regressed
   lines from a ``bench.py --compare-out`` verdict.

``diagnose()`` is the pure core (tests assert on its dict, not on
terminal text); ``main()`` is the CLI shell around it.
"""

from __future__ import annotations

import argparse
import json
import re
import statistics
import sys
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.obs.flight import load_flight, read_flight

# how many trailing ticks count as "recent" when no breach anchors the
# split (a breach splits the dump at its tick instead)
RECENT_TICKS = 8

# a phase regresses when its recent median exceeds twice its baseline
# median AND moves by an absolute floor (sub-ms wiggle is not a story)
REGRESSION_FACTOR = 2.0
REGRESSION_FLOOR_S = 0.005

_SERIES_RE = re.compile(r"^(?P<name>[a-z0-9_]+)(?:\{(?P<labels>.*)\})?$")

# histogram family -> short prefix used in phase keys ("solver/compile")
_FAMILY_SHORT = {
    "karpenter_solver_phase_seconds": "solver",
    "karpenter_consolidation_phase_seconds": "consolidation",
    "karpenter_consolidation_search_phase_seconds": "search",
    "karpenter_reconcile_tick_duration_seconds": "tick",
    "karpenter_provisioner_scheduling_duration_seconds": "scheduling",
    "karpenter_device_compile_seconds": "device_compile",
    "karpenter_store_rpc_seconds": "store_rpc",
    "karpenter_admission_latency_seconds": "admission",
}

# tenant-starvation thresholds (solver service): one tenant's mean
# solve-wait running this factor past the fleet median (with an
# absolute floor so microsecond jitter on an idle service never pages)
# means the weighted-round-robin share is not protecting it — a noisy
# neighbor is monopolizing the batch window or its weight is wrong
_STARVATION_FACTOR = 4.0
_STARVATION_FLOOR_S = 0.01
_STARVATION_MIN_SOLVES = 4

# device-rule thresholds: a warm tick's upload bytes must not grow past
# this factor of the baseline median (with an absolute floor so byte
# jitter on tiny problems never pages) while its resident delta rows
# stay flat — more bytes than the delta justifies means the warm path
# is silently re-uploading something it should have kept resident
_TRANSFER_FACTOR = 2.0
_TRANSFER_FLOOR_B = 4096
_ROWS_SLACK = 1.5


def _parse_series(key: str) -> Tuple[str, Dict[str, str]]:
    m = _SERIES_RE.match(key)
    if m is None:
        return key, {}
    labels = {}
    for pair in (m.group("labels") or "").split(","):
        if "=" in pair:
            k, _, v = pair.partition("=")
            labels[k] = v
    return m.group("name"), labels


def _median(values: List[float]) -> float:
    return statistics.median(values) if values else 0.0


# ----------------------------------------------------------------- analysis
def phase_series(ticks: List[dict]) -> Dict[str, List[float]]:
    """phase key ("solver/compile") -> per-tick self-time seconds, one
    entry per tick (0.0 on ticks where the phase did not run)."""
    out: Dict[str, List[float]] = {}
    for i, tick in enumerate(ticks):
        for key, delta in tick.get("hists", {}).items():
            name, labels = _parse_series(key)
            short = _FAMILY_SHORT.get(name)
            if short is None:
                continue
            # device compile series label per jit function, not phase
            phase = labels.get("phase", "") or labels.get("fn", "")
            pkey = f"{short}/{phase}" if phase else short
            series = out.setdefault(pkey, [0.0] * len(ticks))
            series[i] += float(delta.get("sum_s", 0.0))
    return out


def device_sections(ticks: List[dict]) -> List[dict]:
    """Per-tick ``device`` sections (obs/device.py tick_section; empty
    dicts for dumps predating the observatory)."""
    return [tick.get("device") or {} for tick in ticks]


def resident_delta_rows(ticks: List[dict]) -> List[float]:
    """Per-tick resident scatter rows (the delta-size the transfer rule
    normalizes upload bytes by), from the flight hist deltas."""
    out = []
    for tick in ticks:
        delta = tick.get("hists", {}).get(
            "karpenter_solver_resident_delta_rows", {}
        )
        out.append(float(delta.get("sum_s", 0.0)))
    return out


def ledger_events(ticks: List[dict]) -> List[Tuple[int, dict]]:
    """(tick index, event) pairs in emission order."""
    out = []
    for i, tick in enumerate(ticks):
        for ev in tick.get("events", []):
            out.append((i, ev))
    return out


def counter_deltas(ticks: List[dict], family: str) -> List[float]:
    """Per-tick delta of one counter family summed over its series."""
    out = []
    for tick in ticks:
        total = 0.0
        for key, delta in tick.get("counters", {}).items():
            name, _ = _parse_series(key)
            if name == family:
                total += float(delta)
        out.append(total)
    return out


def tenant_wait_stats(ticks: List[dict]) -> Dict[str, Tuple[int, float]]:
    """tenant -> (solves, total wait seconds) aggregated over the dump,
    from the solver service's per-tenant solve-wait histogram deltas."""
    out: Dict[str, Tuple[int, float]] = {}
    for tick in ticks:
        for key, delta in tick.get("hists", {}).items():
            name, labels = _parse_series(key)
            if name != "karpenter_service_solve_wait_seconds":
                continue
            tenant = labels.get("tenant", "?")
            c, s = out.get(tenant, (0, 0.0))
            out[tenant] = (
                c + int(delta.get("count", 0)),
                s + float(delta.get("sum_s", 0.0)),
            )
    return out


def _split_index(ticks: List[dict], events) -> int:
    """Where baseline ends and "recent" begins: the first SLOBreach's
    tick when one exists, else the last RECENT_TICKS."""
    for i, ev in events:
        if ev.get("type") == "SLOBreach":
            return max(1, i)
    return max(1, len(ticks) - RECENT_TICKS)


def phase_analysis(ticks: List[dict], split: int) -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    for pkey, series in sorted(phase_series(ticks).items()):
        base = _median(series[:split])
        recent = _median(series[split:])
        regressing = (
            recent > base * REGRESSION_FACTOR
            and recent - base > REGRESSION_FLOOR_S
        )
        out[pkey] = {
            "baseline_ms": round(base * 1000.0, 3),
            "recent_ms": round(recent * 1000.0, 3),
            "ratio": round(recent / base, 2) if base > 0 else None,
            "regressing": regressing,
        }
    return out


# ------------------------------------------------------- suspected causes
def suspected_causes(
    ticks: List[dict],
    events: List[Tuple[int, dict]],
    phases: Dict[str, dict],
    bench_verdict: Optional[dict] = None,
    split: Optional[int] = None,
) -> List[str]:
    causes: List[str] = []
    regressing = [k for k, p in phases.items() if p["regressing"]]
    breaches = [(i, ev) for i, ev in events if ev.get("type") == "SLOBreach"]
    if split is None:
        split = _split_index(ticks, events)

    # catalog roll -> compile-cache miss storm -> compile-phase blowup
    rolls = [(i, ev) for i, ev in events if ev.get("type") == "CatalogRolled"]
    if rolls:
        i, roll = rolls[0]
        misses = counter_deltas(
            ticks, "karpenter_solver_compile_cache_misses_total"
        )
        # the roll tick's own misses belong to the roll: the invalidation
        # happens mid-tick, before that tick's solves recompile
        before, after = sum(misses[:i]), sum(misses[i:])
        if after > before:
            msg = (
                f"compile-cache misses spiked after the catalog roll "
                f"(CatalogRolled seq {roll.get('seq')}, tick "
                f"{roll.get('trace_id') or i}): {int(after)} misses after "
                f"vs {int(before)} before"
            )
            compile_keys = [k for k in regressing if k.endswith("/compile")]
            if compile_keys:
                k = compile_keys[0]
                p = phases[k]
                msg += (
                    f"; phase '{k}' regressed to {p['recent_ms']}ms "
                    f"(baseline {p['baseline_ms']}ms)"
                )
            causes.append(msg)

    # circuit open -> provisioning stall
    opens = [(i, ev) for i, ev in events if ev.get("type") == "CircuitOpen"]
    if opens:
        pending = [t.get("summary", {}).get("pending", 0) for t in ticks]
        i, op_ev = opens[0]
        stalled = (
            max(pending[i:], default=0) > max(pending[:i], default=0)
            or any(
                bev.get("attrs", {}).get("rule") == "pending-pod-age"
                for _, bev in breaches
            )
        )
        if stalled:
            causes.append(
                f"CircuitOpen on {op_ev.get('attrs', {}).get('api', '?')} "
                f"(seq {op_ev.get('seq')}) preceded a provisioning stall: "
                f"pending peaked at {max(pending[i:], default=0)} afterwards"
            )

    # shard stuck in migration: a store shard raised its export fence
    # (migration begun) but the coordinator never confirmed the drop
    # (migration committed) — the shard is serving with a rotated epoch
    # and keys that may already live at their new owner.  Matched
    # per-shard so a commit on one shard cannot mask a stall on another.
    begun: Dict[str, float] = {}
    committed: Dict[str, float] = {}
    last_begun_tick: Dict[str, int] = {}
    for i, tick in enumerate(ticks):
        for key, delta in tick.get("counters", {}).items():
            name, labels = _parse_series(key)
            shard = labels.get("shard", "?")
            if name == "karpenter_store_shard_migration_begun_total":
                begun[shard] = begun.get(shard, 0.0) + float(delta)
                last_begun_tick[shard] = i
            elif name == "karpenter_store_shard_migration_committed_total":
                committed[shard] = committed.get(shard, 0.0) + float(delta)
    for shard in sorted(begun):
        pending_migrations = begun[shard] - committed.get(shard, 0.0)
        if pending_migrations > 0:
            causes.append(
                f"store shard {shard} stuck in migration: "
                f"{int(begun[shard])} migration(s) begun but only "
                f"{int(committed.get(shard, 0.0))} committed (last begun "
                f"at tick {last_begun_tick[shard]}) — its export fence "
                "rotated the epoch but the key drop never landed; "
                "re-run the reshard or restore the old topology"
            )

    # ---- device observatory rules (obs/device.py tick sections) -------
    dev = device_sections(ticks)
    compiles = [int(d.get("compiles", 0) or 0) for d in dev]
    warm = [int(d.get("warm_recompiles", 0) or 0) for d in dev]

    # device recompile storm: XLA compile activity concentrated AFTER a
    # catalog roll — the device-layer twin of the compile-cache-miss
    # rule above (the roll obsoletes the resident tensors and the padded
    # shapes, so every jit entry point retraces)
    storm_named = False
    if rolls and any(compiles):
        i, roll = rolls[0]
        before, after = sum(compiles[:i]), sum(compiles[i:])
        if after > before:
            storm_named = True
            msg = (
                f"device recompile storm after the catalog roll "
                f"(CatalogRolled seq {roll.get('seq')}, tick "
                f"{roll.get('trace_id') or i}): {after} device compile(s) "
                f"in the {len(ticks) - i} tick(s) after vs {before} before"
            )
            if sum(warm[i:]):
                msg += f", {sum(warm[i:])} on warm jit entry points"
            dc_keys = [
                k for k in regressing if k.startswith("device_compile")
            ]
            if dc_keys:
                p = phases[dc_keys[0]]
                msg += (
                    f"; compile time '{dc_keys[0]}' regressed to "
                    f"{p['recent_ms']}ms (baseline {p['baseline_ms']}ms)"
                )
            causes.append(msg)
    if sum(warm) and not storm_named:
        # warm recompiles the storm rule did NOT explain — either no
        # roll at all, or a roll with no compile spike behind it:
        # something is retracing on a steady cluster (bucket churn, a
        # donation falling through)
        first = next(i for i, w in enumerate(warm) if w)
        causes.append(
            f"{sum(warm)} warm-tick device recompile(s) not explained "
            f"by a catalog roll (first at tick {first}): a jit entry "
            "point is retracing on a steady cluster — look for padded-"
            "bucket churn or a failed buffer donation"
        )

    # transfer regression: warm ticks uploading more than their resident
    # delta rows justify — the warm path's contract is that a tick ships
    # only its scatter payloads (docs/designs/observability.md §device)
    xfer = [int(d.get("transfer_bytes", 0) or 0) for d in dev]
    if any(xfer):
        rows = resident_delta_rows(ticks)
        base_b, rec_b = _median(xfer[:split]), _median(xfer[split:])
        base_r, rec_r = _median(rows[:split]), _median(rows[split:])
        if (
            rec_b > base_b * _TRANSFER_FACTOR
            and rec_b - base_b > _TRANSFER_FLOOR_B
            and rec_r <= base_r * _ROWS_SLACK + 1.0
        ):
            causes.append(
                f"warm-tick transfer regression: ticks past the split "
                f"upload a median {int(rec_b)}B vs {int(base_b)}B "
                f"baseline while resident delta rows stayed flat "
                f"({base_r:g} -> {rec_r:g}) — the uploads are not "
                "justified by the cluster delta"
            )

    # ---- admission fast path rules (controllers/provisioning.py) ------
    # fallback storm: the single-pod fast path declining at a spiking
    # rate after the split — every decline re-routes an arrival through
    # the batched solve (latency regression for exactly the traffic the
    # fast path exists for), and the dominant reason names the trigger
    # (catalog_roll -> resident tensors obsoleted; resident_miss ->
    # delta planner churn; pod_shape -> the workload stopped being plain)
    fb_per_tick = [0.0] * len(ticks)
    fb_reasons: Dict[str, float] = {}
    fp_mismatches = 0.0
    for i, tick in enumerate(ticks):
        for key, delta in tick.get("counters", {}).items():
            name, labels = _parse_series(key)
            if name == "karpenter_admission_fastpath_fallback_total":
                fb_per_tick[i] += float(delta)
                reason = labels.get("reason", "?")
                fb_reasons[reason] = fb_reasons.get(reason, 0.0) + float(delta)
            elif name == "karpenter_admission_fastpath_mismatch_total":
                fp_mismatches += float(delta)
    fb_before, fb_after = sum(fb_per_tick[:split]), sum(fb_per_tick[split:])
    if fb_after > fb_before and fb_after >= 4:
        top = max(fb_reasons, key=fb_reasons.get) if fb_reasons else "?"
        causes.append(
            f"admission fast-path fallback storm: {int(fb_after)} "
            f"fallback(s) in the {len(ticks) - split} tick(s) after the "
            f"split vs {int(fb_before)} before — single-pod arrivals are "
            f"re-routing through the batched solve; dominant reason "
            f"'{top}' ({int(fb_reasons.get(top, 0))} of "
            f"{int(sum(fb_reasons.values()))})"
        )
    if fp_mismatches:
        causes.append(
            f"{int(fp_mismatches)} admission fast-path verdict "
            "mismatch(es): the admit dispatch disagreed with the "
            "sequential host oracle — the convergence contract requires "
            "this counter to stay 0; no pod was nominated off the "
            "disagreeing verdict, but the device arithmetic (or the "
            "resident mirrors) has drifted and needs a bug hunt"
        )

    # ---- solver service rules (service/server.py) ---------------------
    # tenant starvation: one tenant's mean solve-wait running far past
    # the fleet median — the weighted-round-robin share is not
    # protecting it (noisy neighbor monopolizing the coalesce window,
    # or a misconfigured weight); refusal counts name the backpressure
    # the starved tenant also ate
    waits = tenant_wait_stats(ticks)
    means = {
        t: s / c
        for t, (c, s) in waits.items()
        if c >= _STARVATION_MIN_SOLVES
    }
    if len(means) >= 2:
        fleet_median = _median(sorted(means.values()))
        refusals: Dict[str, float] = {}
        for tick in ticks:
            for key, delta in tick.get("counters", {}).items():
                name, labels = _parse_series(key)
                if name == "karpenter_service_refusals_total":
                    t = labels.get("tenant", "?")
                    refusals[t] = refusals.get(t, 0.0) + float(delta)
        for t in sorted(means):
            mean = means[t]
            if (
                mean > fleet_median * _STARVATION_FACTOR
                and mean - fleet_median > _STARVATION_FLOOR_S
            ):
                msg = (
                    f"tenant '{t}' starving in the solver service: mean "
                    f"solve-wait {mean * 1000.0:.1f}ms over "
                    f"{waits[t][0]} solve(s) vs fleet median "
                    f"{fleet_median * 1000.0:.1f}ms "
                    f"({mean / fleet_median:.1f}x) — check its "
                    "round-robin weight and the noisy neighbors "
                    "sharing its batch bucket"
                )
                if refusals.get(t):
                    msg += (
                        f"; it also ate {int(refusals[t])} "
                        "backpressure refusal(s)"
                    )
                causes.append(msg)

    # warm-recompile attributions are causes by construction
    for i, ev in events:
        if ev.get("type") == "DeviceRecompile":
            a = ev.get("attrs", {})
            causes.append(
                f"warm recompile of device fn '{a.get('fn', '?')}' at "
                f"tick {i} ({a.get('compile_s')}s of compile time on the "
                "hot path)"
            )

    # anomaly attributions are causes by construction
    for _, ev in events:
        if ev.get("type") == "AnomalyDetected":
            a = ev.get("attrs", {})
            causes.append(
                f"anomaly in {a.get('series', '?')} phase "
                f"'{a.get('phase', '')}': observed {a.get('observed_s')}s vs "
                f"baseline {a.get('baseline_s')}s ({a.get('magnitude')}x)"
            )

    # any regressing phase not already blamed gets named on its own
    blamed = " ".join(causes)
    for k in regressing:
        if f"'{k}'" not in blamed:
            p = phases[k]
            causes.append(
                f"phase '{k}' regressed: {p['recent_ms']}ms recent vs "
                f"{p['baseline_ms']}ms baseline"
            )

    if bench_verdict and bench_verdict.get("regressed"):
        causes.append(
            "bench --compare flagged regressions: "
            + ", ".join(bench_verdict["regressed"])
        )
    return causes


# ---------------------------------------------------------------- diagnose
def diagnose(
    flight: dict, bench_verdict: Optional[dict] = None
) -> dict:
    ticks = flight["ticks"]
    events = ledger_events(ticks)
    split = _split_index(ticks, events)
    phases = phase_analysis(ticks, split)
    breaches = [ev for _, ev in events if ev.get("type") == "SLOBreach"]
    recoveries = [ev for _, ev in events if ev.get("type") == "SLORecovered"]
    # the timeline brackets the first breach: everything from a few ticks
    # before it through the end of the dump (or the whole tail of a
    # breach-free dump)
    lo = max(0, split - 4)
    timeline = [
        {"tick": i, **ev} for i, ev in events if i >= lo
    ]
    return {
        "meta": flight["meta"],
        "ticks": len(ticks),
        "split_tick": split,
        "breaches": breaches,
        "recoveries": recoveries,
        "phases": phases,
        "regressing_phases": [
            k for k, p in phases.items() if p["regressing"]
        ],
        "device": {
            "compiles": sum(
                int(d.get("compiles", 0) or 0) for d in device_sections(ticks)
            ),
            "warm_recompiles": sum(
                int(d.get("warm_recompiles", 0) or 0)
                for d in device_sections(ticks)
            ),
            "transfer_bytes": sum(
                int(d.get("transfer_bytes", 0) or 0)
                for d in device_sections(ticks)
            ),
            "resident_bytes_final": int(
                (device_sections(ticks)[-1] if ticks else {}).get(
                    "resident_bytes", 0
                )
                or 0
            ),
        },
        "timeline": timeline,
        "suspected_causes": suspected_causes(
            ticks, events, phases, bench_verdict, split=split
        ),
    }


def render_diagnosis(diag: dict) -> str:
    out: List[str] = []
    meta = diag["meta"]
    out.append(
        f"flight dump: {diag['ticks']} tick(s), trigger="
        f"{meta.get('trigger', '?')}, dumped_ts={meta.get('dumped_ts')}"
    )
    out.append(
        f"SLO breaches: {len(diag['breaches'])}, recoveries: "
        f"{len(diag['recoveries'])}"
    )
    dev = diag.get("device") or {}
    if any(dev.values()):
        out.append(
            f"device: {dev.get('compiles', 0)} compile(s) "
            f"({dev.get('warm_recompiles', 0)} warm), "
            f"{dev.get('transfer_bytes', 0)}B uploaded, "
            f"{dev.get('resident_bytes_final', 0)}B resident at dump time"
        )
    out.append("")
    out.append("phases vs rolling baseline (recent = ticks past the "
               f"split at tick {diag['split_tick']}):")
    out.append(
        f"  {'phase':32s} {'baseline_ms':>12s} {'recent_ms':>10s} "
        f"{'ratio':>6s}"
    )
    for pkey, p in diag["phases"].items():
        flag = "  << REGRESSING" if p["regressing"] else ""
        ratio = f"{p['ratio']:.2f}" if p["ratio"] is not None else "-"
        out.append(
            f"  {pkey:32s} {p['baseline_ms']:12.3f} {p['recent_ms']:10.3f} "
            f"{ratio:>6s}{flag}"
        )
    out.append("")
    out.append("event timeline bracketing the breach:")
    if not diag["timeline"]:
        out.append("  (no ledger events in the dump)")
    for ev in diag["timeline"][-40:]:
        attrs = " ".join(
            f"{k}={v}" for k, v in sorted(ev.get("attrs", {}).items())
        )
        out.append(
            f"  tick {ev['tick']:>4d}  seq {ev.get('seq', '?'):>5}  "
            f"{ev.get('type', '?'):18s} {attrs}"
        )
    out.append("")
    out.append("suspected causes:")
    if diag["suspected_causes"]:
        for cause in diag["suspected_causes"]:
            out.append(f"  - {cause}")
    else:
        out.append("  - none: no regressing phase, breach, or correlated "
                   "event in this dump")
    return "\n".join(out)


# --------------------------------------------------------------------- CLI
def _fetch_flight(base_url: str) -> dict:
    import urllib.request

    url = base_url.rstrip("/") + "/debug/flight"
    with urllib.request.urlopen(url, timeout=10) as resp:
        text = resp.read().decode()
    if not text.strip():
        raise ValueError(
            f"{url} returned an empty body (no flight recorder attached?)"
        )
    return read_flight(text)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m karpenter_tpu doctor",
        description="correlate a flight dump (or a live /debug/flight "
        "endpoint) into a terminal diagnosis: phases vs baseline, the "
        "event timeline around the breach, and suspected causes",
    )
    parser.add_argument(
        "input",
        help="a flight dump JSONL (flight-<trace>.jsonl) or a live "
        "process base URL (http://host:port)",
    )
    parser.add_argument(
        "--bench",
        default="",
        metavar="VERDICT.json",
        help="a `bench.py --compare-out` verdict to fold into the "
        "suspected-causes section",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="emit the diagnosis as JSON instead of the terminal report",
    )
    args = parser.parse_args(argv)

    try:
        if args.input.startswith(("http://", "https://")):
            flight = _fetch_flight(args.input)
        else:
            flight = load_flight(args.input)
        verdict = None
        if args.bench:
            with open(args.bench) as f:
                verdict = json.load(f)
    except (OSError, ValueError) as exc:
        print(f"doctor: {exc}", file=sys.stderr)
        return 64

    diag = diagnose(flight, bench_verdict=verdict)
    if args.json:
        print(json.dumps(diag, indent=2, sort_keys=True))
    else:
        print(render_diagnosis(diag))
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
