"""Mesh sharding for the solver (data = node slots, model = config catalog)."""

from karpenter_tpu.parallel.mesh import (
    assemble_feasibility,
    make_mesh,
    sharded_solve_step,
)

__all__ = ["assemble_feasibility", "make_mesh", "sharded_solve_step"]
