"""Device-mesh sharding for the scheduling solver.

The reference scales its solve by batching windows and per-provisioner
serialization in one Go process (SURVEY.md §5: no distributed backend).
The TPU build instead shards the solve over a `jax.sharding.Mesh` and lets
XLA insert the collectives:

- axis **"data"**: the node-slot axis K — each device owns a shard of the
  open-bin state (residual usage, config commitments, per-signature
  counters).  The first-fit prefix allocation is a cumsum along K, which
  XLA SPMD lowers to an ICI collective prefix.
- axis **"model"**: the config axis C — the instance-type x zone x
  capacity-type catalog is partitioned like a sharded embedding table; the
  per-class argmin over C becomes an all-reduce.
- the **class axis G is the sequential dimension** (the `lax.scan` time
  axis) — the analogue of microbatched pipeline steps; it cannot be
  sharded, and doesn't need to be: per-step work is O(K·R + C·R).

The same mesh recipe runs on one chip (trivial mesh), an ICI-connected
slice, or CPU with `--xla_force_host_platform_device_count` for tests and
the driver's multi-chip dry run.

Why the SCAN kernel is the mesh backend (and not the fused Pallas
kernel under shard_map): the fused kernel keeps the whole slot/config
state VMEM-resident within one core — sharding it would force a manual
collective prefix over the slot axis between kernel invocations,
re-deriving exactly what XLA SPMD already emits for the scan kernel's
K-cumsum.  That price could only be worth paying if the fused kernel
held a material single-chip win, and the measured marginal per-solve
cost says it does not: bench.py's `device_ms` (chained dispatches, one
fetch — the tunnel's fixed RTT cancels) put the fused kernel at
parity-or-worse vs the scan kernel at the ~300-class bench shape
(BENCH_r05), which is also why auto_pack's single-chip dispatch
threshold sits at ~1k classes (ops/pallas_packer.py:PALLAS_MIN_CLASSES).
Both production shapes — the flagship AND the 300+-class heterogeneous
problem — are parity-asserted against the single-device kernel on every
driver dry run (__graft_entry__.dryrun_multichip).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from karpenter_tpu.ops.packer import PackResult, pack_kernel, pad_problem

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 2D (data, model) mesh over the first `n_devices` devices.

    Both axis sizes are POWERS OF TWO (devices beyond the largest
    power-of-two count are left out): the kernel's padded buckets are
    power-of-two sized, and a non-power-of-two axis could not evenly
    divide them.  Counts >= 2 split (n/2, 2) so both axes are exercised.
    """
    devices = jax.devices()
    n = n_devices if n_devices is not None else len(devices)
    n = min(n, len(devices))
    p2 = 1
    while p2 * 2 <= n:
        p2 *= 2
    devices = devices[:p2]
    shape = (p2 // 2, 2) if p2 >= 2 else (1, 1)
    return Mesh(np.array(devices).reshape(shape), (DATA_AXIS, MODEL_AXIS))


def assemble_feasibility(
    type_ok: jax.Array,  # [S, T] bool — signature x type admission
    zone_ok: jax.Array,  # [S, Z] bool
    ct_ok: jax.Array,  # [S, CT] bool
    sig_of: jax.Array,  # [G] int32 — class -> signature
    t_of: jax.Array,  # [C] int32 — config -> type index
    z_of: jax.Array,  # [C] int32
    ct_of: jax.Array,  # [C] int32
) -> jax.Array:
    """Expand factorized admission vectors into the dense [G, C] mask.

    This is the device-side counterpart of the numpy assembly in
    ops/tensorize.py — the O(G·C) part of constraint compilation, sharded
    G over "data" and C over "model" so each device materializes only its
    tile of the mask.
    """
    g_rows = type_ok[sig_of]  # [G, T]
    z_rows = zone_ok[sig_of]  # [G, Z]
    ct_rows = ct_ok[sig_of]  # [G, CT]
    return g_rows[:, t_of] & z_rows[:, z_of] & ct_rows[:, ct_of]


def sharded_solve_step(mesh: Mesh, k_slots: int):
    """Build the jitted, mesh-sharded full solve step.

    Returns ``step(type_ok, zone_ok, ct_ok, sig_of, t_of, z_of, ct_of,
    req, cnt, maxper, slot, alloc, price, openable, used0, cfg0, npods0,
    next0, sig0) -> PackResult`` — feasibility expansion followed by the
    packing scan, compiled once over the mesh with the shardings described
    in the module docstring.
    """
    repl = NamedSharding(mesh, P())
    on_c = NamedSharding(mesh, P(MODEL_AXIS))
    on_c2 = NamedSharding(mesh, P(MODEL_AXIS, None))
    on_k = NamedSharding(mesh, P(DATA_AXIS))
    on_k2 = NamedSharding(mesh, P(DATA_AXIS, None))
    on_g = NamedSharding(mesh, P(DATA_AXIS))
    on_sk = NamedSharding(mesh, P(None, DATA_AXIS))

    def step(
        type_ok, zone_ok, ct_ok, sig_of, t_of, z_of, ct_of,
        req, cnt, maxper, slot, alloc, price, openable,
        used0, cfg0, npods0, next0, sig0,
    ) -> PackResult:
        feas = assemble_feasibility(
            type_ok, zone_ok, ct_ok, sig_of, t_of, z_of, ct_of
        )
        return pack_kernel(
            req, cnt, maxper, slot, feas, alloc, price, openable,
            used0, cfg0, npods0, next0, sig0, k_slots=k_slots,
        )

    return jax.jit(
        step,
        in_shardings=(
            repl, repl, repl, on_g, on_c, on_c, on_c,  # admission + maps
            repl, repl, repl, repl,  # class tensors (scan xs)
            on_c2, on_c, on_c,  # catalog: alloc, price, openable
            on_k2, on_k, on_k, repl, on_sk,  # bin state
        ),
    )


# (mesh, k_slots, objective) -> jitted sharded pack; Mesh is hashable
_SHARDED_PACK_CACHE: dict = {}


def _sharded_pack(mesh: Mesh, k_slots: int, objective: str):
    key = (mesh, k_slots, objective)
    fn = _SHARDED_PACK_CACHE.get(key)
    if fn is not None:
        return fn
    repl = NamedSharding(mesh, P())
    on_c = NamedSharding(mesh, P(MODEL_AXIS))
    on_c2 = NamedSharding(mesh, P(MODEL_AXIS, None))
    on_gc = NamedSharding(mesh, P(None, MODEL_AXIS))
    on_k = NamedSharding(mesh, P(DATA_AXIS))
    on_k2 = NamedSharding(mesh, P(DATA_AXIS, None))
    on_sk = NamedSharding(mesh, P(None, DATA_AXIS))

    def step(
        req, cnt, maxper, slot, feas, alloc, price, openable,
        used0, cfg0, npods0, next0, sig0,
    ) -> PackResult:
        return pack_kernel(
            req, cnt, maxper, slot, feas, alloc, price, openable,
            used0, cfg0, npods0, next0, sig0,
            k_slots=k_slots, objective=objective,
        )

    fn = jax.jit(
        step,
        in_shardings=(
            repl, repl, repl, repl,  # class tensors (scan xs)
            on_gc,  # feas [G, C] — config axis sharded over "model"
            on_c2, on_c, on_c,  # catalog: alloc, price, openable
            on_k2, on_k, on_k, repl, on_sk,  # bin state over "data"
        ),
    )
    _SHARDED_PACK_CACHE[key] = fn
    return fn


_MESH_CONST_CACHE: dict = {}


def mesh_pack_fn(mesh: Optional[Mesh] = None):
    """A TensorScheduler ``pack_fn`` that runs the packing kernel sharded
    over a device mesh: the node-slot state over "data", the config
    catalog over "model", with XLA SPMD inserting the collectives (the
    K-cumsum becomes a collective prefix, the per-class config argmin an
    all-reduce).  Drop-in for ops.packer.run_pack — same padding, same
    PackResult contract, same upload hygiene (bit-packed feasibility,
    catalog constants uploaded once per snapshot with their target
    shardings) — so the whole production solve path (compile -> pack ->
    decode) runs multi-chip without further changes."""
    from karpenter_tpu.ops.packer import cached_device_put, node_slot_bound

    if mesh is None:
        mesh = make_mesh()
    dp = mesh.devices.shape[0]
    on_c = NamedSharding(mesh, P(MODEL_AXIS))
    on_c2 = NamedSharding(mesh, P(MODEL_AXIS, None))

    def pack(prob, k_slots: int = 0, objective: str = "nodes") -> PackResult:
        from karpenter_tpu.obs.device import OBSERVATORY

        # the "data" axis shards the node-slot bucket; keep it divisible
        if k_slots <= 0:
            k_slots = node_slot_bound(prob)
        k_slots = max(k_slots, 8 * dp)
        args, kp = pad_problem(prob, k_slots)
        (req, cnt, maxper, slot, feas, alloc, price, openable,
         used0, cfg0, npods0, e0, sig0) = args
        feas = np.packbits(feas, axis=1, bitorder="little")
        alloc, price, openable = cached_device_put(
            _MESH_CONST_CACHE,
            (prob.alloc, prob.price, prob.openable),
            (alloc.shape, mesh),
            lambda: (alloc, price, openable),
            shardings=(on_c2, on_c, on_c),
            site="mesh_constants",
        )
        return OBSERVATORY.dispatch(
            "mesh_pack", _sharded_pack(mesh, kp, objective),
            req, cnt, maxper, slot, feas, alloc, price, openable,
            used0, cfg0, npods0, e0, sig0,
        )

    pack.kernel_name = "scan-sharded"
    pack.mesh = mesh
    return pack
