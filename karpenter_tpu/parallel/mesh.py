"""Device-mesh sharding for the scheduling solver.

The reference scales its solve by batching windows and per-provisioner
serialization in one Go process (SURVEY.md §5: no distributed backend).
The TPU build instead shards the solve over a `jax.sharding.Mesh` and lets
XLA insert the collectives:

- axis **"data"**: the node-slot axis K — each device owns a shard of the
  open-bin state (residual usage, config commitments, per-signature
  counters).  The first-fit prefix allocation is a cumsum along K, which
  XLA SPMD lowers to an ICI collective prefix.
- axis **"model"**: the config axis C — the instance-type x zone x
  capacity-type catalog is partitioned like a sharded embedding table; the
  per-class argmin over C becomes an all-reduce.
- the **class axis G is the sequential dimension** (the `lax.scan` time
  axis) — the analogue of microbatched pipeline steps; it cannot be
  sharded, and doesn't need to be: per-step work is O(K·R + C·R).

The same mesh recipe runs on one chip (trivial mesh), an ICI-connected
slice, or CPU with `--xla_force_host_platform_device_count` for tests and
the driver's multi-chip dry run.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from karpenter_tpu.ops.packer import PackResult, pack_kernel

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 2D (data, model) mesh over the first `n_devices` devices.

    Even device counts split (n/2, 2) so both axes are exercised; odd
    counts degrade to (n, 1).
    """
    devices = jax.devices()
    n = n_devices if n_devices is not None else len(devices)
    devices = devices[:n]
    if n >= 2 and n % 2 == 0:
        shape = (n // 2, 2)
    else:
        shape = (n, 1)
    return Mesh(np.array(devices).reshape(shape), (DATA_AXIS, MODEL_AXIS))


def assemble_feasibility(
    type_ok: jax.Array,  # [S, T] bool — signature x type admission
    zone_ok: jax.Array,  # [S, Z] bool
    ct_ok: jax.Array,  # [S, CT] bool
    sig_of: jax.Array,  # [G] int32 — class -> signature
    t_of: jax.Array,  # [C] int32 — config -> type index
    z_of: jax.Array,  # [C] int32
    ct_of: jax.Array,  # [C] int32
) -> jax.Array:
    """Expand factorized admission vectors into the dense [G, C] mask.

    This is the device-side counterpart of the numpy assembly in
    ops/tensorize.py — the O(G·C) part of constraint compilation, sharded
    G over "data" and C over "model" so each device materializes only its
    tile of the mask.
    """
    g_rows = type_ok[sig_of]  # [G, T]
    z_rows = zone_ok[sig_of]  # [G, Z]
    ct_rows = ct_ok[sig_of]  # [G, CT]
    return g_rows[:, t_of] & z_rows[:, z_of] & ct_rows[:, ct_of]


def sharded_solve_step(mesh: Mesh, k_slots: int):
    """Build the jitted, mesh-sharded full solve step.

    Returns ``step(type_ok, zone_ok, ct_ok, sig_of, t_of, z_of, ct_of,
    req, cnt, maxper, slot, alloc, price, openable, used0, cfg0, npods0,
    next0, sig0) -> PackResult`` — feasibility expansion followed by the
    packing scan, compiled once over the mesh with the shardings described
    in the module docstring.
    """
    repl = NamedSharding(mesh, P())
    on_c = NamedSharding(mesh, P(MODEL_AXIS))
    on_c2 = NamedSharding(mesh, P(MODEL_AXIS, None))
    on_k = NamedSharding(mesh, P(DATA_AXIS))
    on_k2 = NamedSharding(mesh, P(DATA_AXIS, None))
    on_g = NamedSharding(mesh, P(DATA_AXIS))
    on_sk = NamedSharding(mesh, P(None, DATA_AXIS))

    def step(
        type_ok, zone_ok, ct_ok, sig_of, t_of, z_of, ct_of,
        req, cnt, maxper, slot, alloc, price, openable,
        used0, cfg0, npods0, next0, sig0,
    ) -> PackResult:
        feas = assemble_feasibility(
            type_ok, zone_ok, ct_ok, sig_of, t_of, z_of, ct_of
        )
        return pack_kernel(
            req, cnt, maxper, slot, feas, alloc, price, openable,
            used0, cfg0, npods0, next0, sig0, k_slots=k_slots,
        )

    return jax.jit(
        step,
        in_shardings=(
            repl, repl, repl, on_g, on_c, on_c, on_c,  # admission + maps
            repl, repl, repl, repl,  # class tensors (scan xs)
            on_c2, on_c, on_c,  # catalog: alloc, price, openable
            on_k2, on_k, on_k, repl, on_sk,  # bin state
        ),
    )
