"""Tracer-safety analyzer for ``jax.jit`` entry points.

Generalizes legacy rule 9's named-callsite fence to DECORATOR-DRIVEN
discovery: every jit-produced callable in the package is found from its
binding —

- ``@jax.jit`` / ``@jit`` decorated defs,
- ``@partial(jax.jit, ...)`` / ``@functools.partial(jax.jit, ...)``,
- name bindings ``f = jax.jit(...)`` (module-level or local),

and three properties are checked:

1. **Seamed dispatch**: a discovered jit callable must not be CALLED
   directly anywhere in the package outside the device observatory's
   counted seam (obs/device.py) — every dispatch routes through
   ``OBSERVATORY.dispatch(name, fn, ...)`` so compile/transfer
   accounting cannot rot.  Passing the callable as an argument (the
   dispatch pattern) is fine; calling it from inside ANOTHER traced body
   is device-side composition and also fine.
2. **No host mutation of traced parameters**: ``np.<mutator>(param,
   ...)``, in-place ndarray methods on a parameter, or subscript
   assignment to a parameter inside a traced body — the classic
   TracerArrayConversionError / silent-constant-folding bug class.
3. **No bare ``time.*`` or ``print`` in traced bodies**: both run at
   TRACE time, not run time — a timestamp or log that looks per-call
   but fires once per compile is a lie in any byte-compared artifact.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from karpenter_tpu.analysis.core import (
    Finding,
    PackageSnapshot,
    Rule,
    ScopedVisitor,
    register,
)

_NP_MUTATORS = frozenset(
    {"put", "place", "copyto", "putmask", "fill_diagonal"}
)
# NOTE deliberately no in-place ndarray METHOD check (param.sort() etc):
# inside a traced body the parameters are tracers, whose .sort() is the
# functional jax.numpy method returning a new array — flagging it would
# be a false positive by construction.  Host mutation enters through
# np.* mutators and subscript assignment, both checked below.

# the sanctioned seam file (package-relative)
_SEAM_FILE = "obs/device.py"


def _is_jax_jit(node: ast.expr) -> bool:
    """``jax.jit`` / ``jit`` expression?"""
    if isinstance(node, ast.Attribute):
        return node.attr == "jit" and (
            isinstance(node.value, ast.Name) and node.value.id == "jax"
        )
    return isinstance(node, ast.Name) and node.id == "jit"


def _jit_call(node: ast.expr) -> bool:
    """``jax.jit(...)`` or ``partial(jax.jit, ...)`` expression?"""
    if not isinstance(node, ast.Call):
        return False
    if _is_jax_jit(node.func):
        return True
    f = node.func
    is_partial = (isinstance(f, ast.Name) and f.id == "partial") or (
        isinstance(f, ast.Attribute) and f.attr == "partial"
    )
    return is_partial and any(_is_jax_jit(a) for a in node.args)


def discover_jit(
    tree: ast.Module,
) -> Tuple[Dict[str, ast.AST], Set[str], Dict[ast.AST, Set[str]]]:
    """(decorated defs by name, module-wide bound names, per-function
    local bound names).

    Scoping matters: ``fn = jax.jit(step)`` inside one method must only
    fence calls of ``fn`` within THAT function — a global match would
    flag every unrelated ``fn()`` in the package.  Attribute bindings
    (``self._step_fn = jax.jit(...)``) are object-scoped and therefore
    module-wide by attribute name."""
    defs: Dict[str, ast.AST] = {}
    bound: Set[str] = set()
    local: Dict[ast.AST, Set[str]] = {}

    # names handed to jax.jit as the wrapped FUNCTION (``jax.jit(step,
    # ...)``): their defs are traced bodies even without a decorator —
    # the factory pattern mesh.py/resident.py use
    wrapped: Set[str] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and _is_jax_jit(node.func)
            and node.args
            and isinstance(node.args[0], ast.Name)
        ):
            wrapped.add(node.args[0].id)

    def scan_assign(node: ast.Assign, fn_scope) -> None:
        if not _jit_call(node.value):
            return
        for target in node.targets:
            if isinstance(target, ast.Attribute):
                bound.add(target.attr)
            elif isinstance(target, ast.Name):
                if fn_scope is None:
                    bound.add(target.id)
                else:
                    local.setdefault(fn_scope, set()).add(target.id)

    def walk(node, fn_scope):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if child.name in wrapped or any(
                    _is_jax_jit(d) or _jit_call(d)
                    for d in child.decorator_list
                ):
                    if fn_scope is None:
                        defs[child.name] = child
                    else:
                        # a jit def nested inside a factory is only
                        # callable from that factory: fence its name
                        # locally, not across the package — and never
                        # touch defs[child.name], which may hold a
                        # SAME-NAMED module-level jit def whose body and
                        # call sites must stay covered
                        local.setdefault(fn_scope, set()).add(child.name)
                        defs[f"{fn_scope.name}.{child.name}"] = child
                walk(child, child)
            elif isinstance(child, ast.Assign):
                scan_assign(child, fn_scope)
                walk(child, fn_scope)
            else:
                walk(child, fn_scope)

    walk(tree, None)
    return defs, bound, local


def _param_names(fn: ast.AST) -> Set[str]:
    args = fn.args
    names = {a.arg for a in args.args + args.kwonlyargs + args.posonlyargs}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    return names


@register
class TracerSafetyRule(Rule):
    """jit bodies are pure traced compute; dispatch takes the seam."""

    name = "tracer-safety"
    title = "jit callables seam-dispatched; traced bodies stay pure"
    guards = "transfer/compile accounting + no trace-time host effects"

    def check(self, snap, allowlist) -> List[Finding]:
        out: List[Finding] = []
        # pass 1: discover every jit callable and lint its body
        jit_names: Set[str] = set()
        jit_def_spans: Dict[str, List[Tuple[int, int]]] = {}
        locals_by_rel: Dict[str, Dict[ast.AST, Set[str]]] = {}
        for info in snap.in_package():
            defs, bound, local = discover_jit(info.tree)
            jit_names.update(defs)
            jit_names.update(bound)
            locals_by_rel[info.rel] = local
            for name, fn in defs.items():
                jit_def_spans.setdefault(info.rel, []).append(
                    (fn.lineno, max(fn.lineno, fn.end_lineno or fn.lineno))
                )
                out.extend(self._lint_traced_body(info.rel, name, fn))
        # pass 2: every direct call of a jit name must take the seam
        for info in snap.in_package():
            if info.rel_in_pkg == _SEAM_FILE:
                continue
            out.extend(
                self._lint_call_sites(
                    info, jit_names, jit_def_spans.get(info.rel, []),
                    allowlist,
                )
            )
            # function-local jit bindings: fence calls within their own
            # function only
            for fn_node, names in locals_by_rel[info.rel].items():
                out.extend(
                    self._lint_local_calls(info, fn_node, names, allowlist)
                )
        return out

    def _lint_local_calls(
        self, info, fn_node, names: Set[str], allowlist
    ) -> List[Finding]:
        out: List[Finding] = []
        rel = info.rel
        for node in ast.walk(fn_node):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in names
            ):
                qual = fn_node.name
                if (rel, qual) in allowlist:
                    continue
                out.append(
                    self.finding(
                        rel, node.lineno,
                        f"{qual}: direct call of locally-jitted "
                        f"{node.func.id}(...) bypasses the counted seam "
                        "— route it through OBSERVATORY.dispatch, or "
                        "consciously allowlist this site",
                    )
                )
        return out

    # ------------------------------------------------------- traced bodies
    def _lint_traced_body(self, rel: str, name: str, fn) -> List[Finding]:
        out: List[Finding] = []
        params = _param_names(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Name) and f.id == "print":
                    out.append(
                        self.finding(
                            rel, node.lineno,
                            f"print(...) inside traced body {name}: runs "
                            "at trace time, once per compile — not per "
                            "call",
                        )
                    )
                elif (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "time"
                ):
                    out.append(
                        self.finding(
                            rel, node.lineno,
                            f"time.{f.attr}(...) inside traced body "
                            f"{name}: trace-time host clock, constant-"
                            "folded into the compiled program",
                        )
                    )
                elif (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id in ("np", "numpy")
                    and f.attr in _NP_MUTATORS
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in params
                ):
                    out.append(
                        self.finding(
                            rel, node.lineno,
                            f"np.{f.attr}({node.args[0].id}, ...) mutates "
                            f"a traced parameter of {name} host-side — "
                            "use jnp functional updates (.at[].set)",
                        )
                    )
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for target in targets:
                    if (
                        isinstance(target, ast.Subscript)
                        and isinstance(target.value, ast.Name)
                        and target.value.id in params
                    ):
                        out.append(
                            self.finding(
                                rel, target.lineno,
                                f"{target.value.id}[...] = ... assigns "
                                f"into a traced parameter of {name} — "
                                "tracers are immutable; use .at[].set",
                            )
                        )
        return out

    # --------------------------------------------------------- call sites
    def _lint_call_sites(
        self, info, jit_names: Set[str], def_spans, allowlist
    ) -> List[Finding]:
        out: List[Finding] = []
        rel = info.rel
        rule = self

        def inside_jit(line: int) -> bool:
            return any(lo <= line <= hi for lo, hi in def_spans)

        class V(ScopedVisitor):
            def on_call(self, node):
                f = node.func
                name = None
                if isinstance(f, ast.Name) and f.id in jit_names:
                    name = f.id
                elif isinstance(f, ast.Attribute) and f.attr in jit_names:
                    name = f.attr
                if name is None:
                    return
                if inside_jit(node.lineno):
                    return  # device-side composition inside a traced body
                if (rel, self.qual) in allowlist:
                    return
                out.append(
                    rule.finding(
                        rel, node.lineno,
                        f"{self.qual or '<module>'}: direct call of jit "
                        f"callable {name}(...) bypasses the counted seam "
                        "— route it through OBSERVATORY.dispatch("
                        f"'{name}', {name}, ...), or consciously "
                        "allowlist this site",
                    )
                )

        V().visit(info.tree)
        return out
