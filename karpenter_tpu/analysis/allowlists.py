"""THE declarative allowlist table: one place where every sanctioned
exception to every rule lives, each with the argument for its existence.

Entry types are rule-defined:

- path strings ``"karpenter_tpu/utils/clock.py"`` (file-scoped),
- ``(file, qualified name)`` tuples (call-site / region scoped),
- ``"LockA|LockB"`` pair ids (lock-order),
- ``"root:<rel_in_pkg>:<qual>"`` strings (extra determinism roots — the
  teeth harness hook),
- bare names (doc-vocabulary extensions, used only by synthetic tests).

Adding an entry here is a REVIEWED act: the PR that adds one must say
why the exception is sound (see docs/designs/static-analysis.md).
"""

from __future__ import annotations

from typing import Dict

# ---------------------------------------------------------------- legacy
# rule 3: the genuinely-wall-clock spot — the Clock abstraction itself is
# the one place allowed to read the wall (time.monotonic/perf_counter
# remain free: host-side durations no simulated clock can compress).
_WALL_CLOCK = frozenset({"karpenter_tpu/utils/clock.py"})

# rule 4: the sanctioned scheduler.update call sites in controllers/ —
# the provisioner's one-per-solve sync (extracted to _sync_scheduler so
# the batched solve and the admission fast path share exactly ONE update
# per provisioning pass), the deprovisioner's explicit
# sequential-simulation fallback, and the batched evaluator's
# once-per-pass full-cluster sync.
_SCHEDULER_UPDATE = frozenset(
    {
        ("karpenter_tpu/controllers/provisioning.py",
         "Provisioner._sync_scheduler"),
        ("karpenter_tpu/controllers/disruption.py",
         "DisruptionController._simulate"),
        ("karpenter_tpu/controllers/disruption.py",
         "_RemovalEvaluator._sync_scheduler"),
    }
)

# rule 7: the sanctioned full-tensorize sites — the wrapper itself, the
# cold build / resident-miss rebuild, the direct compile+pack+decode
# kept for tests, and the consolidation base's rebuild fallback.
_FULL_TENSORIZE = frozenset(
    {
        ("karpenter_tpu/scheduling/solver.py",
         "TensorScheduler._compile_tensor"),
        ("karpenter_tpu/scheduling/solver.py", "TensorScheduler._solve"),
        ("karpenter_tpu/scheduling/solver.py",
         "TensorScheduler._solve_tensor"),
        ("karpenter_tpu/scheduling/solver.py",
         "TensorScheduler._build_removal_base"),
    }
)

# rule 8: the sanctioned sequential-descent sites — the lazy per-element
# fallback, the winner's authoritative re-derivation, and the
# consolidation pass entry points (multi -> descent fallback).
_SEQUENTIAL_DESCENT = frozenset(
    {
        ("karpenter_tpu/controllers/disruption.py",
         "_RemovalEvaluator.result"),
        ("karpenter_tpu/controllers/disruption.py",
         "_RemovalEvaluator.vnode_for"),
        ("karpenter_tpu/controllers/disruption.py",
         "DisruptionController._consolidate"),
        ("karpenter_tpu/controllers/disruption.py",
         "DisruptionController._consolidate_multi"),
    }
)

# rule 9: the counted-upload seam is the one sanctioned raw device_put.
_DEVICE_PUT = frozenset(
    {("karpenter_tpu/obs/device.py", "DeviceObservatory.put")}
)

# rule 11: the one sanctioned pool constructor for the controller layer.
_THREAD_SEAM = frozenset(
    {("karpenter_tpu/pipeline.py", "run_concurrently")}
)

# ------------------------------------------------------- lock discipline
# Cross-class lock aliases the AST cannot see: _Subscriber.cond is
# constructed OVER the VersionedStore's lock (store_server.py — offers
# happen under the store lock, the sender waits on the same lock), so
# holding one IS holding the other; without the alias every
# subscribe-under-lock would read as a lock-order edge.
LOCK_ALIASES: Dict[str, str] = {
    "_Subscriber.cond": "VersionedStore.lock",
}

# lock-order scan scope: the layers whose locks interleave across
# threads (store plane, pipeline/operator, controllers, batcher).
LOCK_ORDER_LAYERS = (
    "service/",
    "state/",
    "pipeline.py",
    "operator.py",
    "controllers/",
    "batcher/",
    "utils/leader.py",
)

# lock-blocking sanctioned regions, each with its argument:
_LOCK_BLOCKING = frozenset(
    {
        # The RPC lock EXISTS to serialize the one shared connection:
        # one in-flight request per socket is the framing protocol's
        # invariant, so the send/recv pair must sit inside it.  Nothing
        # else ever takes this lock.
        ("karpenter_tpu/state/remote.py", "RemoteKubeStore._rpc"),
        # Lease operations serialize END-TO-END by design (the
        # base_rv race documented at the _lease_mutex definition):
        # holding the dedicated mutex across flush+RPC is the
        # correctness mechanism, and only lease ops contend on it.
        ("karpenter_tpu/state/remote.py",
         "RemoteKubeStore.try_acquire_lease"),
        ("karpenter_tpu/state/remote.py", "RemoteKubeStore.renew_lease"),
        ("karpenter_tpu/state/remote.py", "RemoteKubeStore.release_lease"),
        # The solver sidecar client: same one-in-flight-RPC-per-
        # connection design as RemoteKubeStore._rpc.
        ("karpenter_tpu/service/client.py", "RemoteSolver._call"),
        # A bin snapshot references LIVE objects, so it must be rendered
        # before the store lock drops (store_server.py documents the
        # contract; the JSON tree path encodes outside).  The watcher
        # condition shares the store lock, so the coalesced-resync build
        # (_resync_payload_locked) sits under the same region.
        ("karpenter_tpu/service/store_server.py", "StoreServer.serve_watch"),
        # The ledger's JSONL sink writes one SMALL event per emit under
        # the ring lock — the lock is what keeps sink lines in seq
        # order; payloads are single events, never snapshot-sized.
        ("karpenter_tpu/obs/events.py", "EventLedger.emit"),
        # The durable log's lock is what keeps on-disk records in seq
        # order — encode+write+fsync MUST sit inside it or a concurrent
        # append could interleave frames and corrupt the segment.  The
        # payload is one commit batch (bounded by the batcher), and the
        # caller already serialized on the store lock: durability-
        # before-ack is the contract under test, not an accident.
        ("karpenter_tpu/state/storelog.py", "DurableReplayLog.append_batch"),
        # Checkpoints write snapshot-sized payloads, but to a TMP file
        # finalized by an atomic rename; the lock orders the segment
        # swap against concurrent appends so recovery's "last
        # checkpoint + contiguous tail" invariant can never observe a
        # half-swapped segment.
        ("karpenter_tpu/state/storelog.py",
         "DurableReplayLog.write_checkpoint"),
    }
)

_LOCK_ORDER = frozenset()

# ------------------------------------------------- determinism analyzer
# The byte-compared surfaces (package-relative so synthetic trees keep
# the vocabulary): per-tick digests, ledger lines, the SLO report, the
# cluster event ledger, and the pipelined twin-run adoption seam.
DETERMINISM_ROOTS = (
    "sim/trace.py:TraceWriter.digest",
    "sim/trace.py:TraceWriter.ledger",
    "sim/trace.py:TraceWriter.report",
    "sim/report.py:build_report",
    "obs/events.py:EventLedger.emit",
    "controllers/disruption.py:DisruptionController._take_speculation",
    "controllers/disruption.py:DisruptionController._pass_fingerprint",
    # the columnar event tape's identity hash: a tape must replay
    # byte-identical to its per-event twin, so everything reachable from
    # the digest (column builds, the counter RNG, per-tick
    # materialization) is a byte-compared surface
    "load/generators.py:EventTape.digest",
)

# sanctioned sinks, each with its argument:
_DETERMINISM = frozenset(
    {
        # THE sanctioned wall-clock: determinism holds because the
        # simulator injects a FakeClock here; replay tests prove the
        # bytes (docs/designs/simulation.md).
        "karpenter_tpu/utils/clock.py",
    }
)

# --------------------------------------------------- tracer-safety seam
_TRACER_SAFETY = frozenset()

# ------------------------------------------------- runtime sanitizer
# Locks under which the runtime blocking witness (sanitizer.py
# note_blocking) is SANCTIONED — each the dynamic twin of a static
# _LOCK_BLOCKING region above, with the same argument:
SANITIZER_BLOCKING_LOCKS = frozenset(
    {
        # one in-flight request per socket is the framing protocol's
        # invariant: the RPC lock EXISTS to hold across send+recv
        "RemoteKubeStore._rpc_lock",
        # lease ops serialize end-to-end by design (the base_rv race):
        # the mutex is held across flush+RPC on purpose
        "RemoteKubeStore._lease_mutex",
        # the solver sidecar's one-in-flight connection lock
        "RemoteSolver._lock",
        # bin snapshots/frames reference LIVE objects and must render
        # before the store lock drops (store_server.py's documented
        # contract — the static serve_watch allowlist's runtime twin)
        "VersionedStore.lock",
        # per-shard RPC serialization: one in-flight request per shard
        # socket is the framing invariant (the sharded twin of
        # RemoteKubeStore._rpc_lock — each StoreChannel carries its own)
        "StoreChannel._lock",
        # durability-before-ack: encode+write+fsync hold the log lock
        # so disk records stay in seq order (the runtime twin of the
        # static storelog.py allowlist entries above)
        "DurableReplayLog._lock",
    }
)

# Runtime lock-order edges the static model does not predict, each
# sanctioned with an argument ("outer|inner" pair ids).  Empty on
# purpose: the sanitized suites currently exercise no edge the static
# analyzer misses — a new entry here means EITHER a static-resolution
# hole (fix locks.py) or a deliberate dynamic-only pattern (argue it).
WITNESS_EDGES = frozenset()

# settings-flow: fields exempt from the READ requirement only (chart
# presence is never exempt — an accepted field costs one values line):
_SETTINGS_FLOW = frozenset(
    {
        # Reference-parity ENI knobs (settings.go:48-61): accepted and
        # validated for config compatibility with reference settings
        # payloads, but this build's fake backend has no ENI density
        # model to consume them yet.  Wiring them into
        # InstanceTypeProvider is open work; until then they are
        # DECLARED dead, not silently dead.
        "reserved_enis",
        "enable_pod_eni",
        "enable_eni_limited_pod_density",
    }
)

# ---------------------------------------- service tenant metrics (rule 12)
# service/ files exempt from the tenant-label requirement because they
# are the STORE plane, not the solver service: one shared cluster store
# per deployment, tenant-less by design — its karpenter_store_* families
# key on method/codec, and tenancy is a solver-service concept.
_SERVICE_TENANT_METRICS = frozenset(
    {
        "karpenter_tpu/service/store_server.py",
        "karpenter_tpu/service/shardrouter.py",
    }
)

# lock-seam: raw constructions sanctioned by (file, "Class.attr"):
_LOCK_SEAM = frozenset(
    {
        # the sanitizer's own mutex: wrapping it in itself would recurse
        ("karpenter_tpu/analysis/sanitizer.py", "LockSanitizer._mu"),
    }
)

ALLOWLISTS: Dict[str, frozenset] = {
    "wall-clock": _WALL_CLOCK,
    "scheduler-update": _SCHEDULER_UPDATE,
    "full-tensorize": _FULL_TENSORIZE,
    "sequential-descent": _SEQUENTIAL_DESCENT,
    "device-put": _DEVICE_PUT,
    "thread-seam": _THREAD_SEAM,
    "lock-blocking": _LOCK_BLOCKING,
    "lock-order": _LOCK_ORDER,
    "determinism-reachability": _DETERMINISM,
    "tracer-safety": _TRACER_SAFETY,
    "settings-flow": _SETTINGS_FLOW,
    "service-tenant-metrics": _SERVICE_TENANT_METRICS,
    "lock-seam": _LOCK_SEAM,
}
