"""Determinism-reachability analyzer.

The repo's byte-compared surfaces — per-tick sim trace digests, ledger
``led`` lines, the SLO report, and the pipelined twin-run adoption seam
— promise that two runs of equal seed produce identical bytes.  The
legacy wall-clock rule fences ``time.time`` at file granularity; this
rule upgrades it to CALL-GRAPH reachability over tainted SOURCES:

- wall clock (``time.time``/``time_ns``, ``datetime.now/utcnow/today``),
- the unseeded module-level ``random.*`` API (seeded ``random.Random(s)``
  instances are the sanctioned way to be random),
- ambient process state: ``os.environ`` / ``os.getenv``, ``os.urandom``,
  ``uuid.uuid1/uuid4``,
- iteration DIRECTLY over a set (``for x in {...}`` / ``for x in
  set(...)``) — id-order iteration feeding ordered output.

A finding means: some function reachable from a byte-compared root
contains a tainted source and is not on the sanctioned-sink list.  The
sanctioned sinks (allowlists.py) are the deliberate exceptions with the
argument for each — e.g. ``utils/clock.py`` IS the wall clock, and
determinism holds because the simulator injects a FakeClock there (the
replay tests prove it byte-for-byte).

Roots are declared in allowlists.DETERMINISM_ROOTS; a root that no
longer resolves is itself a finding, so a refactor cannot silently drop
a surface out of coverage.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.analysis.core import (
    Finding,
    PackageSnapshot,
    Rule,
    register,
)
from karpenter_tpu.analysis.graph import call_graph

_WALL = {"time": {"time", "time_ns"}, "datetime": {"now", "utcnow", "today"}}
_RANDOM_FNS = frozenset(
    {
        "random", "randint", "randrange", "choice", "choices", "shuffle",
        "sample", "uniform", "gauss", "betavariate", "expovariate",
        "getrandbits", "normalvariate", "triangular", "vonmisesvariate",
        "seed",
    }
)


def _classify(mod: str, attr: str) -> Optional[str]:
    """Taint description for a call of ``mod.attr``, or None."""
    if mod == "time" and attr in _WALL["time"]:
        return f"wall clock time.{attr}()"
    if mod in ("datetime", "date") and attr in _WALL["datetime"]:
        return f"wall clock {mod}.{attr}()"
    if mod == "random" and attr in _RANDOM_FNS:
        return f"unseeded global random.{attr}()"
    if mod == "os" and attr in ("getenv", "urandom"):
        return f"ambient os.{attr}()"
    if mod == "uuid" and attr in ("uuid1", "uuid4"):
        return f"nondeterministic uuid.{attr}()"
    return None


_TAINT_MODULES = frozenset({"time", "datetime", "date", "random", "os",
                            "uuid"})


def stdlib_aliases(
    tree: ast.Module,
) -> Tuple[Dict[str, str], Dict[str, Tuple[str, str]]]:
    """(module aliases, from-imported names) for the taint-relevant
    stdlib modules: ``import time as _time`` must not hide the wall
    clock, and neither must ``from time import time`` (a BARE call the
    attribute matcher would never see)."""
    aliases: Dict[str, str] = {}
    from_names: Dict[str, Tuple[str, str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name in _TAINT_MODULES:
                    aliases[alias.asname or alias.name] = alias.name
                # `import datetime` exposes datetime.datetime.now();
                # map the submodule-style alias too
        elif isinstance(node, ast.ImportFrom) and node.module in (
            _TAINT_MODULES
        ):
            for alias in node.names:
                from_names[alias.asname or alias.name] = (
                    node.module, alias.name,
                )
                # `from datetime import datetime/date` behaves like a
                # module alias for the .now()/.today() matcher
                if node.module == "datetime" and alias.name in (
                    "datetime", "date",
                ):
                    aliases[alias.asname or alias.name] = alias.name
    return aliases, from_names


def taint_sources(
    node: ast.AST,
    aliases: Optional[Dict[str, str]] = None,
    from_names: Optional[Dict[str, Tuple[str, str]]] = None,
) -> List[Tuple[int, str]]:
    """(line, description) for every tainted source in a def body."""
    aliases = aliases or {}
    from_names = from_names or {}
    out: List[Tuple[int, str]] = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Name) and f.id in from_names:
                mod, attr = from_names[f.id]
                what = _classify(mod, attr)
                if what:
                    out.append((sub.lineno, what))
            elif isinstance(f, ast.Attribute):
                base = None
                if isinstance(f.value, ast.Name):
                    base = aliases.get(f.value.id, f.value.id)
                elif isinstance(f.value, ast.Attribute):
                    # dotted chains: datetime.datetime.now(),
                    # datetime.date.today()
                    tail = f.value.attr
                    if tail in ("datetime", "date"):
                        base = tail
                if base is not None:
                    what = _classify(base, f.attr)
                    if what:
                        out.append((sub.lineno, what))
        elif isinstance(sub, ast.Attribute):
            if (
                isinstance(sub.value, ast.Name)
                and aliases.get(sub.value.id, sub.value.id) == "os"
                and sub.attr == "environ"
            ):
                out.append((sub.lineno, "ambient os.environ"))
        elif isinstance(sub, ast.Name) and from_names.get(sub.id) == (
            "os", "environ",
        ):
            out.append((sub.lineno, "ambient os.environ"))
        elif isinstance(sub, (ast.For, ast.AsyncFor)):
            it = sub.iter
            if isinstance(it, ast.Set):
                out.append((it.lineno, "iteration over a set literal"))
            elif (
                isinstance(it, ast.Call)
                and isinstance(it.func, ast.Name)
                and it.func.id in ("set", "frozenset")
            ):
                out.append(
                    (it.lineno, f"direct iteration over {it.func.id}(...)")
                )
    return out


@register
class DeterminismReachabilityRule(Rule):
    """No tainted source reachable from a byte-compared surface."""

    name = "determinism-reachability"
    title = "byte-compared surfaces cannot reach a nondeterminism source"
    guards = "replay identity, twin-run identity, led/dig/report bytes"

    def check(self, snap, allowlist) -> List[Finding]:
        from karpenter_tpu.analysis.allowlists import DETERMINISM_ROOTS

        graph = call_graph(snap)
        out: List[Finding] = []
        roots = []
        for root in DETERMINISM_ROOTS:
            # roots are package-relative ("sim/trace.py:TraceWriter.digest")
            # so synthetic trees keep the same vocabulary
            resolved = [
                k for k, d in graph.defs.items()
                if d.module.rel_in_pkg == root.split(":", 1)[0]
                and d.qual == root.split(":", 1)[1]
            ]
            if not resolved:
                # only report unresolved roots against the REAL package
                # (synthetic teeth trees declare their own roots via the
                # allowlist mechanism below).  The package name is
                # DERIVED, not a literal — tools/gen_metrics_doc scrapes
                # quoted karpenter_* literals and must not list this
                # file; the finding anchors at the roots' declaration
                # site, which is also where the fix goes.
                own_pkg = (__package__ or "").split(".")[0]
                if snap.package == own_pkg:
                    out.append(
                        self.finding(
                            f"{own_pkg}/analysis/allowlists.py", 1,
                            f"byte-compared root {root!r} no longer "
                            "resolves — the surface moved; update "
                            "DETERMINISM_ROOTS so it stays covered",
                        )
                    )
                continue
            roots.extend(resolved)
        # synthetic trees: any allowlist entry of the form
        # "root:<rel_in_pkg>:<qual>" adds a root (teeth harness hook)
        for entry in allowlist:
            if isinstance(entry, str) and entry.startswith("root:"):
                _, rel_in_pkg, qual = entry.split(":", 2)
                roots.extend(
                    k for k, d in graph.defs.items()
                    if d.module.rel_in_pkg == rel_in_pkg and d.qual == qual
                )
        sanctioned_files = {
            e for e in allowlist if isinstance(e, str) and e.endswith(".py")
        }
        sanctioned_defs = {e for e in allowlist if isinstance(e, tuple)}
        alias_cache: Dict[str, tuple] = {}
        for key, path in sorted(graph.reachable_from(roots).items()):
            d = graph.defs[key]
            if d.rel in sanctioned_files or (d.rel, d.qual) in sanctioned_defs:
                continue
            if d.rel not in alias_cache:
                alias_cache[d.rel] = stdlib_aliases(d.module.tree)
            aliases, from_names = alias_cache[d.rel]
            for line, what in taint_sources(d.node, aliases, from_names):
                out.append(
                    self.finding(
                        d.rel, line,
                        f"{what} in {d.qual} is reachable from the "
                        f"byte-compared surface via "
                        f"{graph.render_path(path)} — inject it (Clock, "
                        "seeded Random) or sanction the sink with a "
                        "written argument",
                    )
                )
        return out
