"""The runtime concurrency witness artifact (docs/designs/static-analysis.md
§runtime sanitizer).

A witness is what a sanitized run (analysis/sanitizer.py) leaves behind:
the lock-order graph actually exercised, the blocking operations observed
under held locks, the Eraser-style lockset verdict per annotated shared
field, and the findings the run produced.  It is the DYNAMIC half of the
static lock model, so it follows the same artifact discipline Findings
do: JSON with sorted keys, no wall clock, no thread ids, no memory
addresses — two runs of the same seeded scenario serialize to identical
bytes — and a content fingerprint (sha256 over the canonical payload,
truncated like ``Finding.fingerprint``) so CI can diff witnesses the way
it diffs lint reports.

Cross-validation (:func:`cross_validate`) is the payoff: merging a
witness into the static order graph reports BOTH directions —

- a runtime edge the static analyzer never predicted is *static model
  incompleteness* (a finding: either the static model's resolution has a
  hole or a lock name drifted from its ``Class.attr`` identity);
- a static edge never exercised at runtime is a *coverage gap*
  (informational: the sanitized suites simply never drove that path).

Only edges whose BOTH endpoints live in the static model's order
universe (``LOCK_ORDER_LAYERS``-scoped lock attributes) participate:
runtime edges touching out-of-layer locks (a metrics registry lock, a
cache lock) are reported separately as ``unmodeled`` so they cannot
drown the signal in noise the static rule deliberately scopes out.
"""

from __future__ import annotations

import hashlib
import json
import pathlib
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

WITNESS_VERSION = 1


@dataclass
class Witness:
    """One sanitized run's serialized evidence.  Every list is kept
    sorted by the producer (sanitizer.py) so ``dumps`` is deterministic."""

    scenario: str = ""
    # lock names ("Class.attr") ever acquired
    locks: List[str] = field(default_factory=list)
    # {"outer", "inner", "sites": [rel:qual, ...]}
    edges: List[dict] = field(default_factory=list)
    # {"op", "locks": [held names], "site", "allowed": bool}
    blocking: List[dict] = field(default_factory=list)
    # {"field", "state", "lockset": [...], "threads": n, "writers": n}
    fields: List[dict] = field(default_factory=list)
    # Finding.to_dict() records the run produced (empty on a clean run)
    findings: List[dict] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "version": WITNESS_VERSION,
            "scenario": self.scenario,
            "locks": list(self.locks),
            "edges": list(self.edges),
            "blocking": list(self.blocking),
            "fields": list(self.fields),
            "findings": list(self.findings),
        }

    @property
    def fingerprint(self) -> str:
        payload = json.dumps(self.to_dict(), sort_keys=True)
        return hashlib.sha256(payload.encode()).hexdigest()[:16]

    def dumps(self) -> str:
        """The canonical artifact bytes: payload plus its own
        fingerprint, sorted keys, trailing newline."""
        doc = self.to_dict()
        doc["fingerprint"] = self.fingerprint
        return json.dumps(doc, indent=2, sort_keys=True) + "\n"

    def dump(self, path) -> str:
        path = pathlib.Path(path)
        path.write_text(self.dumps())
        return str(path)

    def edge_pairs(self) -> FrozenSet[Tuple[str, str]]:
        return frozenset((e["outer"], e["inner"]) for e in self.edges)

    @classmethod
    def from_dict(cls, doc: dict) -> "Witness":
        if doc.get("version") != WITNESS_VERSION:
            raise ValueError(
                f"witness version {doc.get('version')!r} != "
                f"{WITNESS_VERSION} (not a witness artifact, or a "
                "format this build does not read)"
            )
        return cls(
            scenario=doc.get("scenario", ""),
            locks=list(doc.get("locks", ())),
            edges=list(doc.get("edges", ())),
            blocking=list(doc.get("blocking", ())),
            fields=list(doc.get("fields", ())),
            findings=list(doc.get("findings", ())),
        )

    @classmethod
    def loads(cls, text: str) -> "Witness":
        return cls.from_dict(json.loads(text))

    @classmethod
    def load(cls, path) -> "Witness":
        return cls.loads(pathlib.Path(path).read_text())


@dataclass
class CrossValidation:
    """The static<->dynamic merge verdict for one witness."""

    # runtime edges the static model never predicted, minus the
    # allowlist — each is a finding (static model incompleteness)
    missing_static: List[dict] = field(default_factory=list)
    # static edges never exercised by this witness — informational
    # coverage gaps, never findings (a short scenario proves nothing
    # about paths it does not drive)
    unexercised_static: List[str] = field(default_factory=list)
    # runtime edges with an endpoint outside the static order universe —
    # out of the static rule's deliberate scope, listed for visibility
    unmodeled: List[dict] = field(default_factory=list)
    # runtime edges the static model also predicts (the agreement set)
    confirmed: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.missing_static

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "confirmed": list(self.confirmed),
            "missing_static": list(self.missing_static),
            "unexercised_static": list(self.unexercised_static),
            "unmodeled": list(self.unmodeled),
        }


def _pair_id(outer: str, inner: str) -> str:
    return f"{outer}|{inner}"


def cross_validate(
    witness: Witness,
    static_edges: FrozenSet[Tuple[str, str]],
    universe: FrozenSet[str],
    allowlist: Optional[Sequence[str]] = None,
) -> CrossValidation:
    """Merge a witness's runtime lock-order edges into the static order
    graph.  ``static_edges`` and ``universe`` come from
    :func:`karpenter_tpu.analysis.locks.static_order_edges`;
    ``allowlist`` entries are ``"outer|inner"`` pair ids
    (allowlists.WITNESS_EDGES) sanctioning a runtime-only edge with a
    written argument."""
    allowed = frozenset(allowlist or ())
    out = CrossValidation()
    sites_by_pair: Dict[Tuple[str, str], List[str]] = {
        (e["outer"], e["inner"]): list(e.get("sites", ()))
        for e in witness.edges
    }
    for (outer, inner) in sorted(sites_by_pair):
        pair = _pair_id(outer, inner)
        entry = {
            "outer": outer,
            "inner": inner,
            "sites": sites_by_pair[(outer, inner)],
        }
        if outer not in universe or inner not in universe:
            out.unmodeled.append(entry)
        elif (outer, inner) in static_edges:
            out.confirmed.append(pair)
        elif pair not in allowed:
            out.missing_static.append(entry)
    runtime = witness.edge_pairs()
    out.unexercised_static = sorted(
        _pair_id(a, b) for (a, b) in static_edges if (a, b) not in runtime
    )
    return out
