"""Intra-package definition index + call graph.

The whole-program analyzers (locks.py, reachability.py) need "what does
this function reach?" answers the per-callsite rules cannot give.  The
graph is a deliberate over-approximation tuned for THIS codebase:

- **bare names** resolve through the module's own top-level defs and its
  ``from pkg.mod import f`` imports;
- **self/cls attribute calls** resolve through the enclosing class, then
  its by-name base classes within the package (the ``RemoteKubeStore ->
  KubeStore`` chain);
- **module-alias calls** (``mod.f(...)`` after ``import pkg.mod as
  mod``) resolve into that module;
- **other attribute calls** (``store.subscribe(...)``) resolve to EVERY
  package def of that name — sound for reachability, and kept sane by a
  stoplist of generic container/stdlib-shaped names that would otherwise
  alias half the package together (``get``, ``items``, ``close``, ...).

Nested functions and lambdas are attributed to their enclosing def: a
closure handed to ``mutate(lambda: ...)`` or a local ``def apply()``
runs on the caller's stack for every pattern in this repo, which is
exactly the approximation the lock analyzer wants.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from karpenter_tpu.analysis.core import ModuleInfo, PackageSnapshot

# Attribute names too generic to resolve globally: linking every
# `x.get(...)` to every package class defining `get` would weld the
# graph into one blob.  self-calls still resolve through the class, so
# a stoplisted name only loses the cross-object edge.
ATTR_STOPLIST = frozenset(
    {
        "get", "set", "add", "pop", "items", "keys", "values", "append",
        "extend", "insert", "remove", "discard", "clear", "copy", "update",
        "count", "index", "sort", "split", "join", "strip", "read",
        "write", "flush", "open", "close", "encode", "decode", "format",
        "startswith", "endswith", "lower", "upper", "replace", "setdefault",
        "submit", "result", "wait", "notify", "notify_all", "acquire",
        "release", "start", "run", "stop", "send", "recv", "settimeout",
        "fileno", "shutdown", "popleft", "appendleft", "partition",
        "mark", "match", "fullmatch", "search", "findall", "group",
    }
)


@dataclass
class DefInfo:
    """One function/method definition."""

    key: str  # "rel:Qual.name"
    rel: str
    module: ModuleInfo
    qual: str  # "Class.method" or "func"
    name: str
    cls: Optional[str]
    node: ast.AST
    line: int
    # resolved callee keys for calls anywhere in the def (nested
    # defs/lambdas included)
    callees: Set[str] = field(default_factory=set)


class _ClassIndex:
    def __init__(self):
        # class name -> (rel, bases, {method name -> def key})
        self.classes: Dict[str, List[dict]] = {}

    def add(self, name: str, rel: str, bases: List[str]):
        entry = {"rel": rel, "bases": bases, "methods": {}}
        self.classes.setdefault(name, []).append(entry)
        return entry

    def method(self, cls_name: str, attr: str, _seen=None) -> List[str]:
        """Def keys for ``cls_name.attr``, walking by-name bases within
        the package (first match per class entry wins, like the MRO)."""
        _seen = _seen if _seen is not None else set()
        if cls_name in _seen:
            return []
        _seen.add(cls_name)
        out: List[str] = []
        for entry in self.classes.get(cls_name, ()):
            if attr in entry["methods"]:
                out.append(entry["methods"][attr])
                continue
            for base in entry["bases"]:
                got = self.method(base, attr, _seen)
                if got:
                    out.extend(got)
                    break
        return out


class CallGraph:
    def __init__(self, snap: PackageSnapshot):
        self.snap = snap
        self.defs: Dict[str, DefInfo] = {}
        self.by_name: Dict[str, List[str]] = {}
        self.classes = _ClassIndex()
        # per-module: imported name -> dotted module ("from m import f"
        # maps f -> (module, f); "import m as a" maps a -> (module, None))
        self._imports: Dict[str, Dict[str, Tuple[str, Optional[str]]]] = {}
        self._module_by_dotted = {
            info.name: info for info in snap.modules.values()
        }
        for info in snap.modules.values():
            self._index_module(info)
        for info in snap.modules.values():
            self._link_module(info)

    # ------------------------------------------------------------- indexing
    def _index_module(self, info: ModuleInfo) -> None:
        imports: Dict[str, Tuple[str, Optional[str]]] = {}
        pkg = self.snap.package

        # imports are collected over the WHOLE module (function-level
        # lazy imports included — this repo uses them heavily), scoped
        # module-wide as a deliberate over-approximation
        for child in ast.walk(info.tree):
            if isinstance(child, ast.Import):
                for alias in child.names:
                    if alias.name.split(".")[0] == pkg:
                        imports[alias.asname or alias.name.split(".")[0]] = (
                            alias.name, None,
                        )
            elif isinstance(child, ast.ImportFrom):
                mod = child.module or ""
                if child.level:  # relative import -> absolute
                    base = info.name.split(".")
                    # a package __init__'s dotted name is the package
                    # itself (".__init__" stripped), so level 1 keeps
                    # the full name; plain modules drop one more part
                    is_pkg = info.rel.endswith("/__init__.py")
                    drop = child.level - 1 if is_pkg else child.level
                    base = base[: len(base) - drop] if drop else base
                    mod = ".".join(base + ([mod] if mod else []))
                if mod.split(".")[0] == pkg:
                    for alias in child.names:
                        imports[alias.asname or alias.name] = (
                            mod, alias.name,
                        )

        def walk(node, scope: List[str], cls_entry):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    bases = [
                        b.id if isinstance(b, ast.Name) else b.attr
                        for b in child.bases
                        if isinstance(b, (ast.Name, ast.Attribute))
                    ]
                    entry = self.classes.add(child.name, info.rel, bases)
                    walk(child, scope + [child.name], entry)
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    qual = ".".join(scope + [child.name])
                    key = f"{info.rel}:{qual}"
                    cls = scope[-1] if scope else None
                    self.defs[key] = DefInfo(
                        key=key, rel=info.rel, module=info, qual=qual,
                        name=child.name, cls=cls, node=child,
                        line=child.lineno,
                    )
                    self.by_name.setdefault(child.name, []).append(key)
                    if cls_entry is not None:
                        cls_entry["methods"].setdefault(child.name, key)
                    # nested defs are attributed to the enclosing def:
                    # do NOT recurse into child here — _link walks the
                    # full body including nested defs
                else:
                    walk(child, scope, cls_entry)

        walk(info.tree, [], None)
        self._imports[info.rel] = imports

    # -------------------------------------------------------------- linking
    def resolve_call(
        self,
        node: ast.Call,
        info: ModuleInfo,
        cls: Optional[str],
        strict: bool = False,
    ) -> List[str]:
        """Callee def keys for one Call node (possibly empty).

        ``strict=True`` drops the global by-attribute-name fallback:
        only self/cls/super and module-resolved calls link.  The lock
        analyzers use strict resolution — a lock region reaching every
        same-named method in the package would drown the real convoys
        in cross-object noise; reachability keeps the sound default."""
        imports = self._imports[info.rel]
        f = node.func
        if isinstance(f, ast.Name):
            name = f.id
            if name in imports:
                mod, attr = imports[name]
                target = self._module_by_dotted.get(mod)
                if target is not None and attr is not None:
                    key = f"{target.rel}:{attr}"
                    return [key] if key in self.defs else []
                return []
            key = f"{info.rel}:{name}"
            return [key] if key in self.defs else []
        if isinstance(f, ast.Attribute):
            attr = f.attr
            value = f.value
            if isinstance(value, ast.Name):
                if value.id in ("self", "cls") and cls is not None:
                    got = self.classes.method(cls, attr)
                    if got:
                        return got
                elif value.id in imports:
                    mod, sub = self._imports[info.rel][value.id]
                    target = self._module_by_dotted.get(mod)
                    if target is not None:
                        key = f"{target.rel}:{attr}"
                        return [key] if key in self.defs else []
                    return []
            # super().m(...): the enclosing class's by-name bases
            if (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id == "super"
                and cls is not None
            ):
                out: List[str] = []
                for entry in self.classes.classes.get(cls, ()):
                    for base in entry["bases"]:
                        out.extend(self.classes.method(base, attr))
                return out
            if strict:
                return []
            if attr.startswith("__") or attr in ATTR_STOPLIST:
                return []
            return list(self.by_name.get(attr, ()))
        return []

    def _link_module(self, info: ModuleInfo) -> None:
        for d in self.defs.values():
            if d.rel != info.rel:
                continue
            for node in ast.walk(d.node):
                if isinstance(node, ast.Call):
                    d.callees.update(self.resolve_call(node, info, d.cls))

    # ---------------------------------------------------------- reachability
    def reachable_from(self, keys: Iterable[str]) -> Dict[str, List[str]]:
        """BFS closure: def key -> shortest call path (list of keys,
        root first) for every def reachable from ``keys``."""
        paths: Dict[str, List[str]] = {}
        frontier: List[str] = []
        for k in keys:
            if k in self.defs and k not in paths:
                paths[k] = [k]
                frontier.append(k)
        while frontier:
            nxt: List[str] = []
            for k in frontier:
                for callee in sorted(self.defs[k].callees):
                    if callee not in paths:
                        paths[callee] = paths[k] + [callee]
                        nxt.append(callee)
            frontier = nxt
        return paths

    def render_path(self, path: List[str]) -> str:
        return " -> ".join(
            f"{self.defs[k].rel}:{self.defs[k].qual}" for k in path
        )


# one-entry memo: (snapshot, its graph).  The snapshot is held by
# STRONG reference on purpose — an id()-keyed cache would go stale the
# moment a collected snapshot's address is reused by a new one.
_GRAPH_CACHE: List[Tuple[PackageSnapshot, CallGraph]] = []


def call_graph(snap: PackageSnapshot) -> CallGraph:
    """Snapshot-keyed memo: the lock and reachability rules share one
    graph build per lint run."""
    if _GRAPH_CACHE and _GRAPH_CACHE[0][0] is snap:
        return _GRAPH_CACHE[0][1]
    got = CallGraph(snap)
    _GRAPH_CACHE.clear()  # one live snapshot at a time
    _GRAPH_CACHE.append((snap, got))
    return got
