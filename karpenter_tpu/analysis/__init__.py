"""Whole-program static analysis plane (docs/designs/static-analysis.md).

Every correctness guarantee this repo leans on — byte-identical sim
replay, identical twin-run actions under pipelining, zero verdict
mismatches, honest transfer accounting — is machine-checked here.  The
subsystem has four parts:

- **Rule engine** (core.py): rules are registered classes over a shared
  parsed-AST snapshot of the package (`PackageSnapshot`), findings are
  structured records with stable fingerprints, per-rule allowlists live
  in ONE declarative table (allowlists.py), and a baseline file can
  suppress known findings without deleting the signal.  The CLI is
  ``python -m karpenter_tpu lint [--json] [--rule NAME]``.
- **Lock-discipline analyzer** (locks.py): discovers the package's lock
  attributes, flags blocking operations reachable inside a held-lock
  region, and proves there is no inconsistent acquisition order between
  any two locks in the store/pipeline/operator layers.
- **Determinism-reachability analyzer** (reachability.py): builds an
  intra-package call graph and proves nothing reachable from the
  byte-compared surfaces (sim trace digests, ledger lines, SLO report,
  twin-run adoption) can reach a tainted source — wall clock, unseeded
  random, os.environ — outside the sanctioned-sink list.
- **Tracer-safety analyzer** (tracer.py): every ``jax.jit`` callable is
  discovered from its decorator/binding and must be dispatched through
  the device observatory's counted seam, with no host-side mutation,
  ``time.*`` or ``print`` inside traced bodies.

The 11 legacy lint rules (tests/test_lint.py's original suite) are
ported onto the engine in rules_legacy.py with their allowlists intact.
"""

# The package body imports NOTHING eagerly: every production module now
# imports analysis.sanitizer (the lock construction seam), which runs
# this __init__ — pulling the whole rule engine in eagerly would tax
# every process start and plant a circular-import trap for any future
# rule module that imports production code.  The engine surface loads on
# first attribute access (PEP 562) instead.

_CORE_EXPORTS = frozenset(
    {
        "Finding",
        "PackageSnapshot",
        "Rule",
        "RULES",
        "load_baseline",
        "register",
        "run_rules",
        "to_report",
    }
)


def load_rules() -> None:
    """Import every rule module (idempotent): RULES is complete after.
    Called by __getattr__ below and by core.run_rules, so a direct
    ``from karpenter_tpu.analysis.core import run_rules`` can never run
    against a half-registered catalog."""
    from karpenter_tpu.analysis import (  # noqa: F401
        locks,
        reachability,
        rules_legacy,
        settings_flow,
        tenant_metrics,
        tracer,
    )


def __getattr__(name: str):
    if name in _CORE_EXPORTS:
        load_rules()
        from karpenter_tpu.analysis import core

        return getattr(core, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
