"""Whole-program static analysis plane (docs/designs/static-analysis.md).

Every correctness guarantee this repo leans on — byte-identical sim
replay, identical twin-run actions under pipelining, zero verdict
mismatches, honest transfer accounting — is machine-checked here.  The
subsystem has four parts:

- **Rule engine** (core.py): rules are registered classes over a shared
  parsed-AST snapshot of the package (`PackageSnapshot`), findings are
  structured records with stable fingerprints, per-rule allowlists live
  in ONE declarative table (allowlists.py), and a baseline file can
  suppress known findings without deleting the signal.  The CLI is
  ``python -m karpenter_tpu lint [--json] [--rule NAME]``.
- **Lock-discipline analyzer** (locks.py): discovers the package's lock
  attributes, flags blocking operations reachable inside a held-lock
  region, and proves there is no inconsistent acquisition order between
  any two locks in the store/pipeline/operator layers.
- **Determinism-reachability analyzer** (reachability.py): builds an
  intra-package call graph and proves nothing reachable from the
  byte-compared surfaces (sim trace digests, ledger lines, SLO report,
  twin-run adoption) can reach a tainted source — wall clock, unseeded
  random, os.environ — outside the sanctioned-sink list.
- **Tracer-safety analyzer** (tracer.py): every ``jax.jit`` callable is
  discovered from its decorator/binding and must be dispatched through
  the device observatory's counted seam, with no host-side mutation,
  ``time.*`` or ``print`` inside traced bodies.

The 11 legacy lint rules (tests/test_lint.py's original suite) are
ported onto the engine in rules_legacy.py with their allowlists intact.
"""

from karpenter_tpu.analysis.core import (  # noqa: F401
    Finding,
    PackageSnapshot,
    Rule,
    RULES,
    load_baseline,
    register,
    run_rules,
    to_report,
)

# registering imports: each module's import populates RULES
from karpenter_tpu.analysis import rules_legacy  # noqa: F401,E402
from karpenter_tpu.analysis import locks  # noqa: F401,E402
from karpenter_tpu.analysis import reachability  # noqa: F401,E402
from karpenter_tpu.analysis import tracer  # noqa: F401,E402
