"""Rule 12: service-plane metric emissions carry a ``tenant`` label.

The multi-tenant SolverService's observability contract (docs/designs/
solver-service.md): every metrics family the service plane emits is
tenant-attributed, so one tenant's traffic can never hide inside another
tenant's series — the isolation half of "one mesh serving a fleet".
Machine-checked, the way rule 5 guards the metrics doc:

- every registry WRITE verb (inc / set / observe / time / unset /
  reset_gauge) in a ``service/`` module whose metric-name literal starts
  with ``karpenter_service_`` must pass a labels dict literal containing
  a ``"tenant"`` key at the emission site;
- and the family must appear in docs/metrics.md (regenerate with
  ``python -m karpenter_tpu.tools.gen_metrics_doc``) — a tenant-labeled
  series that ships undocumented is only half-observable.

The allowlist names ``service/`` files exempt because they are a
DIFFERENT plane (the store servers: one shared cluster store per
deployment, tenant-less by design) — path strings, each argued in
allowlists.py.  Dynamic names (``reg.inc(name)``) are out of scope, as
in rule 5: a computed family name is already unlintable there too.
"""

from __future__ import annotations

import ast
import re
from typing import List

from karpenter_tpu.analysis.core import Finding, Rule, register

_WRITE_VERBS = frozenset(
    {"inc", "set", "observe", "time", "unset", "reset_gauge"}
)
_SERVICE_PREFIX = "karpenter_service_"


def _has_tenant_labels(call: ast.Call) -> bool:
    """True when some argument (positional or ``labels=``) is a dict
    literal carrying a literal ``"tenant"`` key."""
    candidates = list(call.args[1:]) + [
        kw.value for kw in call.keywords if kw.arg == "labels"
    ]
    for arg in candidates:
        if isinstance(arg, ast.Dict) and any(
            isinstance(k, ast.Constant) and k.value == "tenant"
            for k in arg.keys
        ):
            return True
    return False


@register
class ServiceTenantMetricsRule(Rule):
    """Every karpenter_service_* emission in service/ is tenant-labeled
    and documented."""

    name = "service-tenant-metrics"
    title = "service-plane metric emissions tenant-labeled and documented"
    guards = (
        "per-tenant observability isolation (no tenant-blind service "
        "series can ship)"
    )

    def check(self, snap, allowlist) -> List[Finding]:
        documented = set(
            re.findall(
                r"`(karpenter_[a-z0-9_]+)`",
                snap.doc_text("docs", "metrics.md"),
            )
        )
        out: List[Finding] = []
        for info in snap.in_package():
            if not info.rel_in_pkg.startswith("service/"):
                continue
            if info.rel in allowlist or info.rel_in_pkg in allowlist:
                continue
            for node in ast.walk(info.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _WRITE_VERBS
                    and node.args
                ):
                    continue
                first = node.args[0]
                if not (
                    isinstance(first, ast.Constant)
                    and isinstance(first.value, str)
                    and first.value.startswith(_SERVICE_PREFIX)
                ):
                    continue
                fam = first.value
                if not _has_tenant_labels(node):
                    out.append(
                        self.finding(
                            info.rel, node.lineno,
                            f"{fam!r} emitted without a 'tenant' label "
                            "— a tenant-blind service series breaks the "
                            "per-tenant observability isolation "
                            "contract; pass an inline labels dict with "
                            "a 'tenant' key",
                        )
                    )
                if fam not in documented and fam not in allowlist:
                    out.append(
                        self.finding(
                            info.rel, node.lineno,
                            f"{fam!r} absent from docs/metrics.md — "
                            "regenerate with `python -m karpenter_tpu."
                            "tools.gen_metrics_doc`",
                        )
                    )
        return out
