"""``python -m karpenter_tpu lint`` — the static-analysis CLI.

Exit codes: 0 clean (no non-baselined findings), 1 findings, 2 internal
error (the analyzer itself broke — CI must distinguish "violations"
from "the checker is down").

``--json`` emits the stable, sorted report schema (core.to_report) so
CI diffs are deterministic; ``--profile`` adds per-rule wall timings (to
stderr in text mode, under ``timings_s`` in JSON mode) so a slow rule
cannot silently balloon tier-1 time.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m karpenter_tpu lint",
        description="whole-program static analysis over the package "
        "(docs/designs/static-analysis.md)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="emit the stable machine-readable report on stdout",
    )
    parser.add_argument(
        "--rule", action="append", default=[], metavar="NAME",
        help="run only this rule (repeatable); default: all registered",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog (name, title, guarded guarantee)",
    )
    parser.add_argument(
        "--profile", action="store_true",
        help="print per-rule wall timings",
    )
    parser.add_argument(
        "--root", default="", metavar="DIR",
        help="package directory to lint (default: the installed "
        "karpenter_tpu package)",
    )
    parser.add_argument(
        "--baseline", default="", metavar="FILE",
        help="suppression file (default: <package>/analysis/"
        "baseline.json)",
    )
    args = parser.parse_args(argv)

    try:
        from karpenter_tpu.analysis import (
            PackageSnapshot,
            RULES,
            load_baseline,
            run_rules,
            to_report,
        )
        from karpenter_tpu.analysis.core import default_baseline_path

        if args.list_rules:
            for name in sorted(RULES):
                rule = RULES[name]
                print(f"{name:28s} {rule.title}")
                print(f"{'':28s}   guards: {rule.guards}")
            return 0

        snap = PackageSnapshot.load(
            pathlib.Path(args.root) if args.root else None
        )
        baseline_path = (
            pathlib.Path(args.baseline)
            if args.baseline
            else default_baseline_path(snap)
        )
        baseline = load_baseline(baseline_path)
        timings = {} if args.profile else None
        live, suppressed = run_rules(
            snap,
            rule_names=args.rule or None,
            baseline=baseline,
            timings=timings,
        )
    except Exception as exc:  # the checker itself broke: exit 2
        print(f"lint internal error: {type(exc).__name__}: {exc}",
              file=sys.stderr)
        return 2

    rule_names = args.rule or sorted(RULES)
    if args.json:
        print(
            json.dumps(
                to_report(snap, live, suppressed, rule_names, timings),
                indent=2,
                sort_keys=True,
            )
        )
    else:
        for f in live:
            print(f.render())
        if suppressed:
            print(f"({len(suppressed)} baselined finding(s) suppressed)")
        print(
            f"lint: {len(live)} finding(s), {len(suppressed)} baselined, "
            f"{len(rule_names)} rule(s)"
        )
        if timings is not None:
            for name, dt in sorted(
                timings.items(), key=lambda kv: -kv[1]
            ):
                print(f"  {name:28s} {dt * 1000:8.1f} ms", file=sys.stderr)
    return 1 if live else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
