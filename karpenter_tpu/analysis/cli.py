"""``python -m karpenter_tpu lint`` — the static-analysis CLI.

Exit codes: 0 clean (no non-baselined findings), 1 findings, 2 internal
error (the analyzer itself broke — CI must distinguish "violations"
from "the checker is down").

``--json`` emits the stable, sorted report schema (core.to_report) so
CI diffs are deterministic; ``--profile`` adds per-rule wall timings (to
stderr in text mode, under ``timings_s`` in JSON mode) so a slow rule
cannot silently balloon tier-1 time.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m karpenter_tpu lint",
        description="whole-program static analysis over the package "
        "(docs/designs/static-analysis.md)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="emit the stable machine-readable report on stdout",
    )
    parser.add_argument(
        "--rule", action="append", default=[], metavar="NAME",
        help="run only this rule (repeatable); default: all registered",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog (name, title, guarded guarantee)",
    )
    parser.add_argument(
        "--profile", action="store_true",
        help="print per-rule wall timings",
    )
    parser.add_argument(
        "--root", default="", metavar="DIR",
        help="package directory to lint (default: the installed "
        "karpenter_tpu package)",
    )
    parser.add_argument(
        "--baseline", default="", metavar="FILE",
        help="suppression file (default: <package>/analysis/"
        "baseline.json)",
    )
    parser.add_argument(
        "--witness", default="", metavar="FILE",
        help="runtime witness artifact (a sanitized run's serialized "
        "lock graph, analysis/witness.py): merge its runtime edges "
        "into the static order graph and report both directions — a "
        "runtime edge the static model never predicted is a FINDING "
        "(static-model incompleteness), a static edge never exercised "
        "is an informational coverage gap",
    )
    args = parser.parse_args(argv)

    try:
        from karpenter_tpu.analysis import (
            PackageSnapshot,
            RULES,
            load_baseline,
            run_rules,
            to_report,
        )
        from karpenter_tpu.analysis.core import default_baseline_path

        if args.list_rules:
            for name in sorted(RULES):
                rule = RULES[name]
                print(f"{name:28s} {rule.title}")
                print(f"{'':28s}   guards: {rule.guards}")
            return 0

        snap = PackageSnapshot.load(
            pathlib.Path(args.root) if args.root else None
        )
        baseline_path = (
            pathlib.Path(args.baseline)
            if args.baseline
            else default_baseline_path(snap)
        )
        baseline = load_baseline(baseline_path)
        timings = {} if args.profile else None
        live, suppressed = run_rules(
            snap,
            rule_names=args.rule or None,
            baseline=baseline,
            timings=timings,
        )
        witness_section = None
        if args.witness:
            witness_section, live = _cross_validate(
                snap, pathlib.Path(args.witness), live
            )
    except Exception as exc:  # the checker itself broke: exit 2
        print(f"lint internal error: {type(exc).__name__}: {exc}",
              file=sys.stderr)
        return 2

    rule_names = args.rule or sorted(RULES)
    if args.json:
        report = to_report(snap, live, suppressed, rule_names, timings)
        if witness_section is not None:
            report["witness"] = witness_section
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for f in live:
            print(f.render())
        if suppressed:
            print(f"({len(suppressed)} baselined finding(s) suppressed)")
        print(
            f"lint: {len(live)} finding(s), {len(suppressed)} baselined, "
            f"{len(rule_names)} rule(s)"
        )
        if witness_section is not None:
            cv = witness_section["cross_validation"]
            print(
                f"witness {witness_section['fingerprint']} "
                f"({witness_section['scenario']}): "
                f"{len(cv['confirmed'])} edge(s) confirmed, "
                f"{len(cv['missing_static'])} missing from the static "
                f"model, {len(cv['unexercised_static'])} static edge(s) "
                f"unexercised (coverage gap), "
                f"{len(cv['unmodeled'])} out-of-layer"
            )
        if timings is not None:
            for name, dt in sorted(
                timings.items(), key=lambda kv: -kv[1]
            ):
                print(f"  {name:28s} {dt * 1000:8.1f} ms", file=sys.stderr)
    return 1 if live else 0


def _cross_validate(snap, witness_path, live):
    """Merge a witness into the static order graph.  Runtime-only edges
    (minus allowlists.WITNESS_EDGES) become live ``witness-gap``
    findings; everything else lands in the report's informational
    ``witness`` section."""
    from karpenter_tpu.analysis.allowlists import WITNESS_EDGES
    from karpenter_tpu.analysis.core import Finding
    from karpenter_tpu.analysis.locks import static_order_edges
    from karpenter_tpu.analysis.witness import Witness, cross_validate

    witness = Witness.load(witness_path)
    edges, universe = static_order_edges(snap)
    cv = cross_validate(witness, edges, universe, WITNESS_EDGES)
    for entry in cv.missing_static:
        site = entry["sites"][0] if entry["sites"] else "?"
        live.append(
            Finding(
                rule="witness-gap",
                file=site.split(":", 1)[0],
                line=0,
                message=(
                    f"runtime lock-order edge {entry['outer']} -> "
                    f"{entry['inner']} (witnessed at {site}) is absent "
                    "from the static order graph — the static model is "
                    "incomplete for this path (or a seam lock name "
                    "drifted); fix the resolution or allowlist the "
                    "edge in WITNESS_EDGES with an argument"
                ),
            )
        )
    section = {
        "scenario": witness.scenario,
        "fingerprint": witness.fingerprint,
        "findings_in_witness": len(witness.findings),
        "cross_validation": cv.to_dict(),
    }
    return section, sorted(live)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
