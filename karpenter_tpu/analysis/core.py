"""Rule engine: parsed-package snapshot, rule registry, findings.

The engine is deliberately import-light: a snapshot is pure ``ast`` over
the package's source files (no module execution), so most rules run in
milliseconds and the CLI can lint a tree that does not even import.  The
two runtime rules (import-clean, annotations-resolve) import the package
explicitly and say so.

Findings carry a stable fingerprint — sha256 over (rule, file, message),
deliberately excluding the line number — so a baseline survives unrelated
line drift and the ``--json`` output diffs deterministically in CI.
"""

from __future__ import annotations

import ast
import hashlib
import json
import pathlib
import time
from dataclasses import dataclass, replace
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

BASELINE_NAME = "baseline.json"


@dataclass(frozen=True, order=True)
class Finding:
    """One structured finding.  Ordering is (rule, file, line, message)
    so sorted finding lists — and therefore the JSON report — are
    deterministic.

    ``occurrence`` disambiguates IDENTICAL (rule, file, message)
    findings by line order — the runner stamps it — so baselining one
    known instance cannot silently suppress a new duplicate added
    later; the fingerprint still survives mere line drift."""

    rule: str
    file: str
    line: int
    message: str
    occurrence: int = 0

    @property
    def fingerprint(self) -> str:
        digest = hashlib.sha256(
            f"{self.rule}|{self.file}|{self.message}|{self.occurrence}"
            .encode()
        ).hexdigest()
        return digest[:16]

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "file": self.file,
            "line": self.line,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class ModuleInfo:
    """One parsed source file of the snapshot."""

    rel: str  # repo-relative posix path ("karpenter_tpu/pipeline.py")
    name: str  # dotted module name ("karpenter_tpu.pipeline")
    path: pathlib.Path
    source: str
    tree: ast.Module

    @property
    def rel_in_pkg(self) -> str:
        """Path relative to the package directory ("pipeline.py",
        "service/store_server.py") — what scope predicates match on, so
        synthetic test trees with a different package name still scope
        identically."""
        return self.rel.partition("/")[2]


class PackageSnapshot:
    """Parsed-AST view of one package directory.

    ``root`` is the package directory; ``repo_root`` is its parent (doc
    files are resolved against it, and ``rel`` paths are repo-relative
    to match the historical allowlist entries).  A file that fails to
    parse becomes a ``parse`` finding instead of aborting the snapshot —
    the engine must be able to report on a broken tree.
    """

    def __init__(self, root: pathlib.Path):
        self.root = pathlib.Path(root)
        self.repo_root = self.root.parent
        self.package = self.root.name
        self.modules: Dict[str, ModuleInfo] = {}
        self.parse_errors: List[Finding] = []
        for path in sorted(self.root.rglob("*.py")):
            if "__pycache__" in path.parts:
                continue
            rel = path.relative_to(self.repo_root).as_posix()
            source = path.read_text()
            try:
                tree = ast.parse(source)
            except SyntaxError as exc:
                self.parse_errors.append(
                    Finding(
                        rule="parse",
                        file=rel,
                        line=exc.lineno or 1,
                        message=f"syntax error: {exc.msg}",
                    )
                )
                continue
            name = rel[: -len(".py")].replace("/", ".")
            if name.endswith(".__init__"):
                name = name[: -len(".__init__")]
            self.modules[rel] = ModuleInfo(
                rel=rel, name=name, path=path, source=source, tree=tree
            )

    @classmethod
    def load(cls, root: Optional[pathlib.Path] = None) -> "PackageSnapshot":
        if root is None:
            import karpenter_tpu

            root = pathlib.Path(karpenter_tpu.__path__[0])
        return cls(pathlib.Path(root))

    def module_names(self) -> List[str]:
        return sorted(m.name for m in self.modules.values())

    def in_package(self, *rel_in_pkg: str):
        """Modules whose package-relative path starts with any given
        prefix (e.g. ``in_package("controllers/")``); no args = all."""
        for rel in sorted(self.modules):
            info = self.modules[rel]
            if not rel_in_pkg or any(
                info.rel_in_pkg == p or info.rel_in_pkg.startswith(p)
                for p in rel_in_pkg
            ):
                yield info

    def doc_text(self, *parts: str) -> str:
        """A repo doc file's text, empty when absent (synthetic trees)."""
        path = self.repo_root.joinpath(*parts)
        return path.read_text() if path.exists() else ""


class Rule:
    """Base class: subclasses register with :func:`register` and
    implement ``check``.  ``allowlist`` is the rule's entry from the ONE
    declarative table (allowlists.py) — its element type is rule-defined
    (rel paths, ``(rel, qualname)`` tuples, lock-pair ids, ...)."""

    name: str = ""
    title: str = ""  # one-line catalog entry
    guards: str = ""  # the guarantee this rule protects

    def check(
        self, snap: PackageSnapshot, allowlist: frozenset
    ) -> List[Finding]:
        raise NotImplementedError

    def finding(self, rel: str, line: int, message: str) -> Finding:
        return Finding(rule=self.name, file=rel, line=line, message=message)


RULES: Dict[str, type] = {}


def register(cls):
    assert cls.name and cls.name not in RULES, cls
    RULES[cls.name] = cls
    return cls


class ScopedVisitor(ast.NodeVisitor):
    """Shared visitor: tracks the class/function scope stack and the
    lexical loop depth — the qualified-name + in-loop machinery every
    call-site rule shares.  Subclasses override ``on_call``."""

    def __init__(self):
        self.scope: List[str] = []
        self.loops = 0

    @property
    def qual(self) -> str:
        return ".".join(self.scope)

    def _scoped(self, node):
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    visit_ClassDef = _scoped
    visit_FunctionDef = _scoped
    visit_AsyncFunctionDef = _scoped

    def _loop(self, node):
        self.loops += 1
        self.generic_visit(node)
        self.loops -= 1

    visit_For = visit_While = visit_AsyncFor = _loop

    def visit_Call(self, node):
        self.on_call(node)
        self.generic_visit(node)

    def on_call(self, node: ast.Call) -> None:  # pragma: no cover
        pass


def call_name(node: ast.Call) -> Optional[str]:
    """The called name for bare (``f(...)``) and attribute
    (``x.f(...)``) call forms."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


# --------------------------------------------------------------- baseline
def load_baseline(path: Optional[pathlib.Path]) -> Dict[str, str]:
    """fingerprint -> note.  Missing file = empty baseline."""
    if path is None or not pathlib.Path(path).exists():
        return {}
    data = json.loads(pathlib.Path(path).read_text())
    return {
        entry["fingerprint"]: entry.get("note", "")
        for entry in data.get("suppressions", [])
    }


def default_baseline_path(snap: PackageSnapshot) -> pathlib.Path:
    return snap.root / "analysis" / BASELINE_NAME


# ------------------------------------------------------------------ runner
def run_rules(
    snap: PackageSnapshot,
    rule_names: Optional[Sequence[str]] = None,
    allowlists: Optional[Dict[str, frozenset]] = None,
    baseline: Optional[Dict[str, str]] = None,
    timings: Optional[Dict[str, float]] = None,
) -> Tuple[List[Finding], List[Finding]]:
    """Run the selected rules (default: all registered) and split the
    sorted findings into (live, baselined).  ``timings`` — when a dict
    is passed — receives per-rule wall seconds (the ``--profile``
    surface; never part of the deterministic JSON)."""
    from karpenter_tpu.analysis import load_rules

    load_rules()  # the registry must be complete, however we were imported
    if allowlists is None:
        from karpenter_tpu.analysis.allowlists import ALLOWLISTS

        allowlists = ALLOWLISTS
    baseline = baseline or {}
    all_rules = rule_names is None or not rule_names
    names = list(rule_names) if rule_names else sorted(RULES)
    unknown = [n for n in names if n not in RULES]
    if unknown:
        raise KeyError(f"unknown rule(s): {', '.join(unknown)}")
    if timings is not None and any(
        n in ("lock-blocking", "lock-order") for n in names
    ):
        # --profile attribution fix: the lock rules share ONE memoized
        # region scan (locks.region_scan), so whichever rule ran first
        # used to absorb the whole scan's wall time and the others read
        # as free — profile numbers did not reflect real cost.  Warm the
        # shared scan OUTSIDE the per-rule timers and report it as its
        # own line; per-rule numbers are then each rule's marginal cost.
        from karpenter_tpu.analysis.locks import region_scan

        t0 = time.perf_counter()
        region_scan(snap).scan_regions()
        timings["shared-scan"] = time.perf_counter() - t0
    findings: List[Finding] = list(snap.parse_errors)
    for name in names:
        rule = RULES[name]()
        t0 = time.perf_counter()
        findings.extend(
            rule.check(snap, frozenset(allowlists.get(name, frozenset())))
        )
        if timings is not None:
            timings[name] = time.perf_counter() - t0
    findings.sort()
    # stamp occurrence indexes (line order) onto identical
    # (rule, file, message) findings so their fingerprints differ
    counts: Dict[Tuple[str, str, str], int] = {}
    stamped: List[Finding] = []
    for f in findings:
        key = (f.rule, f.file, f.message)
        n = counts.get(key, 0)
        counts[key] = n + 1
        stamped.append(replace(f, occurrence=n) if n else f)
    findings = stamped
    if baseline and all_rules:
        # stale-baseline hygiene: a suppression whose fingerprint matches
        # no current finding is itself a finding — otherwise a fixed
        # violation's entry rots silently (and keeps reviewers trusting a
        # suppression list that no longer suppresses anything).  Checked
        # against the occurrence-stamped fingerprints (what baselines
        # store), and only when the FULL rule set ran: a --rule subset
        # cannot judge entries owned by rules it did not run.
        matched = {f.fingerprint for f in findings}
        stale = [
            Finding(
                rule="stale-baseline",
                file=f"{snap.package}/analysis/{BASELINE_NAME}",
                line=1,
                message=(
                    f"baseline entry {fp} ({baseline[fp] or 'no note'}) "
                    "matches no current finding — the suppressed "
                    "violation is gone; delete the entry"
                ),
            )
            for fp in sorted(baseline)
            if fp not in matched
        ]
        if stale:
            findings = sorted(findings + stale)
    live = [f for f in findings if f.fingerprint not in baseline]
    suppressed = [f for f in findings if f.fingerprint in baseline]
    return live, suppressed


def to_report(
    snap: PackageSnapshot,
    live: Iterable[Finding],
    suppressed: Iterable[Finding],
    rule_names: Sequence[str],
    timings: Optional[Dict[str, float]] = None,
) -> dict:
    """The stable ``--json`` schema: versioned, keys sorted by the
    emitter, finding lists pre-sorted.  ``timings`` appears only under
    ``--profile`` (wall clock is deliberately not in the default,
    CI-diffable report)."""
    live, suppressed = sorted(live), sorted(suppressed)
    report = {
        "version": 1,
        "package": snap.package,
        "rules": sorted(rule_names),
        "counts": {
            "findings": len(live),
            "baselined": len(suppressed),
        },
        "findings": [f.to_dict() for f in live],
        "baselined": [f.to_dict() for f in suppressed],
    }
    if timings is not None:
        report["timings_s"] = {
            name: round(dt, 6) for name, dt in sorted(timings.items())
        }
    return report
