"""Lock-discipline analyzers (the PR 12/13 review bug classes, CI-checked).

Two rules over a discovered lock model:

- **lock-blocking**: a blocking operation — socket I/O, a store RPC,
  payload encoding, a device fetch, a ``run_concurrently`` join —
  reachable while a ``threading.Lock/RLock/Condition`` is held.  This is
  exactly the class PR 12's review caught by hand (a JSON snapshot
  encoded under the store lock): a blocking call under a hot lock turns
  every other thread's cheap critical section into a convoy.
- **lock-order**: two locks acquired in both nesting orders anywhere in
  the analyzed layers — the cross-thread deadlock seam PR 13's pipeline
  introduced a whole new class of.

The model is discovery-driven: lock attributes are found from
``self.X = threading.Lock()/RLock()/Condition(...)`` assignments, a
``Condition(self.Y)`` aliases onto Y, and cross-class aliases the AST
cannot see (a Condition built over another object's lock) are declared
in allowlists.LOCK_ALIASES.  Reachability inside a held region follows
the shared call graph (graph.py) to a bounded depth, so a lock held
around ``self._flush_dirty()`` still sees the socket write three calls
down.

Lock identity: ``Class.attr`` when the attribute is resolvable to one
defining class, else ``?.attr``.  Ambiguous identities still get
blocking-scan coverage but are excluded from order edges — a false
inversion between two unrelated ``_lock`` attributes would be noise,
not signal.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from karpenter_tpu.analysis.core import (
    Finding,
    PackageSnapshot,
    Rule,
    call_name,
    register,
)
from karpenter_tpu.analysis.graph import CallGraph, call_graph

# lock constructors: the raw stdlib forms plus the sanitizer seam
# (analysis/sanitizer.py make_*) the package routes construction
# through — the kind is what Condition-aliasing keys on
LOCK_CTORS: Dict[str, str] = {
    "Lock": "Lock",
    "RLock": "RLock",
    "Condition": "Condition",
    "make_lock": "Lock",
    "make_rlock": "RLock",
    "make_condition": "Condition",
}

# blocking-call detectors: called name -> why it must not run under a
# lock.  Name-based on purpose — the package's own seams (send_frame,
# _rpc, run_concurrently) are the vocabulary the rule fences.
BLOCKING_CALLS: Dict[str, str] = {
    "send_frame": "socket send",
    "recv_frame": "socket recv",
    "sendall": "socket send",
    "create_connection": "socket connect",
    "encode_payload": "payload codec encode",
    "dumps": "json.dumps of a payload",
    "_rpc": "store RPC round trip",
    "block_until_ready": "device sync fetch",
    "device_get": "device fetch",
    "fetch_verdict_rows": "device fetch",
    "run_concurrently": "thread fan-out join",
}

# call-graph expansion depth inside a held region: deep enough for the
# lease -> flush -> forward -> rpc chain, bounded so name-resolution
# over-approximation cannot weld the whole package into one region
MAX_DEPTH = 5

# Bounded per-OBJECT codecs: one dataclass in, one small string/tree out.
# The blocking rule targets PAYLOAD-sized work (frames, snapshots) under
# a lock; a single-object canonical() IS the in-place-mutation detector
# the store mirror deliberately runs under its lock, so descending into
# it would flag the design itself.
BOUNDED_OPAQUE = frozenset({"canonical", "to_wire", "from_wire",
                            "materialize"})


def _blocking_reason(node: ast.Call) -> Optional[Tuple[str, str]]:
    name = call_name(node)
    if name is None or name not in BLOCKING_CALLS:
        return None
    if name == "dumps":
        f = node.func
        if not (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "json"
        ):
            return None
    return name, BLOCKING_CALLS[name]


@dataclass
class LockModel:
    """Discovered lock attributes + per-function lock/blocking facts."""

    # (class name, attr) -> kind ("Lock"/"RLock"/"Condition")
    owners: Dict[Tuple[str, str], str] = field(default_factory=dict)
    # attr -> defining class names
    by_attr: Dict[str, Set[str]] = field(default_factory=dict)
    # canonical id -> canonical id (Condition-over-lock aliases)
    aliases: Dict[str, str] = field(default_factory=dict)
    # (class name, attr) -> package-relative defining file (the
    # cross-validation universe filter keys on the defining layer)
    files: Dict[Tuple[str, str], str] = field(default_factory=dict)

    def canonical(self, lock_id: str) -> str:
        seen = set()
        while lock_id in self.aliases and lock_id not in seen:
            seen.add(lock_id)
            lock_id = self.aliases[lock_id]
        return lock_id

    def resolve(self, expr: ast.expr, cls: Optional[str]) -> Optional[str]:
        """Lock identity for a ``with EXPR:`` context expression, or
        None when EXPR is not a discovered lock attribute."""
        if not isinstance(expr, ast.Attribute):
            return None
        attr = expr.attr
        owners = self.by_attr.get(attr)
        if not owners:
            return None
        if (
            isinstance(expr.value, ast.Name)
            and expr.value.id in ("self", "cls")
            and cls in owners
        ):
            return self.canonical(f"{cls}.{attr}")
        if len(owners) == 1:
            return self.canonical(f"{next(iter(owners))}.{attr}")
        return f"?.{attr}"


def class_own_nodes(cls_node: ast.ClassDef):
    """Walk one class's OWN subtree, excluding nested ClassDefs — those
    are visited as classes in their own right by the caller's outer
    walk; descending into them here would attribute an inner class's
    lock assignments to the outer class (a phantom ``Outer.attr``
    identity next to the real ``Inner.attr``)."""
    stack = list(cls_node.body)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.ClassDef):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def build_lock_model(snap: PackageSnapshot, extra_aliases=None) -> LockModel:
    model = LockModel()
    for info in snap.in_package():
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for sub in class_own_nodes(node):
                if not (
                    isinstance(sub, ast.Assign)
                    and isinstance(sub.value, ast.Call)
                ):
                    continue
                ctor = call_name(sub.value)
                kind = LOCK_CTORS.get(ctor or "")
                if kind is None:
                    continue
                for target in sub.targets:
                    attr = None
                    if (
                        isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"
                    ):
                        attr = target.attr
                    elif isinstance(target, ast.Name):
                        attr = target.id
                    if attr is None:
                        continue
                    model.owners[(node.name, attr)] = kind
                    model.by_attr.setdefault(attr, set()).add(node.name)
                    model.files[(node.name, attr)] = info.rel_in_pkg
                    # Condition(self.X) / make_condition(name, self.X):
                    # alias onto the wrapped lock
                    wrap_idx = 1 if ctor == "make_condition" else 0
                    if (
                        kind == "Condition"
                        and len(sub.value.args) > wrap_idx
                    ):
                        arg = sub.value.args[wrap_idx]
                        if (
                            isinstance(arg, ast.Attribute)
                            and isinstance(arg.value, ast.Name)
                            and arg.value.id == "self"
                        ):
                            model.aliases[f"{node.name}.{attr}"] = (
                                f"{node.name}.{arg.attr}"
                            )
    for src, dst in (extra_aliases or {}).items():
        model.aliases[src] = dst
    return model


@dataclass
class _DefFacts:
    """Per-def direct facts (anywhere in the body)."""

    blocking: List[Tuple[str, str, int]] = field(default_factory=list)
    acquires: List[Tuple[str, int]] = field(default_factory=list)


class _RegionScan:
    """Held-region analysis over one snapshot: per-def facts plus a
    bounded-depth closure of what a held body reaches."""

    def __init__(self, snap: PackageSnapshot, model: LockModel,
                 graph: CallGraph):
        self.snap = snap
        self.model = model
        self.graph = graph
        self.facts: Dict[str, _DefFacts] = {}
        # strict callee sets (no global by-name fallback): lock regions
        # follow only calls the receiver provably owns
        self.strict_callees: Dict[str, Set[str]] = {}
        for key, d in graph.defs.items():
            facts = _DefFacts()
            callees: Set[str] = set()
            local_types = self._local_types(d)
            for node in ast.walk(d.node):
                if isinstance(node, ast.Call):
                    hit = _blocking_reason(node)
                    if hit:
                        facts.blocking.append((hit[0], hit[1], node.lineno))
                    callees.update(self._resolve(node, d, local_types))
                elif isinstance(node, ast.With):
                    for item in node.items:
                        lock = model.resolve(item.context_expr, d.cls)
                        if lock is not None:
                            facts.acquires.append((lock, node.lineno))
            self.facts[key] = facts
            self.strict_callees[key] = callees

    def _local_types(self, d) -> Dict[str, str]:
        """Constructor-based local type inference: ``bucket =
        _Bucket(...)`` binds bucket's class for the rest of the def, so
        ``bucket.add(...)`` resolves even though ``add`` is a stoplisted
        generic name — the runtime witness caught exactly this hole (the
        Batcher._lock -> _Bucket._cv edge was invisible statically).  A
        name rebound to DIFFERENT classes in one def is dropped as
        ambiguous."""
        out: Dict[str, str] = {}
        ambiguous: Set[str] = set()
        for node in ast.walk(d.node):
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
            ):
                continue
            cls = node.value.func.id
            if cls not in self.graph.classes.classes:
                continue
            var = node.targets[0].id
            if var in out and out[var] != cls:
                ambiguous.add(var)
            out[var] = cls
        for var in ambiguous:
            del out[var]
        return out

    def _resolve(self, node: ast.Call, d,
                 local_types: Dict[str, str]) -> List[str]:
        """Strict resolution plus the local constructor-type fallback."""
        got = self.graph.resolve_call(node, d.module, d.cls, strict=True)
        if got:
            return got
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id in local_types
        ):
            return self.graph.classes.method(
                local_types[f.value.id], f.attr
            )
        return []

    def region_calls(self, body: List[ast.stmt], d,
                     local_types: Optional[Dict[str, str]] = None) -> Set[str]:
        """Callee def keys for calls lexically inside a with-body."""
        if local_types is None:
            local_types = self._local_types(d)
        out: Set[str] = set()
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    out.update(self._resolve(node, d, local_types))
        return out

    def closure(self, keys: Set[str]) -> Dict[str, List[str]]:
        """key -> shortest path from the region, depth-bounded; bounded
        per-object codecs are opaque (never descended into)."""
        paths = {
            k: [k]
            for k in keys
            if k in self.graph.defs
            and self.graph.defs[k].name not in BOUNDED_OPAQUE
        }
        frontier = list(paths)
        for _ in range(MAX_DEPTH - 1):
            nxt = []
            for k in frontier:
                for callee in sorted(self.strict_callees[k]):
                    if (
                        callee not in paths
                        and self.graph.defs[callee].name
                        not in BOUNDED_OPAQUE
                    ):
                        paths[callee] = paths[k] + [callee]
                        nxt.append(callee)
            frontier = nxt
        return paths

    def _path_str(self, path: List[str]) -> str:
        return " -> ".join(self.graph.defs[k].qual for k in path)

    def scan_regions(self):
        """(def, lock_id, with_line, blocking hits, order edges) per held
        region, computed once per scan and cached — both lock rules read
        the same list.  Blocking hits: (op, reason, site, path str).
        Order edges: (inner lock, site, path str)."""
        cached = getattr(self, "_regions", None)
        if cached is None:
            cached = list(self._scan_regions())
            self._regions = cached
        return cached

    def _scan_regions(self):
        for key in sorted(self.graph.defs):
            d = self.graph.defs[key]
            for node in ast.walk(d.node):
                if not isinstance(node, ast.With):
                    continue
                resolved = [
                    self.model.resolve(item.context_expr, d.cls)
                    for item in node.items
                ]
                for idx, lock in enumerate(resolved):
                    if lock is None:
                        continue
                    blocking: List[Tuple[str, str, str, str]] = []
                    edges: List[Tuple[str, str, str]] = []
                    # sibling items of the SAME with acquire in item
                    # order: `with a, b:` is an a -> b edge exactly like
                    # the nested form
                    for later in resolved[idx + 1:]:
                        if later is not None and later != lock:
                            edges.append(
                                (later, f"{d.rel}:{node.lineno}", d.qual)
                            )
                    # site strings carry the FILE only, never the line:
                    # finding messages feed line-stable fingerprints
                    # (core.py's baseline contract), and the with-line
                    # on the Finding itself locates the region
                    # direct hits inside the body
                    for stmt in node.body:
                        for sub in ast.walk(stmt):
                            if isinstance(sub, ast.Call):
                                hit = _blocking_reason(sub)
                                if hit:
                                    blocking.append(
                                        (hit[0], hit[1], d.rel, d.qual)
                                    )
                            elif isinstance(sub, ast.With):
                                for it in sub.items:
                                    inner = self.model.resolve(
                                        it.context_expr, d.cls
                                    )
                                    if inner and inner != lock:
                                        edges.append(
                                            (inner, d.rel, d.qual)
                                        )
                    # transitive hits through the call graph
                    region = self.region_calls(node.body, d)
                    for callee, path in sorted(self.closure(region).items()):
                        cf = self.facts.get(callee)
                        cd = self.graph.defs[callee]
                        if cf is None:
                            continue
                        for op, reason, _line in cf.blocking:
                            blocking.append(
                                (
                                    op, reason, cd.rel,
                                    f"{d.qual} -> {self._path_str(path)}",
                                )
                            )
                        for inner, _line in cf.acquires:
                            if inner != lock:
                                edges.append(
                                    (
                                        inner, cd.rel,
                                        f"{d.qual} -> {self._path_str(path)}",
                                    )
                                )
                    yield d, lock, node.lineno, blocking, edges


def _layer(info_rel_in_pkg: str, layers) -> bool:
    return any(
        info_rel_in_pkg == p or info_rel_in_pkg.startswith(p) for p in layers
    )


# one-entry memo (snapshot held by strong ref, the call_graph pattern):
# the two lock rules share one model+region scan per lint run instead of
# each paying the full-package held-region analysis
_SCAN_CACHE: List[tuple] = []


def region_scan(snap: PackageSnapshot) -> _RegionScan:
    from karpenter_tpu.analysis.allowlists import LOCK_ALIASES

    if _SCAN_CACHE and _SCAN_CACHE[0][0] is snap:
        return _SCAN_CACHE[0][1]
    model = build_lock_model(snap, LOCK_ALIASES)
    scan = _RegionScan(snap, model, call_graph(snap))
    _SCAN_CACHE.clear()
    _SCAN_CACHE.append((snap, scan))
    return scan


@register
class LockBlockingRule(Rule):
    """Blocking operations reachable under a held lock."""

    name = "lock-blocking"
    title = "no blocking op (socket/RPC/encode/device/join) under a lock"
    guards = "store and pipeline tick latency; no convoy on hot locks"

    def check(self, snap, allowlist) -> List[Finding]:
        scan = region_scan(snap)
        out: List[Finding] = []
        for d, lock, line, blocking, _edges in scan.scan_regions():
            if (d.rel, d.qual) in allowlist:
                continue
            seen = set()
            for op, reason, site, path in blocking:
                if (op, site) in seen:
                    continue
                seen.add((op, site))
                out.append(
                    self.finding(
                        d.rel, line,
                        f"{d.qual}: {op}(...) ({reason}) at {site} runs "
                        f"under {lock} via {path} — move the blocking "
                        "work outside the critical section, or "
                        "consciously allowlist this region",
                    )
                )
        return out


@register
class LockOrderRule(Rule):
    """Inconsistent lock-acquisition order across the analyzed layers."""

    name = "lock-order"
    title = "consistent lock acquisition order (no A->B and B->A)"
    guards = "no cross-thread deadlock between store/pipeline/operator"

    def check(self, snap, allowlist) -> List[Finding]:
        from karpenter_tpu.analysis.allowlists import LOCK_ORDER_LAYERS

        scan = region_scan(snap)
        # (outer, inner) -> [(file, line, path)]
        edges: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}
        for d, lock, line, _blocking, region_edges in scan.scan_regions():
            if not _layer(d.module.rel_in_pkg, LOCK_ORDER_LAYERS):
                continue
            for inner, _site, path in region_edges:
                if inner.startswith("?.") or lock.startswith("?."):
                    continue  # ambiguous identities make false inversions
                edges.setdefault((lock, inner), []).append(
                    (d.rel, line, path)
                )
        out: List[Finding] = []
        for (a, b), sites in sorted(edges.items()):
            if (b, a) not in edges or a >= b:
                continue  # report each inverted pair once, from the
                # lexicographically smaller side
            pair = f"{a}|{b}"
            if pair in allowlist:
                continue
            rel, line, path = sites[0]
            rsites = edges[(b, a)]
            # no line numbers in the MESSAGE (fingerprint stability);
            # the finding's own line anchors the forward site
            out.append(
                self.finding(
                    rel, line,
                    f"lock order inversion: {a} -> {b} (here, via {path}) "
                    f"but {b} -> {a} in "
                    f"{rsites[0][0]} (via {rsites[0][2]}) "
                    "— pick one global order or merge the locks",
                )
            )
        return out


# ---------------------------------------------- static<->dynamic surface
def static_order_edges(
    snap: PackageSnapshot,
) -> Tuple[frozenset, frozenset]:
    """(edges, universe) for witness cross-validation (witness.py):
    every nested-acquisition edge the static model predicts within
    ``LOCK_ORDER_LAYERS`` — ALL of them, not just inverted pairs — plus
    the universe of layer-scoped canonical lock ids.  A runtime edge
    between universe locks that is absent here means the static model's
    resolution has a hole (or a seam lock name drifted)."""
    from karpenter_tpu.analysis.allowlists import LOCK_ORDER_LAYERS

    scan = region_scan(snap)
    edges: Set[Tuple[str, str]] = set()
    for d, lock, _line, _blocking, region_edges in scan.scan_regions():
        if not _layer(d.module.rel_in_pkg, LOCK_ORDER_LAYERS):
            continue
        for inner, _site, _path in region_edges:
            if inner.startswith("?.") or lock.startswith("?."):
                continue
            edges.add((lock, inner))
    universe = frozenset(
        scan.model.canonical(f"{cls}.{attr}")
        for (cls, attr), rel in scan.model.files.items()
        if _layer(rel, LOCK_ORDER_LAYERS)
    )
    return frozenset(edges), universe


@register
class LockSeamRule(Rule):
    """Raw ``threading.Lock/RLock/Condition`` construction is fenced to
    the sanitizer seam (analysis/sanitizer.py make_lock/make_rlock/
    make_condition) — a raw lock is invisible to the runtime witness,
    so a sanitized suite proves nothing about it.  The rule also checks
    the seam's ``name`` argument against the assignment's static
    identity (``Class.attr``): the witness and the static model must
    speak the same vocabulary or cross-validation silently rots."""

    name = "lock-seam"
    title = "locks constructed via the sanitizer seam, names = Class.attr"
    guards = "runtime witness coverage + static<->dynamic name agreement"

    _RAW = frozenset({"Lock", "RLock", "Condition"})
    _SEAM = frozenset({"make_lock", "make_rlock", "make_condition"})

    def check(self, snap, allowlist) -> List[Finding]:
        out: List[Finding] = []
        for info in snap.in_package():
            # names imported straight off threading (`from threading
            # import Lock`): a bare `Lock()` built through them is just
            # as raw as `threading.Lock()` — the fence must not be
            # bypassable by import style
            from_threading = {
                (alias.asname or alias.name): alias.name
                for imp in ast.walk(info.tree)
                if isinstance(imp, ast.ImportFrom)
                and imp.module == "threading"
                for alias in imp.names
            }
            for node in ast.walk(info.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for sub in class_own_nodes(node):
                    if not (
                        isinstance(sub, ast.Assign)
                        and isinstance(sub.value, ast.Call)
                    ):
                        continue
                    ctor = call_name(sub.value)
                    target = sub.targets[0]
                    attr = None
                    if (
                        isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"
                    ):
                        attr = target.attr
                    if attr is None:
                        continue
                    f = sub.value.func
                    raw_kind = None
                    if ctor in self._RAW and (
                        isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "threading"
                    ):
                        raw_kind = ctor
                    elif (
                        isinstance(f, ast.Name)
                        and from_threading.get(f.id) in self._RAW
                    ):
                        raw_kind = from_threading[f.id]
                    if raw_kind is not None:
                        if (info.rel, f"{node.name}.{attr}") in allowlist:
                            continue
                        out.append(
                            self.finding(
                                info.rel, sub.lineno,
                                f"{node.name}.{attr} = threading."
                                f"{raw_kind}() constructed raw — route "
                                "through analysis.sanitizer."
                                f"make_{raw_kind.lower()}(...) so "
                                "sanitized runs can witness it, or "
                                "consciously allowlist it",
                            )
                        )
                    elif ctor in self._SEAM:
                        args = sub.value.args
                        want = f"{node.name}.{attr}"
                        got = (
                            args[0].value
                            if args
                            and isinstance(args[0], ast.Constant)
                            and isinstance(args[0].value, str)
                            else None
                        )
                        if got != want:
                            out.append(
                                self.finding(
                                    info.rel, sub.lineno,
                                    f"{want} = {ctor}({got!r}) — the "
                                    "seam name must be the lock's "
                                    f"static identity {want!r} "
                                    "(witness<->static cross-validation "
                                    "matches on it)",
                                )
                            )
        return out
