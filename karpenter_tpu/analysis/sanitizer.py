"""Runtime concurrency sanitizer: the dynamic half of the lock plane.

The static analyzers (locks.py) prove ordering and blocking discipline
over the edges the AST can resolve; this module witnesses what the
threads actually DO — the TSan/lockdep/Eraser lineage:

- **Lock-order witness**: instrumented ``Lock``/``RLock``/``Condition``
  wrappers record, per thread, the stack of held locks; every first
  acquisition under held locks adds ``held -> new`` edges to a runtime
  order graph.  A pair acquired in both orders anywhere in the run is an
  ``rt-lock-order`` finding — the same pairwise inversion semantics as
  the static ``lock-order`` rule, over observed rather than predicted
  edges.
- **Blocking witness**: the package's blocking seams (socket frame I/O,
  ``_rpc``, payload encodes, the ``run_concurrently`` join — exactly the
  vocabulary locks.py names) call :func:`note_blocking`; a blocking op
  executed while the thread holds a non-sanctioned lock is an
  ``rt-lock-blocking`` finding.
- **Eraser lockset**: annotated shared state (store maps, subscriber
  queues, the pipeline speculation slot, the observatory merge dict)
  calls :func:`note_access`; a field touched from >= 2 threads whose
  candidate lockset intersection goes empty with a writer involved is an
  ``rt-race`` finding.

**The construction seam.**  ``make_lock(name)`` / ``make_rlock(name)`` /
``make_condition(name, lock)`` is the ONE place the package constructs
its synchronization primitives (the ``lock-seam`` lint rule fences raw
``threading.Lock()`` construction the way rule 11 fences raw threads to
``run_concurrently``).  ``name`` must be the lock's static identity —
``"Class.attr"`` exactly as locks.py discovers it (lint-checked) — which
is what makes the runtime witness and the static model speak the same
vocabulary and cross-validation (analysis/witness.py) meaningful.

**Production default: off.**  With no sanitizer enabled the seam returns
the stdlib classes themselves — not wrappers with a fast path, the very
objects ``threading`` hands out — so steady-state cost is zero beyond
one ``is None`` test at construction time; ``note_blocking`` and
``note_access`` are a module-global load and a branch.  Enabled (the
sanitized test suites, ``Settings.enable_lock_sanitizer``), every
acquisition pays a thread-local update plus, on first acquisition, a
stack walk for the site string — measured by the
``sanitizer_lock_overhead`` bench line.

Everything serialized is deterministic: lock names, repo-relative site
strings, sorted JSON — never thread ids, wall-clock stamps, or object
addresses (witness.py holds the artifact contract).

The deadlock watchdog (:class:`LockWatchdog`) reuses the same holder
table: an optional thread that, when EVERY currently-held lock has been
held past a stall threshold, hands the live lock graph to a callback
(the operator dumps it next to a flight record) — a production
``hung tick`` postmortem artifact, not a test assertion.
"""

from __future__ import annotations

import itertools
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from karpenter_tpu.analysis.core import Finding
from karpenter_tpu.analysis.witness import Witness

# the active sanitizer, None in production.  Module-global on purpose:
# the seams (note_blocking in codec/remote/pipeline) must be reachable
# without constructor plumbing through every layer, exactly like the
# device OBSERVATORY.
_ACTIVE: Optional["LockSanitizer"] = None


def current() -> Optional["LockSanitizer"]:
    return _ACTIVE


def enable(scenario: str = "default") -> "LockSanitizer":
    """Install a fresh sanitizer.  Locks constructed from here on are
    wrapped; locks constructed before stay stdlib (enable BEFORE
    building the object graph under test)."""
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError(
            "lock sanitizer already enabled; disable() the previous one "
            "(nesting would split the witness across two graphs)"
        )
    _ACTIVE = LockSanitizer(scenario)
    return _ACTIVE


def disable() -> Optional["LockSanitizer"]:
    """Uninstall and return the active sanitizer (its witness stays
    readable; already-wrapped locks keep recording into it, which is
    fine for teardown races — the artifact is read after join)."""
    global _ACTIVE
    san = _ACTIVE
    _ACTIVE = None
    return san


# ------------------------------------------------------------------ seam
def make_lock(name: str):
    """``threading.Lock()``, instrumented when a sanitizer is active.
    ``name`` is the lock's static identity ("Class.attr", lint-checked
    against the assignment site)."""
    san = _ACTIVE
    if san is None:
        return threading.Lock()
    return _SanitizedLock(san, name, threading.Lock())


def make_rlock(name: str):
    san = _ACTIVE
    if san is None:
        return threading.RLock()
    return _SanitizedRLock(san, name, threading.RLock())


def make_condition(name: str, lock=None):
    """``threading.Condition(lock)``.  A condition over a sanitized lock
    aliases onto that lock's identity (the ``_Subscriber.cond`` ==
    ``VersionedStore.lock`` relationship LOCK_ALIASES declares for the
    static model) — waiting releases it, waking re-acquires it, and the
    witness sees one lock, not two."""
    san = _ACTIVE
    if san is None:
        return threading.Condition(lock)
    if isinstance(lock, (_SanitizedLock, _SanitizedRLock)):
        inner = threading.Condition(lock._inner)
        return _SanitizedCondition(san, lock.name, inner)
    inner = threading.Condition(lock)
    return _SanitizedCondition(san, name, inner)


# the blocking-op vocabulary mirrors locks.BLOCKING_CALLS: these are the
# seams that actually call note_blocking (socket frame I/O, the store
# RPC, payload encodes, the fan-out join)
def note_blocking(op: str) -> None:
    """Called by the package's blocking seams.  No-op unless sanitized."""
    san = _ACTIVE
    if san is not None:
        san._note_blocking(op)


def note_access(fieldname: str, write: bool = True) -> None:
    """Eraser lockset annotation for one shared field ("Class.attr").
    Called at the field's touch points.  No-op unless sanitized."""
    san = _ACTIVE
    if san is not None:
        san._note_access(fieldname, write)


# ------------------------------------------------------------- wrappers
class _SanitizedLock:
    """Drop-in ``threading.Lock`` recording into the sanitizer."""

    __slots__ = ("_san", "name", "_inner")

    def __init__(self, san: "LockSanitizer", name: str, inner):
        self._san = san
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._san._note_acquire(self.name)
        return got

    def release(self) -> None:
        self._san._note_release(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class _SanitizedRLock(_SanitizedLock):
    """Reentrant variant: the sanitizer tracks per-thread hold counts,
    so only the 0->1 acquisition records edges and only the 1->0 release
    pops the held stack."""

    __slots__ = ()

    def locked(self) -> bool:  # RLock has no .locked() pre-3.12
        return self._san._held_somewhere(self.name)


class _SanitizedCondition:
    """Wraps a real Condition built over the REAL underlying lock (so
    the stdlib wait/notify machinery is untouched) and mirrors the
    acquire/release bookkeeping under the aliased lock name.  ``wait``
    releases every reentrant hold and restores it on wake, exactly as
    ``Condition._release_save`` does underneath."""

    __slots__ = ("_san", "name", "_inner")

    def __init__(self, san: "LockSanitizer", name: str, inner):
        self._san = san
        self.name = name
        self._inner = inner

    def acquire(self, *args) -> bool:
        got = self._inner.acquire(*args)
        if got:
            self._san._note_acquire(self.name)
        return got

    def release(self) -> None:
        self._san._note_release(self.name)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        saved = self._san._note_release_all(self.name)
        try:
            return self._inner.wait(timeout)
        finally:
            if saved:
                self._san._note_acquire(self.name, count=saved)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        saved = self._san._note_release_all(self.name)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            if saved:
                self._san._note_acquire(self.name, count=saved)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()


# ------------------------------------------------------------ the brain
class _FieldState:
    """Eraser state machine for one annotated field.

    virgin -> exclusive (first thread; init pattern, no refinement) ->
    shared (second thread reads) / shared-modified (any later write).
    The candidate lockset starts as the held set at the FIRST
    cross-thread access and intersects on every access after; an empty
    lockset in shared-modified is a race (reported once)."""

    __slots__ = ("state", "first_thread", "lockset", "threads", "writers",
                 "raced")

    def __init__(self):
        self.state = "virgin"
        self.first_thread: Optional[int] = None
        self.lockset: Optional[frozenset] = None  # None = not yet shared
        self.threads = 0
        self.writers = 0
        self.raced = False


class LockSanitizer:
    """One sanitized run's recording state.  All shared tables live
    under a RAW ``threading.Lock`` (wrapping the sanitizer's own mutex
    in itself would recurse; the lock-seam allowlist names this
    construction)."""

    def __init__(self, scenario: str = "default"):
        self.scenario = scenario
        self._mu = threading.Lock()
        self._tls = threading.local()
        # stable per-thread tokens, assigned at first touch: OS thread
        # idents are REUSED the moment a thread exits (a writer that
        # finishes before its sibling starts can hand its ident over,
        # collapsing two threads into "one" for the lockset algorithm),
        # so thread identity lives in the thread-local, which dies with
        # the thread and is never recycled
        self._tid_counter = itertools.count(1)
        # (outer, inner) -> sorted-on-read set of site strings
        self._edges: Dict[Tuple[str, str], set] = {}
        # (op, heldtuple, site, allowed) observation dedup
        self._blocking: Dict[Tuple[str, Tuple[str, ...], str], bool] = {}
        self._fields: Dict[str, _FieldState] = {}
        self._locks: set = set()
        self._field_threads: Dict[str, set] = {}
        self._field_writers: Dict[str, set] = {}
        # (lock name, site) of releases by threads that never acquired
        # — cross-thread ownership handoff the bookkeeping cannot track
        self._foreign_releases: set = set()
        # live holds for the watchdog: (thread token, lock name) ->
        # (thread name, since-monotonic-seconds); never serialized into
        # the witness
        self._holds: Dict[Tuple[int, str], Tuple[str, float]] = {}
        # sanctioned blocking regions: a lock that EXISTS to serialize
        # the blocking op (the one-in-flight-RPC pattern); populated
        # from allowlists.SANITIZER_BLOCKING_LOCKS
        from karpenter_tpu.analysis.allowlists import (
            SANITIZER_BLOCKING_LOCKS,
        )

        self._blocking_ok = frozenset(SANITIZER_BLOCKING_LOCKS)

    # ---------------------------------------------------------- per-thread
    def _state(self):
        st = getattr(self._tls, "state", None)
        if st is None:
            st = {
                "held": [],
                "counts": {},
                "tid": next(self._tid_counter),
                "name": threading.current_thread().name,
            }
            self._tls.state = st
        return st

    @staticmethod
    def _site() -> Tuple[str, int]:
        """(repo-relative file, line) of the first frame outside this
        module — the acquisition/annotation site.  Deterministic across
        runs (code locations, not addresses)."""
        f = sys._getframe(2)
        while f is not None and f.f_code.co_filename == __file__:
            f = f.f_back
        if f is None:  # pragma: no cover - only if called at module top
            return "?", 0
        fname = f.f_code.co_filename.replace("\\", "/")
        idx = fname.rfind("karpenter_tpu/")
        rel = fname[idx:] if idx >= 0 else fname.rsplit("/", 1)[-1]
        return f"{rel}:{f.f_code.co_name}", f.f_lineno

    # ------------------------------------------------------------ recording
    def _note_acquire(self, name: str, count: int = 1) -> None:
        st = self._state()
        counts = st["counts"]
        prev = counts.get(name, 0)
        counts[name] = prev + count
        if prev:
            return  # reentrant re-acquire: no new edges, no new hold
        held: List[str] = st["held"]
        site = self._site()[0] if held else ""
        with self._mu:  # one round trip: edges + lock set + holder table
            self._locks.add(name)
            for h in held:
                if h != name:
                    self._edges.setdefault((h, name), set()).add(site)
            self._holds[(st["tid"], name)] = (
                st["name"], time.monotonic()
            )
        held.append(name)

    def _note_release(self, name: str) -> None:
        st = self._state()
        counts = st["counts"]
        prev = counts.get(name, 0)
        if prev == 0:
            # released on a thread that never acquired it (ownership
            # handoff — legal for threading.Lock, but it would corrupt
            # the per-thread bookkeeping silently): record loudly as an
            # anomaly finding instead of emitting wrong edges forever
            site, _line = self._site()
            with self._mu:
                self._foreign_releases.add((name, site))
            return
        if prev > 1:
            counts[name] = prev - 1
            return
        counts.pop(name, None)
        held: List[str] = st["held"]
        # locks are normally released LIFO, but non-nested release is
        # legal — remove by value from the tail
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break
        with self._mu:
            self._holds.pop((st["tid"], name), None)

    def _note_release_all(self, name: str) -> int:
        """Condition.wait: drop EVERY reentrant hold of ``name`` for
        this thread, returning the count to restore on wake (0 when the
        thread held nothing — stdlib wait() raises in that case and no
        bookkeeping must be restored)."""
        st = self._state()
        saved = st["counts"].get(name, 0)
        if saved:
            st["counts"][name] = 1
            self._note_release(name)
        return saved

    def _held_somewhere(self, name: str) -> bool:
        with self._mu:
            keys = list(self._holds)
        return any(k[1] == name for k in keys)

    def _note_blocking(self, op: str) -> None:
        st = self._state()
        held = tuple(st["held"])
        if not held:
            return
        site, _line = self._site()
        # sanctioned ONLY when every held lock is sanctioned: holding a
        # one-in-flight RPC lock must not launder an unrelated outer
        # lock (the convoy the finding exists to catch is exactly
        # blocking-op-under-SOME-unsanctioned-lock)
        allowed = all(h in self._blocking_ok for h in held)
        with self._mu:
            self._blocking[(op, held, site)] = allowed

    def _note_access(self, fieldname: str, write: bool) -> None:
        st = self._state()
        held = frozenset(st["held"])
        ident = st["tid"]
        with self._mu:
            fs = self._fields.get(fieldname)
            if fs is None:
                fs = _FieldState()
                self._fields[fieldname] = fs
                self._field_threads[fieldname] = set()
                self._field_writers[fieldname] = set()
            self._field_threads[fieldname].add(ident)
            if write:
                self._field_writers[fieldname].add(ident)
            if fs.state == "virgin":
                fs.state = "exclusive"
                fs.first_thread = ident
                return
            if fs.state == "exclusive" and ident == fs.first_thread:
                return  # init pattern: same thread, no refinement
            # a second thread arrived (or sharing already began):
            # candidate lockset = intersection of held sets from the
            # first cross-thread access on
            fs.lockset = held if fs.lockset is None else fs.lockset & held
            if write:
                fs.state = "shared-modified"
            elif fs.state != "shared-modified":
                fs.state = "shared"
            if fs.state == "shared-modified" and not fs.lockset:
                fs.raced = True

    # -------------------------------------------------------------- reports
    def live_holds(self) -> List[dict]:
        """The watchdog's view: every currently-held lock with its hold
        age.  Thread identity is the thread NAME (stable for named test
        threads; informative either way) — never the id."""
        now = time.monotonic()
        with self._mu:
            holds = dict(self._holds)
        return [
            {
                "lock": name,
                "thread": tname,
                "held_s": round(now - since, 3),
            }
            for (_tid, name), (tname, since) in sorted(
                holds.items(),
                key=lambda kv: (kv[0][1], kv[1][0], kv[0][0]),
            )
        ]

    def findings(self) -> List[Finding]:
        """The run's verdict, Finding-shaped so the sanitized suites
        assert on it exactly like the lint gate asserts on rules."""
        out: List[Finding] = []
        with self._mu:
            edges = {k: sorted(v) for k, v in self._edges.items()}
            blocking = dict(self._blocking)
            fields = {
                f: (fs, sorted(self._field_threads[f]),
                    sorted(self._field_writers[f]))
                for f, fs in self._fields.items()
            }
        for (a, b), sites in sorted(edges.items()):
            if (b, a) not in edges or a >= b:
                continue
            rsites = sorted(edges[(b, a)])
            rel = sites[0].split(":", 1)[0]
            out.append(
                Finding(
                    rule="rt-lock-order",
                    file=rel,
                    line=0,
                    message=(
                        f"runtime lock order inversion: {a} -> {b} "
                        f"(at {sites[0]}) but {b} -> {a} "
                        f"(at {rsites[0]}) — two live threads took "
                        "these locks in opposite orders"
                    ),
                )
            )
        for (op, held, site), allowed in sorted(blocking.items()):
            if allowed:
                continue
            rel = site.split(":", 1)[0]
            out.append(
                Finding(
                    rule="rt-lock-blocking",
                    file=rel,
                    line=0,
                    message=(
                        f"blocking op {op}(...) executed at {site} while "
                        f"holding {', '.join(held)} — observed at "
                        "runtime, not just reachable"
                    ),
                )
            )
        with self._mu:
            foreign = sorted(self._foreign_releases)
        for name, site in foreign:
            rel = site.split(":", 1)[0]
            out.append(
                Finding(
                    rule="rt-foreign-release",
                    file=rel,
                    line=0,
                    message=(
                        f"{name} released at {site} by a thread that "
                        "never acquired it — cross-thread lock handoff "
                        "the witness cannot track; its edges and holds "
                        "for this lock are unreliable from here on"
                    ),
                )
            )
        for fname, (fs, threads, writers) in sorted(fields.items()):
            if fs.raced:
                out.append(
                    Finding(
                        rule="rt-race",
                        file="karpenter_tpu/analysis/sanitizer.py",
                        line=0,
                        message=(
                            f"lockset race on {fname}: touched by "
                            f"{len(threads)} threads "
                            f"({len(writers)} writing) with an EMPTY "
                            "common lockset — no single lock protects "
                            "every access"
                        ),
                    )
                )
        return sorted(out)

    def witness(self) -> Witness:
        """The deterministic artifact (witness.py owns the contract)."""
        with self._mu:
            edges = {k: sorted(v) for k, v in self._edges.items()}
            blocking = dict(self._blocking)
            locks = sorted(self._locks)
            fields = {
                f: (fs, len(self._field_threads[f]),
                    len(self._field_writers[f]))
                for f, fs in self._fields.items()
            }
        return Witness(
            scenario=self.scenario,
            locks=locks,
            edges=[
                {"outer": a, "inner": b, "sites": sites}
                for (a, b), sites in sorted(edges.items())
            ],
            blocking=[
                {
                    "op": op,
                    "locks": list(held),
                    "site": site,
                    "allowed": allowed,
                }
                for (op, held, site), allowed in sorted(blocking.items())
            ],
            fields=[
                {
                    "field": f,
                    "state": fs.state,
                    "lockset": (
                        sorted(fs.lockset) if fs.lockset is not None
                        else None
                    ),
                    "threads": nthreads,
                    "writers": nwriters,
                }
                for f, (fs, nthreads, nwriters) in sorted(fields.items())
            ],
            findings=[f.to_dict() for f in self.findings()],
        )


# ----------------------------------------------------------- the watchdog
class LockWatchdog:
    """Production deadlock watchdog over the sanitizer's holder table.

    Fires ``on_stall(report)`` when locks are held and EVERY current
    holder has been stuck past ``stall_s`` — the all-holders-stalled
    shape of a deadlock or a wedged tick, as opposed to one long busy
    critical section among healthy ones.  One report per episode: it
    re-arms only after the stalled hold-set changes.  The thread is
    constructed HERE (analysis/, outside the thread-seam fence) so the
    operator only starts/stops it."""

    def __init__(
        self,
        sanitizer: LockSanitizer,
        stall_s: float,
        on_stall: Callable[[dict], None],
        interval_s: Optional[float] = None,
    ):
        self.sanitizer = sanitizer
        self.stall_s = stall_s
        self.on_stall = on_stall
        self.interval_s = interval_s or max(0.1, stall_s / 4.0)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_fired: Optional[frozenset] = None

    def check(self, now: Optional[float] = None) -> Optional[dict]:
        """One poll (exposed for deterministic tests).  Returns the
        stall report when it fires, else None."""
        now = time.monotonic() if now is None else now
        with self.sanitizer._mu:
            holds = dict(self.sanitizer._holds)
        if not holds:
            self._last_fired = None
            return None
        ages = [now - since for (_tname, since) in holds.values()]
        if min(ages) < self.stall_s:
            self._last_fired = None
            return None
        key = frozenset(holds)
        if key == self._last_fired:
            return None  # same episode, already reported
        self._last_fired = key
        report = {
            "stall_s": self.stall_s,
            "holds": self.sanitizer.live_holds(),
            "witness_fingerprint": self.sanitizer.witness().fingerprint,
        }
        self.on_stall(report)
        return report

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="lock-watchdog", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check()
            except Exception:  # pragma: no cover - must never kill the
                pass  # process it is diagnosing

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
