"""The 11 legacy lint rules, ported onto the rule engine.

These are the checks tests/test_lint.py originally enforced as ad-hoc
test functions; each keeps its historical allowlist (allowlists.py) and
semantics.  The shared machinery — scope stacks, in-loop tagging,
(file, qualname) allowlisting — lives in :class:`CallSiteRule` instead
of six copy-pasted visitors.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from karpenter_tpu.analysis.core import (
    Finding,
    PackageSnapshot,
    Rule,
    ScopedVisitor,
    call_name,
    register,
)


# ---------------------------------------------------------------- runtime
def import_snapshot_modules(snap: PackageSnapshot):
    """Import every module of the snapshot, yielding (ModuleInfo,
    module-or-None, exception-or-None).  The snapshot's repo root is
    put on sys.path for synthetic trees; the real package is already
    importable (and mostly already imported)."""
    import importlib
    import sys

    added = str(snap.repo_root) not in sys.path
    if added:
        sys.path.insert(0, str(snap.repo_root))
    try:
        for info in snap.in_package():
            try:
                yield info, importlib.import_module(info.name), None
            except Exception as exc:
                yield info, None, exc
    finally:
        if added:
            sys.path.remove(str(snap.repo_root))


@register
class ImportCleanRule(Rule):
    """Rule 1: every module imports cleanly."""

    name = "import-clean"
    title = "every package module imports without error"
    guards = "a module that cannot import cannot be reconciled against"

    def check(self, snap, allowlist) -> List[Finding]:
        out = []
        for info, _mod, exc in import_snapshot_modules(snap):
            if exc is not None and info.rel not in allowlist:
                out.append(
                    self.finding(
                        info.rel, 1,
                        f"module {info.name} failed to import: "
                        f"{type(exc).__name__}: {exc}",
                    )
                )
        return out


@register
class AnnotationsResolveRule(Rule):
    """Rule 2: ``typing.get_type_hints`` resolves on every public
    function/method — catches annotations referencing never-imported
    names (the ``Optional``-without-import bug class)."""

    name = "annotations-resolve"
    title = "type annotations resolve on every public def"
    guards = "annotation rot (names referenced but never imported)"

    def check(self, snap, allowlist) -> List[Finding]:
        import inspect
        import typing

        out = []
        for info, mod, exc in import_snapshot_modules(snap):
            if mod is None or info.rel in allowlist:
                continue
            targets = []
            for _, obj in vars(mod).items():
                if inspect.isfunction(obj) and obj.__module__ == info.name:
                    targets.append(obj)
                elif inspect.isclass(obj) and obj.__module__ == info.name:
                    targets.append(obj)
                    for _, m in vars(obj).items():
                        if inspect.isfunction(m):
                            targets.append(m)
            for t in targets:
                try:
                    typing.get_type_hints(t)
                except NameError as err:
                    qual = getattr(t, "__qualname__", t)
                    line = 1
                    try:
                        line = t.__code__.co_firstlineno
                    except AttributeError:
                        pass
                    out.append(
                        self.finding(
                            info.rel, line,
                            f"unresolvable annotation on {qual}: {err}",
                        )
                    )
                except Exception:
                    pass  # forward refs to runtime-only types are fine
        return out


# -------------------------------------------------------------- wall clock
_WALL_CLOCK_RE = re.compile(r"\btime\.(?:time|sleep)\s*\(")


@register
class WallClockRule(Rule):
    """Rule 3: no ``time.time``/``time.sleep`` calls outside
    utils/clock.py — all time flows through the injectable Clock so a
    FakeClock compresses every wait and two equal seeds replay
    byte-identically.
    (``time.monotonic``/``perf_counter`` stay free: they measure host
    durations no simulated clock can compress.)"""

    name = "wall-clock"
    title = "wall clock only inside the injectable Clock"
    guards = "byte-identical sim replay (docs/designs/simulation.md)"

    def check(self, snap, allowlist) -> List[Finding]:
        out = []
        for info in snap.in_package():
            if info.rel in allowlist:
                continue
            for lineno, line in enumerate(info.source.splitlines(), 1):
                code = line.split("#", 1)[0]
                if _WALL_CLOCK_RE.search(code):
                    out.append(
                        self.finding(
                            info.rel, lineno,
                            f"wall-clock call outside the Clock seam: "
                            f"{line.strip()} (route through the injected "
                            "Clock, or allowlist a genuinely-wall-clock "
                            "spot)",
                        )
                    )
        return out


# ---------------------------------------------------- call-site rule base
class CallSiteRule(Rule):
    """Shared machinery for the fenced-call-site rules: a set of call
    names (bare or attribute form), an optional package-relative scan
    scope, allowlisting by ``(file, qualified name)``, and in-loop
    tagging for the per-candidate antipatterns."""

    names: frozenset = frozenset()
    scan: tuple = ()  # rel_in_pkg prefixes; () = whole package
    loop_tag = True
    advice = ""
    # DENY fence: rel-path suffixes where no allowlist entry may ever
    # sanction a match — the rule fires there even when an entry exists,
    # and the entry itself is flagged.  This is how a module whose whole
    # contract is "never does X" (the admission fast path vs tensorize)
    # stays un-allowlistable by construction.
    deny: tuple = ()

    def match(self, node: ast.Call, name: Optional[str]) -> Optional[str]:
        """The matched display name, or None.  Subclasses with richer
        predicates (scheduler-update's receiver check) override."""
        return name if name in self.names else None

    def check(self, snap, allowlist) -> List[Finding]:
        out: List[Finding] = []
        rule = self

        if self.deny:
            # an allowlist entry pointing into a DENIED file is itself a
            # finding: the fence must be visible at review time, not
            # only when someone writes the forbidden call
            for entry in sorted(allowlist, key=repr):
                rel_entry = entry[0] if isinstance(entry, tuple) else entry
                if isinstance(rel_entry, str) and rel_entry.endswith(
                    self.deny
                ):
                    out.append(
                        self.finding(
                            rel_entry, 0,
                            f"allowlist entry {entry!r} references a "
                            f"DENIED file — no exception to "
                            f"'{self.title}' may be sanctioned there",
                        )
                    )

        for info in snap.in_package(*self.scan):
            rel = info.rel
            # str.endswith(()) is False, so an empty deny never matches
            denied = rel.endswith(self.deny)

            class V(ScopedVisitor):
                def on_call(self, node):
                    matched = rule.match(node, call_name(node))
                    if matched is None:
                        return
                    if (rel, self.qual) in allowlist and not denied:
                        return
                    where = (
                        "INSIDE A LOOP"
                        if rule.loop_tag and self.loops
                        else "call"
                    )
                    if denied:
                        where += ", DENIED file"
                    out.append(
                        rule.finding(
                            rel, node.lineno,
                            f"{self.qual or '<module>'}: {matched}(...) "
                            f"[{where}] — {rule.advice}",
                        )
                    )

            V().visit(info.tree)
        return out


@register
class SchedulerUpdateRule(CallSiteRule):
    """Rule 4: ``scheduler.update()`` in controllers/ only at the
    sanctioned sites — a per-candidate update loop re-compiles the whole
    problem per subset (docs/designs/consolidation-batching.md)."""

    name = "scheduler-update"
    title = "scheduler.update() fenced to the sanctioned controller sites"
    guards = "the batched consolidation win (no serial re-simulation)"
    scan = ("controllers/",)
    advice = (
        "batch the simulations through TensorScheduler.evaluate_removals, "
        "or allowlist a genuinely one-shot site"
    )

    def match(self, node, name):
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "update"
            and "scheduler" in ast.unparse(f.value).lower()
        ):
            return f"{ast.unparse(f.value)}.update"
        return None


@register
class FullTensorizeRule(CallSiteRule):
    """Rule 7: no full-tensorize call outside the sanctioned cold-build
    and rebuild-fallback sites — warm ticks flow through the resident
    delta path (ops/resident.py, docs/designs/resident-tensors.md)."""

    name = "full-tensorize"
    title = "full tensorize fenced to cold-build/rebuild sites"
    guards = "the resident-tensor warm path (35 ms flagship p50)"
    names = frozenset({"compile_problem", "_compile_tensor"})
    scan = ("controllers/", "scheduling/")
    # the admission fast path's sub-millisecond budget is STRUCTURAL:
    # its module may never tensorize, and no future allowlist entry may
    # carve an exception (docs/designs/admission-fastpath.md)
    deny = ("scheduling/fastpath.py",)
    advice = (
        "route warm updates through the resident delta path, or "
        "consciously allowlist a cold-build/rebuild site"
    )


@register
class SequentialDescentRule(CallSiteRule):
    """Rule 8: the sequential consolidation descent is reachable only
    from the allowlisted fallback and re-derivation sites — what-ifs
    flow through the population/verdict kernels
    (docs/designs/consolidation-search.md)."""

    name = "sequential-descent"
    title = "sequential descent fenced to fallback/re-derivation sites"
    guards = "the device-resident consolidation search promotion"
    names = frozenset(
        {"_simulate", "_consolidate_multi", "_consolidate_multi_descent"}
    )
    advice = (
        "batch the what-ifs through evaluate_population/evaluate_removals, "
        "or consciously allowlist a fallback/re-derivation site"
    )


@register
class DevicePutRule(CallSiteRule):
    """Rule 9: raw ``device_put`` only inside the counted seam
    (obs/device.py DeviceObservatory.put) — an upload that bypasses it
    vanishes from ``karpenter_device_transfer_bytes_total{site}``."""

    name = "device-put"
    title = "raw device_put fenced to the observatory's counted seam"
    guards = "complete host->device transfer accounting"
    names = frozenset({"device_put"})
    advice = (
        "route the upload through OBSERVATORY.put(site, ...), or "
        "consciously allowlist it"
    )


@register
class ThreadSeamRule(CallSiteRule):
    """Rule 11: thread construction in the controller layer is fenced to
    the pipeline seam — a raw Thread/ThreadPoolExecutor in controllers/
    or operator.py is an unscheduled side channel the twin-run and
    byte-identity proofs cannot see."""

    name = "thread-seam"
    title = "controller-layer threads fenced to pipeline.run_concurrently"
    guards = "the pipelined-reconcile determinism story"
    names = frozenset({"Thread", "ThreadPoolExecutor"})
    scan = ("controllers/", "operator.py", "pipeline.py")
    loop_tag = False
    advice = (
        "route the fan-out through pipeline.run_concurrently / declare a "
        "pipeline stage, or consciously allowlist it"
    )


# ----------------------------------------------------------- doc-rot rules
_REGISTRY_VERBS = frozenset(
    {
        "inc", "set", "observe", "time", "unset", "reset_gauge",
        "counter", "gauge", "histogram", "quantile",
    }
)


@register
class MetricDocRule(Rule):
    """Rule 5: every metric-name literal passed to a registry verb
    appears in docs/metrics.md — a new series cannot ship without
    regenerating the reference page (tools/gen_metrics_doc.py)."""

    name = "metric-doc"
    title = "metric literals documented in docs/metrics.md"
    guards = "the /metrics HELP/TYPE catalog and the metrics doc"

    def documented(self, snap) -> set:
        return set(
            re.findall(
                r"`(karpenter_[a-z0-9_]+)`", snap.doc_text("docs", "metrics.md")
            )
        )

    def check(self, snap, allowlist) -> List[Finding]:
        documented = self.documented(snap) | set(
            e for e in allowlist if isinstance(e, str)
        )
        out = []
        for info in snap.in_package():
            for node in ast.walk(info.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _REGISTRY_VERBS
                    and node.args
                ):
                    continue
                first = node.args[0]
                if not (
                    isinstance(first, ast.Constant)
                    and isinstance(first.value, str)
                    and first.value.startswith("karpenter_")
                ):
                    continue
                if first.value not in documented:
                    out.append(
                        self.finding(
                            info.rel, node.lineno,
                            f"{first.value!r} passed to "
                            f".{node.func.attr}() but absent from "
                            "docs/metrics.md (run `python -m "
                            "karpenter_tpu.tools.gen_metrics_doc`)",
                        )
                    )
        return out


_EVENT_VERBS = frozenset({"event", "emit"})
_EVENT_TYPE_RE = re.compile(r"[A-Z][A-Za-z0-9]*")


@register
class EventDocRule(Rule):
    """Rule 6: every ledger event-type literal emitted via
    ``Registry.event(...)`` / ``EventLedger.emit(...)`` appears in the
    observability design's taxonomy."""

    name = "event-doc"
    title = "ledger event types documented in the observability design"
    guards = "the decision-event taxonomy (SLOBreach, ... cannot ship dark)"

    def documented(self, snap) -> set:
        return set(
            re.findall(
                r"`([A-Z][A-Za-z0-9]*)`",
                snap.doc_text("docs", "designs", "observability.md"),
            )
        )

    def check(self, snap, allowlist) -> List[Finding]:
        documented = self.documented(snap) | set(
            e for e in allowlist if isinstance(e, str)
        )
        out = []
        for info in snap.in_package():
            for node in ast.walk(info.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _EVENT_VERBS
                    and node.args
                ):
                    continue
                first = node.args[0]
                if not (
                    isinstance(first, ast.Constant)
                    and isinstance(first.value, str)
                    and _EVENT_TYPE_RE.fullmatch(first.value)
                ):
                    continue
                if first.value not in documented:
                    out.append(
                        self.finding(
                            info.rel, node.lineno,
                            f"event type {first.value!r} passed to "
                            f".{node.func.attr}() but absent from "
                            "docs/designs/observability.md",
                        )
                    )
        return out


_STORE_FRAME_FILES = (
    "service/store_server.py",
    "state/remote.py",
    "service/shardrouter.py",
    "state/storelog.py",
)
_STORE_FRAME_KEYS = frozenset({"method", "type"})


@register
class StoreFrameRule(Rule):
    """Rule 10: every wire frame ``method``/``type`` literal the store
    plane sends must appear (backticked) in docs/designs/store-scale.md
    — the protocol-vocabulary doc-rot guard."""

    name = "store-frame"
    title = "store wire-frame vocabulary documented in the design doc"
    guards = "the reviewable mixed-version negotiation story"

    def documented(self, snap) -> set:
        return set(
            re.findall(
                r"`([a-z][a-z0-9_]*)`",
                snap.doc_text("docs", "designs", "store-scale.md"),
            )
        )

    def check(self, snap, allowlist) -> List[Finding]:
        documented = self.documented(snap) | set(
            e for e in allowlist if isinstance(e, str)
        )
        out = []
        for info in snap.in_package():
            if info.rel_in_pkg not in _STORE_FRAME_FILES:
                continue
            for node in ast.walk(info.tree):
                if not isinstance(node, ast.Dict):
                    continue
                for key, value in zip(node.keys, node.values):
                    if not (
                        isinstance(key, ast.Constant)
                        and key.value in _STORE_FRAME_KEYS
                        and isinstance(value, ast.Constant)
                        and isinstance(value.value, str)
                    ):
                        continue
                    if value.value not in documented:
                        out.append(
                            self.finding(
                                info.rel, value.lineno,
                                f"frame {key.value} literal "
                                f"{value.value!r} absent from "
                                "docs/designs/store-scale.md",
                            )
                        )
        return out
