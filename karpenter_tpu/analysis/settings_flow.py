"""The settings-flow rule: every ``Settings`` field must actually FLOW.

The dead-twin-knob bug class (caught by hand in the PR 12/13 reviews):
a field lands on the dataclass, gets validated, maybe even documented —
and is never read by any layer, or never exposed through the chart, so
operators "configure" a knob that changes nothing.  Machine-checked:

1. **read somewhere**: the field name is read as an attribute (or via a
   ``getattr`` string literal) somewhere in the package OUTSIDE
   api/settings.py itself (reads inside ``validate()`` don't make a
   knob live);
2. **chart-exposed**: the field appears in ``deploy/chart/values.yaml``
   under ``settings:`` AND in the configmap template, so the rendered
   ``settings.json`` can actually carry it (tests/test_deploy.py proves
   the rendered payload loads — this rule proves the key EXISTS to
   render).

Read detection is deliberately name-based and over-approximating: any
``x.field_name`` counts, whoever ``x`` is.  A false "read" keeps the
rule quiet, which is the safe failure direction for a doc-rot class of
check.  The allowlist names fields exempt from the READ requirement
(reference-parity knobs retained for config compatibility), each with
its argument in allowlists.py; chart presence is never exempt — an
accepted field costs one values.yaml line.

Synthetic trees without an ``api/settings.py`` (or without chart files)
skip the corresponding half — the teeth harness forges both.
"""

from __future__ import annotations

import ast
import re
from typing import List, Set

from karpenter_tpu.analysis.core import (
    Finding,
    PackageSnapshot,
    Rule,
    register,
)

SETTINGS_REL = "api/settings.py"


def settings_fields(snap: PackageSnapshot) -> List[tuple]:
    """[(field name, line)] of the Settings dataclass, public fields
    only, declaration order."""
    info = next(
        (m for m in snap.in_package(SETTINGS_REL)), None
    )
    if info is None:
        return []
    out: List[tuple] = []
    for node in ast.walk(info.tree):
        if isinstance(node, ast.ClassDef) and node.name == "Settings":
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)
                    and not stmt.target.id.startswith("_")
                ):
                    out.append((stmt.target.id, stmt.lineno))
    return out


def _attribute_reads(snap: PackageSnapshot) -> Set[str]:
    """Every attribute name read (or getattr'd by literal) anywhere in
    the package outside the settings module."""
    reads: Set[str] = set()
    for info in snap.in_package():
        if info.rel_in_pkg == SETTINGS_REL:
            continue
        for node in ast.walk(info.tree):
            if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                reads.add(node.attr)
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "getattr"
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
            ):
                reads.add(node.args[1].value)
    return reads


def _settings_block(values_text: str) -> str:
    """The ``settings:`` mapping of values.yaml — keys are matched
    INSIDE this block only, so a Settings field named like some other
    chart key (``replicas``, ``port``) cannot satisfy the presence
    check by accident."""
    m = re.search(
        r"^settings:\s*\n((?:[ \t]+.*\n?|\n)*)", values_text, re.M
    )
    return m.group(1) if m else ""


@register
class SettingsFlowRule(Rule):
    """Every Settings field is read in the package and chart-exposed."""

    name = "settings-flow"
    title = "every Settings field read in-package and chart-exposed"
    guards = "no dead twin knobs (a configured setting always flows)"

    def check(self, snap, allowlist) -> List[Finding]:
        fields = settings_fields(snap)
        if not fields:
            return []
        reads = _attribute_reads(snap)
        values_text = _settings_block(
            snap.doc_text("deploy", "chart", "values.yaml")
        )
        configmap_text = snap.doc_text(
            "deploy", "chart", "templates", "configmap.yaml"
        )
        out: List[Finding] = []
        rel = f"{snap.package}/{SETTINGS_REL}"
        for fname, line in fields:
            if fname not in reads and fname not in allowlist:
                out.append(
                    self.finding(
                        rel, line,
                        f"Settings.{fname} is never read in the package "
                        "— a dead twin knob: configuring it changes "
                        "nothing.  Wire it or allowlist it with an "
                        "argument",
                    )
                )
            if values_text and not re.search(
                rf"^\s+{re.escape(fname)}:", values_text, re.M
            ):
                out.append(
                    self.finding(
                        rel, line,
                        f"Settings.{fname} missing from deploy/chart/"
                        "values.yaml — the chart cannot set it",
                    )
                )
            if configmap_text and f'"{fname}"' not in configmap_text:
                out.append(
                    self.finding(
                        rel, line,
                        f"Settings.{fname} missing from the configmap "
                        "template — the rendered settings.json cannot "
                        "carry it",
                    )
                )
        return out
