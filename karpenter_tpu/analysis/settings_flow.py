"""The settings-flow rule: every ``Settings`` field must actually FLOW.

The dead-twin-knob bug class (caught by hand in the PR 12/13 reviews):
a field lands on the dataclass, gets validated, maybe even documented —
and is never read by any layer, or never exposed through the chart, so
operators "configure" a knob that changes nothing.  Machine-checked:

1. **read somewhere**: the field name is read as an attribute (or via a
   ``getattr`` string literal) somewhere in the package OUTSIDE
   api/settings.py itself (reads inside ``validate()`` don't make a
   knob live);
2. **chart-exposed**: the field appears in ``deploy/chart/values.yaml``
   under ``settings:`` AND in the configmap template, so the rendered
   ``settings.json`` can actually carry it (tests/test_deploy.py proves
   the rendered payload loads — this rule proves the key EXISTS to
   render).  A field may instead live under a STRUCTURED values block
   (the ``service.multiTenant.*`` shape): its configmap line then
   references ``.Values.<dotted>`` paths, and the rule resolves each
   against the values.yaml document — an unresolvable path is the same
   dead knob, just spelled nested.

Read detection is deliberately name-based and over-approximating: any
``x.field_name`` counts, whoever ``x`` is.  A false "read" keeps the
rule quiet, which is the safe failure direction for a doc-rot class of
check.  The allowlist names fields exempt from the READ requirement
(reference-parity knobs retained for config compatibility), each with
its argument in allowlists.py; chart presence is never exempt — an
accepted field costs one values.yaml line.

Synthetic trees without an ``api/settings.py`` (or without chart files)
skip the corresponding half — the teeth harness forges both.
"""

from __future__ import annotations

import ast
import re
from typing import List, Set

from karpenter_tpu.analysis.core import (
    Finding,
    PackageSnapshot,
    Rule,
    register,
)

SETTINGS_REL = "api/settings.py"


def settings_fields(snap: PackageSnapshot) -> List[tuple]:
    """[(field name, line)] of the Settings dataclass, public fields
    only, declaration order."""
    info = next(
        (m for m in snap.in_package(SETTINGS_REL)), None
    )
    if info is None:
        return []
    out: List[tuple] = []
    for node in ast.walk(info.tree):
        if isinstance(node, ast.ClassDef) and node.name == "Settings":
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)
                    and not stmt.target.id.startswith("_")
                ):
                    out.append((stmt.target.id, stmt.lineno))
    return out


def _attribute_reads(snap: PackageSnapshot) -> Set[str]:
    """Every attribute name read (or getattr'd by literal) anywhere in
    the package outside the settings module."""
    reads: Set[str] = set()
    for info in snap.in_package():
        if info.rel_in_pkg == SETTINGS_REL:
            continue
        for node in ast.walk(info.tree):
            if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                reads.add(node.attr)
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "getattr"
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
            ):
                reads.add(node.args[1].value)
    return reads


_VALUES_REF = re.compile(r"\.Values\.([A-Za-z0-9_.]+)")


def _values_paths(values_text: str) -> Set[str]:
    """Every dotted path definable from values.yaml's mapping structure
    ("service.multiTenant.enabled", ...), by indentation walk — no YAML
    dependency, and forgiving of the teeth harness's forged snippets."""
    paths: Set[str] = set()
    stack: List[tuple] = []  # (indent, key)
    for line in values_text.splitlines():
        stripped = line.split("#", 1)[0].rstrip()
        m = re.match(r"^(\s*)([A-Za-z0-9_]+):", stripped)
        if not m:
            continue
        indent = len(m.group(1))
        while stack and stack[-1][0] >= indent:
            stack.pop()
        stack.append((indent, m.group(2)))
        paths.add(".".join(k for _, k in stack))
    return paths


def _configmap_refs_resolve(
    fname: str, configmap_text: str, values_paths: Set[str]
) -> bool:
    """True when the configmap line carrying ``"fname"`` references at
    least one ``.Values.`` path and every referenced path resolves in
    values.yaml — the nested-values exposure route."""
    for line in configmap_text.splitlines():
        if f'"{fname}"' not in line:
            continue
        refs = _VALUES_REF.findall(line)
        return bool(refs) and all(r in values_paths for r in refs)
    return False


def _settings_block(values_text: str) -> str:
    """The ``settings:`` mapping of values.yaml — keys are matched
    INSIDE this block only, so a Settings field named like some other
    chart key (``replicas``, ``port``) cannot satisfy the presence
    check by accident."""
    m = re.search(
        r"^settings:\s*\n((?:[ \t]+.*\n?|\n)*)", values_text, re.M
    )
    return m.group(1) if m else ""


@register
class SettingsFlowRule(Rule):
    """Every Settings field is read in the package and chart-exposed."""

    name = "settings-flow"
    title = "every Settings field read in-package and chart-exposed"
    guards = "no dead twin knobs (a configured setting always flows)"

    def check(self, snap, allowlist) -> List[Finding]:
        fields = settings_fields(snap)
        if not fields:
            return []
        reads = _attribute_reads(snap)
        full_values = snap.doc_text("deploy", "chart", "values.yaml")
        values_text = _settings_block(full_values)
        values_paths = _values_paths(full_values)
        configmap_text = snap.doc_text(
            "deploy", "chart", "templates", "configmap.yaml"
        )
        out: List[Finding] = []
        rel = f"{snap.package}/{SETTINGS_REL}"
        for fname, line in fields:
            if fname not in reads and fname not in allowlist:
                out.append(
                    self.finding(
                        rel, line,
                        f"Settings.{fname} is never read in the package "
                        "— a dead twin knob: configuring it changes "
                        "nothing.  Wire it or allowlist it with an "
                        "argument",
                    )
                )
            if (
                values_text
                and not re.search(
                    rf"^\s+{re.escape(fname)}:", values_text, re.M
                )
                and not _configmap_refs_resolve(
                    fname, configmap_text, values_paths
                )
            ):
                out.append(
                    self.finding(
                        rel, line,
                        f"Settings.{fname} missing from deploy/chart/"
                        "values.yaml — the chart cannot set it (neither "
                        "a settings: key nor a resolvable nested "
                        ".Values path in its configmap line)",
                    )
                )
            if configmap_text and f'"{fname}"' not in configmap_text:
                out.append(
                    self.finding(
                        rel, line,
                        f"Settings.{fname} missing from the configmap "
                        "template — the rendered settings.json cannot "
                        "carry it",
                    )
                )
        return out
