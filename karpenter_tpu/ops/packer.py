"""The batched packing kernel: a jitted first-fit-decreasing mass scan.

This replaces the reference's per-pod FFD loop (karpenter-core bin-packing,
reference designs/bin-packing.md:18-42) with a TPU-shaped formulation: one
`lax.scan` step per *pod class* (see ops/tensorize.py), placing the whole
class at once with vectorized tensor ops:

- **first-fit over open nodes**: per-slot capacity for the class is a
  broadcast floor-divide over the residual-resource matrix [K, R]; the
  "first fit, in node order" semantics of FFD become an exclusive-cumsum
  prefix allocation over the K axis — every slot takes
  ``clip(n - prefix_capacity, 0, cap)``.
- **new-node opening**: the best config for the class is an argmin of
  price-per-pod over the config axis [C]; `ceil(n/per_node)` fresh slots
  open in one shot via an index-window mask.
- **anti-affinity / hostname spread**: a per-(signature, slot) placement
  counter caps how many pods of a tracked signature each node takes.

Everything is static-shape: (G, C, K, R) are padded to buckets by the
caller, so XLA compiles once per bucket and replays.  The scan state is
O(K·R + S·K); per-step work is O(K·R + C·R) elementwise — MXU-free but
VPU-friendly, fully fused by XLA.

Shardability: the C axis (configs) and K axis (node slots) are both
embarrassingly data-parallel except for the K-cumsum and the C-argmin,
which XLA SPMD lowers to collectives; `parallel/mesh.py` provides the
pjit wrappers used by the multi-chip dry run.
"""

from __future__ import annotations

from functools import partial
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_tpu.obs.device import OBSERVATORY
from karpenter_tpu.ops.tensorize import CompiledProblem
from karpenter_tpu.utils.trace import TRACER, phase


class PackResult(NamedTuple):
    """Device outputs of one packing solve."""

    # counts are PLACEMENT UNITS, not raw pods: a hostname co-location
    # macro class (tensorize.ClassMeta.group_size) is one unit covering
    # its whole group — decode expands units back to pods
    take: jax.Array  # [G, K] int32 — units of class g placed on slot k
    leftover: jax.Array  # [G] int32 — units that fit nowhere
    node_cfg: jax.Array  # [K] int32 — config row per slot (-1 = unused)
    node_pods: jax.Array  # [K] int32 — total placement units per slot
    node_used: jax.Array  # [K, R] float32 — final residual usage
    # optional pre-bundled (take+leftover+cfg+used) flat buffer: present on
    # the buffered path so the solver's fetch is exactly ONE transfer
    bundle: Optional[jax.Array] = None


def _per_node_cap(rem: jax.Array, req: jax.Array) -> jax.Array:
    """How many copies of `req` fit in each residual vector.

    rem: [..., R], req: [R] -> int32 [...].  Axes the class doesn't request
    are unconstraining.  The 1e-4 nudge absorbs float32 accumulation error
    (requests are >= 1e-3 in canonical units, so it can't overcount).
    """
    safe = jnp.where(req > 0, req, 1.0)
    per_axis = jnp.where(
        req > 0, jnp.floor(rem / safe + 1e-4), jnp.float32(2**30)
    )
    cap = jnp.min(per_axis, axis=-1)
    return jnp.maximum(cap, 0.0).astype(jnp.int32)


def _unpack_feas_bits(words: jax.Array, n_cols: int) -> jax.Array:
    """Device-side inverse of host `np.packbits(..., bitorder="little")`
    for any integer word width: bit k of word w is feasibility column
    ``w * width + k``.  THE single bit-order contract for every packed
    upload path (pack_kernel's uint8 rows, pack_kernel_buffered's int32
    words) — change it here and both stay in sync."""
    width = words.dtype.itemsize * 8
    shifts = jnp.arange(width, dtype=words.dtype)
    bits = (words[:, :, None] >> shifts) & words.dtype.type(1)
    return bits.astype(bool).reshape(words.shape[0], -1)[:, :n_cols]


def _pack_core(
    req, cnt, maxper, slot, feas, alloc, price, openable,
    used0, cfg0, npods0, next_slot0, sig0, *, k_slots, objective,
) -> PackResult:
    """The packing math, shared by every entry point (plain, bit-packed,
    and single-buffer).  Traced inside the callers' jits."""
    K = k_slots
    idx = jnp.arange(K, dtype=jnp.int32)
    # price normalized to [0, 1) so it can serve as a pure tie-break in the
    # "nodes" objective (reference FFD fits maximal pods, then picks the
    # cheapest type — designs/bin-packing.md:18-42 + instance.go:391-408)
    price_ceil = jnp.max(jnp.where(openable, price, 0.0)) + 1.0
    price_norm = price / price_ceil

    # ---- per-class NEW-NODE choice, hoisted out of the scan -------------
    # The best openable config for a class depends only on (feas, alloc,
    # price, maxper) — never on the scan carry — so it is one parallel
    # [G, C] pass instead of G sequential [C, R] passes inside the scan.
    # The scan's critical path is then pure [K]-sized work per class, which
    # is what makes the sequential FFD latency-viable on a real chip.
    cap_all = _per_node_cap(alloc[None, :, :], req[:, None, :])  # [G, C]
    cap_all = jnp.minimum(cap_all, maxper[:, None])
    ok_all = feas & openable[None, :] & (cap_all > 0)
    if objective == "cost":
        # minimize $/pod (may open more, smaller nodes)
        score_all = price[None, :] / cap_all.astype(jnp.float32)
    else:
        # minimize node count: max pods-per-node, price as tie-break
        score_all = -cap_all.astype(jnp.float32) + price_norm[None, :]
    score_all = jnp.where(ok_all, score_all, jnp.inf)
    c_star_all = jnp.argmin(score_all, axis=1).astype(jnp.int32)  # [G]
    g_idx = jnp.arange(req.shape[0])
    new_ok_all = ok_all[g_idx, c_star_all]  # [G]
    per_all = jnp.maximum(cap_all[g_idx, c_star_all], 1)  # [G]

    def step(carry, xs):
        used, cfg, npods, nxt, sigcnt = carry
        req_g, n_g, maxper_g, slot_g, feas_g, c_star, new_ok, per = xs

        # ---- fill open nodes, first-fit in slot order -------------------
        valid = cfg >= 0
        cfg_safe = jnp.maximum(cfg, 0)
        rem = alloc[cfg_safe] - used  # [K, R]
        cap = _per_node_cap(rem, req_g)  # [K]
        sig_room = jnp.maximum(maxper_g - sigcnt[slot_g], 0)
        cap = jnp.minimum(cap, sig_room)
        cap = jnp.where(valid & feas_g[cfg_safe], cap, 0)
        prefix = jnp.cumsum(cap) - cap  # exclusive cumsum
        take1 = jnp.clip(n_g - prefix, 0, cap)
        n2 = n_g - take1.sum()

        # ---- open new nodes on the precomputed best config ---------------
        need = jnp.where(new_ok, (n2 + per - 1) // per, 0)
        opened = jnp.minimum(need, K - nxt)
        window = (idx >= nxt) & (idx < nxt + opened)
        take2 = jnp.where(window, jnp.clip(n2 - (idx - nxt) * per, 0, per), 0)
        leftover = n2 - take2.sum()

        take = take1 + take2
        used = used + take[:, None].astype(jnp.float32) * req_g[None, :]
        cfg = jnp.where(window, c_star, cfg)
        npods = npods + take
        sigcnt = sigcnt.at[slot_g].add(take)
        nxt = nxt + opened
        return (used, cfg, npods, nxt, sigcnt), (take, leftover)

    carry0 = (used0, cfg0, npods0, next_slot0, sig0)
    (used, cfg, npods, _, _), (takes, leftovers) = jax.lax.scan(
        step,
        carry0,
        (req, cnt, maxper, slot, feas, c_star_all, new_ok_all, per_all),
        unroll=8,
    )
    return PackResult(
        take=takes, leftover=leftovers, node_cfg=cfg, node_pods=npods,
        node_used=used,
    )


@partial(jax.jit, static_argnames=("k_slots", "objective"))
def pack_kernel(
    req: jax.Array,  # [G, R] float32
    cnt: jax.Array,  # [G] int32
    maxper: jax.Array,  # [G] int32
    slot: jax.Array,  # [G] int32
    feas: jax.Array,  # [G, C] bool (or uint8 bit-packed rows)
    alloc: jax.Array,  # [C, R] float32
    price: jax.Array,  # [C] float32
    openable: jax.Array,  # [C] bool
    used0: jax.Array,  # [K, R] float32 (existing-node prefill, zero-padded)
    cfg0: jax.Array,  # [K] int32 (-1 where no existing node)
    npods0: jax.Array,  # [K] int32
    next_slot0: jax.Array,  # int32 — first free slot
    sig0: jax.Array,  # [S, K] int32 — per-signature placement counts
    *,
    k_slots: int,
    objective: str = "nodes",
) -> PackResult:
    if feas.dtype == jnp.uint8:
        # bit-packed rows (parallel/mesh.py packs host-side): ship 1 bit
        # per entry, unpack on device — the upload is latency that lands
        # on the solve budget on a tunneled link
        feas = _unpack_feas_bits(feas, feas.shape[1] * 8)
    return _pack_core(
        req, cnt, maxper, slot, feas, alloc, price, openable,
        used0, cfg0, npods0, next_slot0, sig0,
        k_slots=k_slots, objective=objective,
    )


@jax.jit
def admit_kernel(
    req: jax.Array,  # [G, R] float32 — resident class requests
    cnt: jax.Array,  # [G] int32
    feas: jax.Array,  # [G, C] bool
    alloc: jax.Array,  # [C, R] float32
    openable: jax.Array,  # [C] bool
    used0: jax.Array,  # [K, R] float32 — live-node prefill
    cfg0: jax.Array,  # [K] int32 (fe+k on live columns, -1 past them)
    g: jax.Array,  # int32 — the single class row to score
) -> jax.Array:
    """The single-pod admission score: ONE tiny fused dispatch over the
    device-resident buffers (docs/designs/admission-fastpath.md).

    This is exactly `_pack_core`'s existing-node fill for one class —
    the same `_per_node_cap` row math, the same feasibility gate, the
    same exclusive-cumsum first-fit prefix — with the scan, the
    signature counters, and the new-node opening all dropped, because
    the fast path's eligibility gate guarantees they are vacuous for
    the resident plain shape (maxper=BIG, sig0=0, single live class).
    Sharing `_per_node_cap` keeps the arithmetic provably identical to
    the authoritative solve: both paths floor the same float32 ratios,
    so the sequential host oracle in scheduling/fastpath.py can demand
    bit-equality, not tolerance.

    Returns ONE [K+2] int32 array — take-per-slot, total placed, and an
    open-capacity bit (some openable config fits the class, i.e. the
    batched solve could still place it on a NEW node) — so the host
    fetch is exactly one transfer.
    """
    req_g = req[g]
    feas_g = feas[g]
    valid = cfg0 >= 0
    cfg_safe = jnp.maximum(cfg0, 0)
    rem = alloc[cfg_safe] - used0  # [K, R]
    cap = _per_node_cap(rem, req_g)  # [K]
    cap = jnp.where(valid & feas_g[cfg_safe], cap, 0)
    prefix = jnp.cumsum(cap) - cap  # exclusive cumsum: first-fit order
    take1 = jnp.clip(cnt[g] - prefix, 0, cap)
    placed = take1.sum()
    cap_open = _per_node_cap(alloc, req_g)  # [C]
    open_ok = (feas_g & openable & (cap_open > 0)).any()
    return jnp.concatenate(
        [take1, jnp.stack([placed, open_ok.astype(jnp.int32)])]
    )


@partial(
    jax.jit, static_argnames=("Gp", "Cp", "Kp", "R", "Sp", "objective")
)
def pack_kernel_buffered(
    buf: jax.Array,  # ONE flat float32 buffer (see build_input_buffer)
    alloc: jax.Array,  # [C, R] float32 (device-cached catalog constant)
    price: jax.Array,  # [C] float32
    openable: jax.Array,  # [C] bool
    *,
    Gp: int,
    Cp: int,
    Kp: int,
    R: int,
    Sp: int,
    objective: str = "nodes",
):
    """Single-upload / single-dispatch / single-read solve path.

    On the tunneled TPU every host<->device operation queues a round
    trip, and the sync at fetch time drains them all — so the per-solve
    tensors travel as ONE array (bitcast-packed by build_input_buffer),
    one jit call does slice + unpack + pack + output-bundling, and the
    caller reads back ONE array (`bundle`).  The PackResult device arrays
    ride along un-fetched for the overflow fallback."""
    off = 0
    req = buf[off : off + Gp * R].reshape(Gp, R); off += Gp * R
    used0 = buf[off : off + Kp * R].reshape(Kp, R); off += Kp * R
    n_i32 = 3 * Gp + 2 * Kp + 1 + Sp * Kp
    i32 = jax.lax.bitcast_convert_type(buf[off : off + n_i32], jnp.int32)
    off += n_i32
    cnt = i32[:Gp]
    maxper = i32[Gp : 2 * Gp]
    slot = i32[2 * Gp : 3 * Gp]
    cfg0 = i32[3 * Gp : 3 * Gp + Kp]
    npods0 = i32[3 * Gp + Kp : 3 * Gp + 2 * Kp]
    next0 = i32[3 * Gp + 2 * Kp]
    sig0 = i32[3 * Gp + 2 * Kp + 1 :].reshape(Sp, Kp)
    # feasibility bits: 32 columns per int32 word, little-endian both ways
    W = (Cp + 31) // 32
    fi = jax.lax.bitcast_convert_type(buf[off:], jnp.int32).reshape(Gp, W)
    feas = _unpack_feas_bits(fi, Cp)
    res = _pack_core(
        req, cnt, maxper, slot, feas, alloc, price, openable,
        used0, cfg0, npods0, next0, sig0,
        k_slots=Kp, objective=objective,
    )
    bundle = bundle_outputs(res.take, res.leftover, res.node_cfg, res.node_used)
    return bundle, res


def build_input_buffer(args) -> np.ndarray:
    """Flatten the padded kernel arguments (minus the device-cached
    catalog constants) into the ONE float32 upload buffer
    pack_kernel_buffered expects."""
    (req, cnt, maxper, slot, feas, _alloc, _price, _openable,
     used0, cfg0, npods0, e0, sig0) = args
    i32 = np.concatenate(
        [
            cnt, maxper, slot, cfg0, npods0,
            np.asarray([e0], np.int32), sig0.ravel(),
        ]
    ).astype(np.int32)
    packed = np.packbits(feas, axis=1, bitorder="little")
    W4 = 4 * ((packed.shape[1] + 3) // 4)  # pad bytes to whole int32 words
    if packed.shape[1] != W4:
        packed = np.pad(packed, ((0, 0), (0, W4 - packed.shape[1])))
    feas_i32 = packed.reshape(-1).view("<u4").astype(np.uint32).view(np.int32)
    return np.concatenate(
        [
            req.ravel().astype(np.float32),
            used0.ravel().astype(np.float32),
            i32.view(np.float32),
            feas_i32.view(np.float32),
        ]
    )


# ---------------------------------------------------------------------------
# Host wrapper: padding / bucketing so jit compiles once per bucket
# ---------------------------------------------------------------------------


def _bucket(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


def _bucket_classes(n: int) -> int:
    """Class-axis bucket: the scan runs one sequential step per padded
    class, so padding waste is pure latency.  Below 64 use power-of-two
    buckets (few variants); above, round up to a multiple of 64 — at most
    ~1.25x more compile variants, but a 317-class solve runs 320 steps
    instead of 512."""
    if n <= 64:
        return _bucket(n)
    return ((n + 63) // 64) * 64


def node_slot_bound(prob: CompiledProblem) -> int:
    """Upper bound on node slots: existing nodes + worst case one node per
    *constrained* pod but bounded-by-capacity for the rest."""
    n_existing = len(prob.used0)
    n_pods = prob.total_pods()
    constrained = int(prob.cnt[prob.maxper < 2**20].sum()) if len(prob.cnt) else 0
    # every unconstrained pod could still need its own node if nothing else
    # fits; cap the bound at total pods to stay finite but tight in practice
    return n_existing + max(constrained, min(n_pods, max(256, constrained)))


def pad_problem(prob: CompiledProblem, k_slots: int = 0) -> Tuple[tuple, int]:
    """Pad a compiled problem to power-of-two bucket shapes.

    Returns the positional argument tuple for `pack_kernel` plus the padded
    slot count Kp (the kernel's static shape).  Bucketing means XLA compiles
    once per (G, C, K) bucket and replays for every solve that fits.
    """
    G, C = prob.feas.shape
    R = prob.req.shape[1] if prob.req.size else len(prob.axes)
    if k_slots <= 0:
        k_slots = node_slot_bound(prob)
    Gp = _bucket_classes(max(G, 1))
    Cp, Kp = _bucket(max(C, 1)), _bucket(max(k_slots, 1))
    Sp = _bucket(max(prob.n_track_slots, 1), floor=2)
    E = len(prob.used0)

    req = np.zeros((Gp, R), np.float32)
    req[:G] = prob.req
    cnt = np.zeros(Gp, np.int32)
    cnt[:G] = prob.cnt
    maxper = np.zeros(Gp, np.int32)
    maxper[:G] = prob.maxper
    slot = np.zeros(Gp, np.int32)
    slot[:G] = prob.slot
    feas = np.zeros((Gp, Cp), bool)
    feas[:G, :C] = prob.feas
    alloc = np.zeros((Cp, R), np.float32)
    alloc[:C] = prob.alloc
    price = np.full(Cp, np.inf, np.float32)
    price[:C] = prob.price
    openable = np.zeros(Cp, bool)
    openable[:C] = prob.openable
    used0 = np.zeros((Kp, R), np.float32)
    used0[:E] = prob.used0
    cfg0 = np.full(Kp, -1, np.int32)
    cfg0[:E] = prob.cfg0
    npods0 = np.zeros(Kp, np.int32)
    npods0[:E] = prob.npods0
    sig0 = np.zeros((Sp, Kp), np.int32)
    sig0[: prob.sig_used0.shape[0], :E] = prob.sig_used0

    args = (
        req, cnt, maxper, slot, feas, alloc, price, openable,
        # next_slot0 stays a HOST scalar: a jnp scalar here costs a full
        # device round trip the moment the buffered path np.asarray()s it
        used0, cfg0, npods0, np.int32(E), sig0,
    )
    return args, Kp


@jax.jit
def bundle_outputs(
    take: jax.Array,
    leftover: jax.Array,
    node_cfg: jax.Array,
    node_used: jax.Array,
) -> jax.Array:
    """Everything decode needs, as ONE flat float32 buffer.

    On the tunneled TPU link a device->host read costs a full round trip
    PER ARRAY (jax.device_get copies pytree leaves separately), and the
    solve's fetch moved six arrays — six round trips dominated the whole
    solve latency.  Bitcasting the int32 pieces to float32 and
    concatenating makes the fetch exactly one transfer; the host view()s
    the slices back losslessly (bitcast, not cast)."""
    vals, idx, nnz = compact_take(take)
    as_f32 = lambda a: jax.lax.bitcast_convert_type(
        a.astype(jnp.int32), jnp.float32
    ).reshape(-1)
    return jnp.concatenate(
        [
            as_f32(vals),
            as_f32(idx),
            as_f32(nnz.reshape(1)),
            as_f32(leftover),
            as_f32(node_cfg),
            node_used.astype(jnp.float32).reshape(-1),
        ]
    )


def fetch_bundled(res: "PackResult"):
    """The single-read fetch: bundle the kernel outputs on device (or use
    the pre-bundled buffer when present), read ONE array, slice it apart
    on the host.  Shared by the in-process solver and the sidecar so the
    transfer-hygiene contract can't desynchronize between them.
    Returns host (take, leftover, node_cfg, node_used)."""
    # getattr: duck-typed pack results (custom pack_fn namedtuples) may
    # not carry a bundle field at all
    buf = getattr(res, "bundle", None)
    if buf is None:
        buf = OBSERVATORY.dispatch(
            "bundle_outputs", bundle_outputs,
            res.take, res.leftover, res.node_cfg, res.node_used,
        )
    return unbundle_outputs(np.asarray(buf), res.take, res.node_used.shape)


def unbundle_outputs(
    buf: np.ndarray, take_dev: jax.Array, node_used_shape: Tuple[int, int]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Host-side inverse of `bundle_outputs`: slice the flat buffer and
    bitcast the int32 sections back.  Returns (take, leftover, node_cfg,
    node_used); falls back to a dense take fetch iff nnz overflowed the
    sparse buffer (same contract as expand_take)."""
    G = take_dev.shape[0]
    k = int(np.prod(take_dev.shape)) // G
    ncap = G + 2 * k
    i32 = buf.view(np.int32)
    off = 0
    vals = i32[off : off + ncap]; off += ncap
    idx = i32[off : off + ncap]; off += ncap
    nnz = int(i32[off]); off += 1
    leftover = i32[off : off + G]; off += G
    K = node_used_shape[0]
    node_cfg = i32[off : off + K]; off += K
    node_used = buf[off:].reshape(node_used_shape).copy()
    take = expand_take(vals, idx, nnz, take_dev)
    return take, leftover.copy(), node_cfg.copy(), node_used


@jax.jit
def compact_take(take: jax.Array):
    """Sparse (values, flat indices, nnz) view of a take matrix
    ([G, K...] — trailing slot axes may be flat or tiled).

    FFD leaves take sparse — each class touches a prefix of partially
    filled slots plus its freshly opened window — and on a high-latency
    device link fetching the dense int32 matrix is the solve's largest
    transfer.  Callers fetch the sparse triple and fall back to the dense
    array iff nnz overflowed the static (heuristic) G + 2K buffer."""
    flat = take.reshape(-1)
    k = flat.shape[0] // take.shape[0]
    ncap = take.shape[0] + 2 * k
    (idx,) = jnp.nonzero(flat, size=ncap, fill_value=0)
    return flat[idx], idx, jnp.count_nonzero(flat)


def expand_take(
    vals: np.ndarray, idx: np.ndarray, nnz: int, take_dev: jax.Array
) -> np.ndarray:
    """Rebuild the dense take matrix from its fetched sparse triple,
    falling back to a dense fetch iff nnz overflowed the static buffer.
    Kept separate from the fetch so callers can bundle the sparse triple
    into ONE device_get with their other outputs (each device_get is a
    full round trip on a tunneled link)."""
    shape = take_dev.shape
    if int(nnz) > len(idx):
        return np.asarray(jax.device_get(take_dev))
    out = np.zeros(int(np.prod(shape)), np.int32)
    out[idx] = vals
    return out.reshape(shape)


# ---------------------------------------------------------------------------
# Batched what-if removal verdicts (consolidation's N simulations in ONE
# dispatch — see docs/designs/consolidation-batching.md)
# ---------------------------------------------------------------------------

# verdict row layout ([B, RV_WIDTH] float32; int fields bit-exact in f32
# range — every count/index here is far below 2**24)
RV_LEFTOVER = 0  # placement units that fit nowhere
RV_NEW_COUNT = 1  # freshly opened node slots
RV_C_MIN = 2  # config row of the cheapest widen-equivalent alternate
RV_MIN_PRICE = 3  # its (float32) price; +inf when the mask was empty
RV_C_STAR = 4  # config row the kernel committed for the single new node
RV_MERGE = 5  # 1.0 when decode compaction might merge >=2 new nodes to 1
RV_WIDTH = 6


def _verdict_row(
    cnt_p, rm, perm,
    req, maxper, slot, feas, alloc, price, openable,
    used0, cfg0, npods0, next_slot0, sig0,
    pool_id, zone_id, ct_id, compactable,
    *, k_slots, objective,
):
    """One what-if subset's verdict row ([RV_WIDTH] float32) — the SINGLE
    definition of the batched verdict math, vmapped by BOTH the
    per-subset kernel (host-built counts/permutations) and the population
    kernel (device-built from removal masks), so the two entry points can
    never drift apart arithmetically.

    Inputs per element: ``cnt_p`` per-class counts in PERMUTED positions,
    ``rm`` the removed-slot mask, ``perm`` the class order the subset's
    own compile would have produced."""
    idx = jnp.arange(k_slots, dtype=jnp.int32)
    feas_p = feas[perm]
    res = _pack_core(
        req[perm], cnt_p, maxper[perm], slot[perm], feas_p,
        alloc, price, openable,
        used0, jnp.where(rm, -1, cfg0), npods0, next_slot0, sig0,
        k_slots=k_slots, objective=objective,
    )
    leftover_units = res.leftover.sum()
    newmask = (idx >= next_slot0) & (res.node_pods > 0)
    new_count = newmask.sum()
    # single-new-node replacement price, widen-equivalent: min config
    # price over { committed } ∪ { openable configs feasible for every
    # class on the node, holding its final usage, sharing the
    # committed pool/zone/capacity-type } — exactly the alternate set
    # _add_alternate_types widens to, whose min VirtualNode.
    # cheapest_price() reports on the sequential path
    k_star = jnp.argmax(newmask)
    c_star = jnp.maximum(res.node_cfg[k_star], 0)
    on_new = res.take[:, k_star] > 0
    class_feas = jnp.where(on_new[:, None], feas_p, True).all(axis=0)
    fits_used = (
        res.node_used[k_star][None, :] <= alloc + 1e-6
    ).all(axis=1)
    same = (
        (pool_id == pool_id[c_star])
        & (zone_id == zone_id[c_star])
        & (ct_id == ct_id[c_star])
    )
    m = openable & class_feas & fits_used & same
    masked = jnp.where(m, price, jnp.inf)
    c_min = jnp.argmin(masked).astype(jnp.int32)
    min_price = masked[c_min]
    # decode-compaction escape hatch: a >=2-new-node result flips to
    # "fits with one replacement" only if _compact_small_nodes can
    # merge the new nodes down to ONE.  Necessary conditions, checked
    # here so conclusive not-fits verdicts skip the host fallback: all
    # but at most one new node is a donor (<= 8 placement units, every
    # class on it movable), and SOME openable config feasible for
    # every new-node class holds the union of all new-node load (the
    # try_add probe can re-type a node through the widen machinery, so
    # the absorber is not limited to its committed config).  The test
    # is deliberately a superset of what compaction can really do —
    # a spurious positive costs one host fallback, never a wrong
    # verdict.
    bad_k = ((res.take > 0) & (~compactable[perm])[:, None]).any(axis=0)
    donor_k = newmask & (res.node_pods <= 8) & ~bad_k
    n_nondonor = (newmask & ~donor_k).sum()
    new_load = jnp.where(newmask[:, None], res.node_used, 0.0).sum(
        axis=0
    )
    on_any_new = ((res.take > 0) & newmask[None, :]).any(axis=1)
    all_new_feas = jnp.where(on_any_new[:, None], feas_p, True).all(
        axis=0
    )
    hold = (
        (new_load[None, :] <= alloc + 1e-6).all(axis=1)
        & openable
        & all_new_feas
    ).any()
    merge = (new_count >= 2) & (n_nondonor <= 1) & hold
    return jnp.stack(
        [
            leftover_units.astype(jnp.float32),
            new_count.astype(jnp.float32),
            c_min.astype(jnp.float32),
            min_price,
            c_star.astype(jnp.float32),
            merge.astype(jnp.float32),
        ]
    )


@partial(jax.jit, static_argnames=("k_slots", "objective"))
def removal_verdict_kernel(
    req: jax.Array,  # [G, R] float32 — base class requests
    maxper: jax.Array,  # [G] int32
    slot: jax.Array,  # [G] int32
    feas: jax.Array,  # [G, C] bool
    alloc: jax.Array,  # [C, R] float32
    price: jax.Array,  # [C] float32
    openable: jax.Array,  # [C] bool
    used0: jax.Array,  # [K, R] float32 — FULL remaining-cluster prefill
    cfg0: jax.Array,  # [K] int32
    npods0: jax.Array,  # [K] int32
    next_slot0: jax.Array,  # int32 — first free slot (== live-node count)
    sig0: jax.Array,  # [S, K] int32
    pool_id: jax.Array,  # [C] int32 — -1 on existing/padding rows
    zone_id: jax.Array,  # [C] int32
    ct_id: jax.Array,  # [C] int32
    compactable: jax.Array,  # [G] bool — class movable by decode compaction
    cnt_b: jax.Array,  # [B, G] int32 — per-element counts, PERMUTED positions
    rm_b: jax.Array,  # [B, K] bool — per-element removed-slot mask
    perm_b: jax.Array,  # [B, G] int32 — per-element class order
    *,
    k_slots: int,
    objective: str = "nodes",
) -> jax.Array:
    """One batched dispatch answering N what-if consolidation questions.

    The base problem (classes over the candidate-universe pods, existing
    rows over the FULL remaining cluster) is compiled and padded ONCE;
    each batch element b expresses one candidate subset as

    - ``rm_b[b]``: a removal mask over the node-slot axis — masked slots
      get ``cfg0 = -1``, which zeroes their placement capacity exactly as
      if the node were absent (first-fit slot ORDER of the survivors is
      unchanged, so the packing equals the subset's own compile),
    - ``cnt_b[b]``: the subset's reschedulable pods as per-class counts
      (classes outside the subset are 0-count no-ops), and
    - ``perm_b[b]``: the class order the subset's OWN compile would have
      produced (first occurrence over its pod list) — the scan is order-
      sensitive, so each element replays its sequential class order.

    Only per-element VERDICT rows come back (see RV_* layout): fits /
    new-node count / replacement price (computed with the decoder's
    widen-equivalent alternate scan so the price matches
    ``VirtualNode.cheapest_price()``), plus a donor flag marking the one
    decode divergence (small-node compaction) the caller must resolve
    host-side.  The full decode runs host-side only for the winner.
    """

    def one(cnt_p, rm, perm):
        return _verdict_row(
            cnt_p, rm, perm,
            req, maxper, slot, feas, alloc, price, openable,
            used0, cfg0, npods0, next_slot0, sig0,
            pool_id, zone_id, ct_id, compactable,
            k_slots=k_slots, objective=objective,
        )

    return jax.vmap(one)(cnt_b, rm_b, perm_b)


# population search over removal masks (docs/designs/consolidation-search.md)
# — sentinels for the device-side class-order computation.  A class with a
# zero count sorts AFTER every present class (the host path appends absent
# classes in index order; jnp.argsort is stable, so one shared key gives
# the identical order); the composite first-occurrence keys are
# host-guarded to stay below the sentinel (solver._build_removal_base).
POP_KEY_ABSENT = 2**30  # argsort key for classes outside the subset
POP_OCC_ABSENT = 2**29  # occ fill for (candidate, class) pairs w/o pods


@partial(jax.jit, static_argnames=("k_slots", "objective"))
def population_verdict_kernel(
    req: jax.Array,  # [G, R] float32 — base class requests
    maxper: jax.Array,  # [G] int32
    slot: jax.Array,  # [G] int32
    feas: jax.Array,  # [G, C] bool
    alloc: jax.Array,  # [C, R] float32
    price: jax.Array,  # [C] float32
    openable: jax.Array,  # [C] bool
    used0: jax.Array,  # [K, R] float32 — FULL remaining-cluster prefill
    cfg0: jax.Array,  # [K] int32
    npods0: jax.Array,  # [K] int32
    next_slot0: jax.Array,  # int32 — first free slot (== live-node count)
    sig0: jax.Array,  # [S, K] int32
    pool_id: jax.Array,  # [C] int32
    zone_id: jax.Array,  # [C] int32
    ct_id: jax.Array,  # [C] int32
    compactable: jax.Array,  # [G] bool
    cand_cnt: jax.Array,  # [U, G] int32 — per-candidate per-class counts
    cand_slot: jax.Array,  # [U] int32 — live column (k_slots = not live)
    cand_occ: jax.Array,  # [U, G] int32 — first-occurrence composite
    sort_rank: jax.Array,  # [G] int32 — dense rank of the FFD sort key
    occ_span: jax.Array,  # int32 — strict upper bound on cand_occ values
    masks: jax.Array,  # [P, U] bool — the population of removal masks
    *,
    k_slots: int,
    objective: str = "nodes",
) -> jax.Array:
    """The population search's scoring dispatch: P candidate SUBSETS,
    encoded as removal masks over the universe axis, scored through the
    shared verdict math in ONE vmapped call — with the per-subset count
    vector, removed-slot mask, and FFD class order all derived ON DEVICE
    from the mask, so the host never loops over the population.

    Per member (see docs/designs/consolidation-search.md §mask encoding):

    - counts: ``cnt = Σ_{j∈mask} cand_cnt[j]`` — the subset's
      reschedulable pods as per-class placement counts;
    - removed slots: scatter of ``cand_slot`` over the selected rows
      (candidates absent from the live columns scatter out of range and
      drop — both paths compiled them away already);
    - class order: the subset's own compile sorts classes by the FFD key
      with ties in first-occurrence order over its pod list.  Candidates
      concatenate in universe rank order, so the first occurrence of
      class g is ``min_j(cand_occ[j, g])`` over selected j, where
      ``cand_occ[j, g] = j * max_pods + pos``; the composite argsort key
      ``sort_rank * occ_span + occ`` reproduces the host sort exactly
      (dense ranks make float-key ties explicit; jnp.argsort is stable,
      so absent classes keep index order behind the sentinel).

    Returns the [P, RV_WIDTH] verdict matrix — identical rows to
    ``removal_verdict_kernel`` for identical subsets, which is what the
    parity fuzz (tests/test_consolidation_search.py) pins."""

    def one(sel):
        cnt_g = jnp.where(sel[:, None], cand_cnt, 0).sum(axis=0)
        cnt_g = cnt_g.astype(jnp.int32)
        rm = (
            jnp.zeros(k_slots, jnp.int32)
            .at[cand_slot]
            .max(sel.astype(jnp.int32), mode="drop")
        ) > 0
        occ = jnp.where(sel[:, None], cand_occ, POP_OCC_ABSENT).min(axis=0)
        key = jnp.where(
            cnt_g > 0, sort_rank * occ_span + occ, POP_KEY_ABSENT
        )
        perm = jnp.argsort(key).astype(jnp.int32)
        return _verdict_row(
            cnt_g[perm], rm, perm,
            req, maxper, slot, feas, alloc, price, openable,
            used0, cfg0, npods0, next_slot0, sig0,
            pool_id, zone_id, ct_id, compactable,
            k_slots=k_slots, objective=objective,
        )

    return jax.vmap(one)(masks)


def dispatch_population_verdicts(
    padded_args: tuple,
    k_slots: int,
    pool_id: np.ndarray,
    zone_id: np.ndarray,
    ct_id: np.ndarray,
    compactable: np.ndarray,
    cand_cnt: np.ndarray,
    cand_slot: np.ndarray,
    cand_occ: np.ndarray,
    sort_rank: np.ndarray,
    occ_span: int,
    masks: np.ndarray,
    objective: str = "nodes",
):
    """The ENQUEUE half of the population scoring kernel over pre-padded
    base args (`pad_problem` output, device-resident via the removal
    base): an async JAX dispatch that returns the in-flight device array
    WITHOUT blocking — the pipelined reconcile's dispatch stage, so the
    device scores masks while the host runs other controllers.  The
    caller pads the population and universe axes to power-of-two buckets
    so XLA compiles once per shape; `fetch_verdict_rows` is the blocking
    half."""
    (req, _cnt, maxper, slot, feas, alloc, price, openable,
     used0, cfg0, npods0, e0, sig0) = padded_args
    with phase("dispatch"):
        return OBSERVATORY.dispatch(
            "population_verdict_kernel", population_verdict_kernel,
            req, maxper, slot, feas, alloc, price, openable,
            used0, cfg0, npods0, e0, sig0,
            pool_id, zone_id, ct_id, compactable,
            cand_cnt, cand_slot, cand_occ, sort_rank,
            jnp.int32(occ_span), masks,
            k_slots=k_slots, objective=objective,
        )


def fetch_verdict_rows(out, kernel_name: str) -> np.ndarray:
    """The BLOCKING half of a verdict dispatch: one device read for the
    whole batch/population, recorded as the kernel's `device.block` span
    (the hard barrier on the tick timeline)."""
    with phase("device_block"), TRACER.span(f"device.block.{kernel_name}"):
        return np.asarray(out)


def run_population_verdicts(
    padded_args: tuple,
    k_slots: int,
    pool_id: np.ndarray,
    zone_id: np.ndarray,
    ct_id: np.ndarray,
    compactable: np.ndarray,
    cand_cnt: np.ndarray,
    cand_slot: np.ndarray,
    cand_occ: np.ndarray,
    sort_rank: np.ndarray,
    occ_span: int,
    masks: np.ndarray,
    objective: str = "nodes",
) -> np.ndarray:
    """Dispatch + fetch in one call (the sequential schedule): the [P,
    RV_WIDTH] verdict matrix for the whole population."""
    out = dispatch_population_verdicts(
        padded_args, k_slots, pool_id, zone_id, ct_id, compactable,
        cand_cnt, cand_slot, cand_occ, sort_rank, occ_span, masks,
        objective=objective,
    )
    return fetch_verdict_rows(out, "population_verdict_kernel")


def run_removal_verdicts(
    padded_args: tuple,
    k_slots: int,
    pool_id: np.ndarray,
    zone_id: np.ndarray,
    ct_id: np.ndarray,
    compactable: np.ndarray,
    cnt_b: np.ndarray,
    rm_b: np.ndarray,
    perm_b: np.ndarray,
    objective: str = "nodes",
) -> np.ndarray:
    """Dispatch the batched verdict kernel over pre-padded base args
    (`pad_problem` output) and fetch the [B, RV_WIDTH] verdict matrix —
    ONE device read for the whole batch.  The batch axis is padded to a
    power-of-two bucket by the caller so XLA compiles once per shape."""
    (req, _cnt, maxper, slot, feas, alloc, price, openable,
     used0, cfg0, npods0, e0, sig0) = padded_args
    with phase("dispatch"):
        out = OBSERVATORY.dispatch(
            "removal_verdict_kernel", removal_verdict_kernel,
            req, maxper, slot, feas, alloc, price, openable,
            used0, cfg0, npods0, e0, sig0,
            pool_id, zone_id, ct_id, compactable,
            cnt_b, rm_b, perm_b,
            k_slots=k_slots, objective=objective,
        )
    return fetch_verdict_rows(out, "removal_verdict_kernel")


# device-resident constant caches, keyed by source-array identity with the
# sources pinned in the entry so the id-based key stays sound (the same
# pattern as TensorScheduler's catalog cache).  Eviction is LRU: python
# dicts iterate in insertion order, so re-inserting on every hit keeps the
# first key the least-recently-used one.  A wholesale clear() here would
# evict every HOT device constant the moment a 33rd catalog snapshot
# appears, forcing re-uploads mid-tick on the high-latency device link.
_DEVICE_CACHE_CAP = 32


def cached_device_put(
    cache: dict, srcs: tuple, extra_key: tuple, build, shardings=None,
    site: str = "device_constants",
):
    key = tuple(id(s) for s in srcs) + extra_key
    ent = cache.get(key)
    if ent is not None and all(a is b for a, b in zip(ent[0], srcs)):
        del cache[key]  # re-insert: mark most-recently-used
        cache[key] = ent
        return ent[1]
    built = build()
    # the counted seam (obs/device.py): a cache miss is a real upload,
    # attributed to the caller's `site`; a hit transfers nothing
    dev = OBSERVATORY.put(site, built, shardings if shardings else None)
    while len(cache) >= _DEVICE_CACHE_CAP:
        cache.pop(next(iter(cache)))  # evict ONLY the least-recently-used
    cache[key] = (srcs, dev)
    return dev


_DEV_CONST_CACHE: dict = {}


def _device_constants(prob, alloc_p, price_p, openable_p):
    return cached_device_put(
        _DEV_CONST_CACHE,
        (prob.alloc, prob.price, prob.openable),
        (alloc_p.shape,),
        lambda: (alloc_p, price_p, openable_p),
        site="pack_constants",
    )


# ---------------------------------------------------------------------------
# Fleet kernel: many tenants' solves in ONE vmapped dispatch
# (docs/designs/solver-service.md).  The multi-tenant SolverService stacks
# same-bucket problems from different tenants along a leading axis and runs
# _pack_core under vmap — one device round trip amortizes dispatch overhead
# across the whole batch.
#
# Bit-identity contract: every op in _pack_core is per-problem under vmap
# (the scan, the cumsums, the argmin all reduce over NON-batch axes in the
# same order as the solo kernel), and the only float reductions are
# max/min/floor — order-insensitive — while the accumulating sums are all
# int32.  A tenant's row of the fleet solve is therefore bit-equal to its
# solo pack_kernel solve; tests/test_service_tenants.py pins it.
# ---------------------------------------------------------------------------


def fleet_row_len(Gp: int, Kp: int, R: int) -> int:
    """Length of one tenant's flat output row: dense take + leftover +
    node_cfg + node_used.  Dense (not compact_take) because per-row nnz
    varies across tenants and a static sparse cap would force the whole
    batch onto the overflow path whenever one tenant's solve is dense."""
    return Gp * Kp + Gp + Kp + Kp * R


@partial(jax.jit, static_argnames=("k_slots", "objective"))
def fleet_pack_kernel(
    cols,  # 13-tuple (PACK_ARG_ORDER) of length-B tuples of per-tenant arrays
    *,
    k_slots: int,
    objective: str = "nodes",
):
    """B same-bucket solves in one dispatch; returns ONE [B, L] float32
    buffer (L = fleet_row_len) so the service's fetch is a single read.

    ``cols`` is a pytree: stacking happens INSIDE the jit, so a tenant
    whose arrays are already device-resident (the service's tenant pool)
    uploads nothing — only numpy leaves cross the link, and the counted
    dispatch seam attributes them.  The batch size B is part of the trace
    signature (tuple length); the service pads B to a power-of-two bucket
    by repeating a row, so XLA compiles once per (B bucket, shape bucket).
    Feasibility must arrive as bool rows (pad_problem's layout) — the
    bit-packed upload variants stay solo-path-only.
    """
    stacked = [jnp.stack(col) for col in cols]

    def one(req, cnt, maxper, slot, feas, alloc, price, openable,
            used0, cfg0, npods0, next0, sig0):
        res = _pack_core(
            req, cnt, maxper, slot, feas, alloc, price, openable,
            used0, cfg0, npods0, next0, sig0,
            k_slots=k_slots, objective=objective,
        )
        as_f32 = lambda a: jax.lax.bitcast_convert_type(
            a.astype(jnp.int32), jnp.float32
        ).reshape(-1)
        return jnp.concatenate(
            [
                as_f32(res.take),
                as_f32(res.leftover),
                as_f32(res.node_cfg),
                res.node_used.astype(jnp.float32).reshape(-1),
            ]
        )

    return jax.vmap(one)(*stacked)


def fleet_unbundle(
    buf: np.ndarray, Gp: int, Kp: int, R: int
) -> List[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
    """Host-side inverse of one fleet_pack_kernel row, applied per row:
    returns [(take, leftover, node_cfg, node_used)] * B.  Bitcast (view),
    not cast, so int32 sections round-trip losslessly — the same contract
    as unbundle_outputs."""
    rows = np.ascontiguousarray(buf, dtype=np.float32)
    out = []
    for row in rows:
        i32 = row.view(np.int32)
        off = Gp * Kp
        take = i32[:off].reshape(Gp, Kp).copy()
        leftover = i32[off : off + Gp].copy()
        off += Gp
        node_cfg = i32[off : off + Kp].copy()
        off += Kp
        node_used = row[off : off + Kp * R].reshape(Kp, R).copy()
        out.append((take, leftover, node_cfg, node_used))
    return out


def run_pack(
    prob: CompiledProblem, k_slots: int = 0, objective: str = "nodes"
) -> PackResult:
    """Pad a compiled problem to bucket shapes and run the jitted kernel.

    Returns device arrays; the caller (scheduling/solver.py) decodes them
    back into nodes and placements.  If the solve overflows ``k_slots``
    (leftover pods while feasible configs remained), the caller should retry
    with a doubled bucket.

    Transfer hygiene for the high-latency device link: all per-solve
    tensors ride in ONE flat buffer (feasibility as 32-bit words, see
    build_input_buffer), the config-axis constants are uploaded once per
    catalog snapshot and reused from the device cache, and the outputs
    come back pre-bundled so the solver's fetch is a single read.
    """
    with phase("pad"):
        args, Kp = pad_problem(prob, k_slots)
        (req, _cnt, _maxper, _slot, _feas, alloc_h, price_h, openable_h,
         _used0, _cfg0, _npods0, _e0, sig0) = args
        alloc, price, openable = _device_constants(
            prob, alloc_h, price_h, openable_h
        )
        Gp, R = req.shape
        Cp = alloc_h.shape[0]
        Sp = sig0.shape[0]
        buf = build_input_buffer(args)
    bundle, res = OBSERVATORY.dispatch(
        "pack_kernel_buffered", pack_kernel_buffered,
        buf, alloc, price, openable,
        Gp=Gp, Cp=Cp, Kp=Kp, R=R, Sp=Sp, objective=objective,
    )
    return res._replace(bundle=bundle)
