"""The batched packing kernel: a jitted first-fit-decreasing mass scan.

This replaces the reference's per-pod FFD loop (karpenter-core bin-packing,
reference designs/bin-packing.md:18-42) with a TPU-shaped formulation: one
`lax.scan` step per *pod class* (see ops/tensorize.py), placing the whole
class at once with vectorized tensor ops:

- **first-fit over open nodes**: per-slot capacity for the class is a
  broadcast floor-divide over the residual-resource matrix [K, R]; the
  "first fit, in node order" semantics of FFD become an exclusive-cumsum
  prefix allocation over the K axis — every slot takes
  ``clip(n - prefix_capacity, 0, cap)``.
- **new-node opening**: the best config for the class is an argmin of
  price-per-pod over the config axis [C]; `ceil(n/per_node)` fresh slots
  open in one shot via an index-window mask.
- **anti-affinity / hostname spread**: a per-(signature, slot) placement
  counter caps how many pods of a tracked signature each node takes.

Everything is static-shape: (G, C, K, R) are padded to buckets by the
caller, so XLA compiles once per bucket and replays.  The scan state is
O(K·R + S·K); per-step work is O(K·R + C·R) elementwise — MXU-free but
VPU-friendly, fully fused by XLA.

Shardability: the C axis (configs) and K axis (node slots) are both
embarrassingly data-parallel except for the K-cumsum and the C-argmin,
which XLA SPMD lowers to collectives; `parallel/mesh.py` provides the
pjit wrappers used by the multi-chip dry run.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_tpu.ops.tensorize import CompiledProblem

class PackResult(NamedTuple):
    """Device outputs of one packing solve."""

    take: jax.Array  # [G, K] int32 — pods of class g placed on slot k
    leftover: jax.Array  # [G] int32 — pods that fit nowhere
    node_cfg: jax.Array  # [K] int32 — config row per slot (-1 = unused)
    node_pods: jax.Array  # [K] int32 — total pods per slot
    node_used: jax.Array  # [K, R] float32 — final residual usage


def _per_node_cap(rem: jax.Array, req: jax.Array) -> jax.Array:
    """How many copies of `req` fit in each residual vector.

    rem: [..., R], req: [R] -> int32 [...].  Axes the class doesn't request
    are unconstraining.  The 1e-4 nudge absorbs float32 accumulation error
    (requests are >= 1e-3 in canonical units, so it can't overcount).
    """
    safe = jnp.where(req > 0, req, 1.0)
    per_axis = jnp.where(
        req > 0, jnp.floor(rem / safe + 1e-4), jnp.float32(2**30)
    )
    cap = jnp.min(per_axis, axis=-1)
    return jnp.maximum(cap, 0.0).astype(jnp.int32)


@partial(jax.jit, static_argnames=("k_slots", "objective"))
def pack_kernel(
    req: jax.Array,  # [G, R] float32
    cnt: jax.Array,  # [G] int32
    maxper: jax.Array,  # [G] int32
    slot: jax.Array,  # [G] int32
    feas: jax.Array,  # [G, C] bool
    alloc: jax.Array,  # [C, R] float32
    price: jax.Array,  # [C] float32
    openable: jax.Array,  # [C] bool
    used0: jax.Array,  # [K, R] float32 (existing-node prefill, zero-padded)
    cfg0: jax.Array,  # [K] int32 (-1 where no existing node)
    npods0: jax.Array,  # [K] int32
    next_slot0: jax.Array,  # int32 — first free slot
    sig0: jax.Array,  # [S, K] int32 — per-signature placement counts
    *,
    k_slots: int,
    objective: str = "nodes",
) -> PackResult:
    K = k_slots
    idx = jnp.arange(K, dtype=jnp.int32)
    if feas.dtype == jnp.uint8:
        # bit-packed rows (run_pack packs host-side): the feasibility matrix
        # is the bulk of the per-solve host->device upload, and on a
        # tunneled device the upload is latency that lands on the 200ms
        # budget — ship 1 bit per entry and unpack on device
        shifts = jnp.arange(8, dtype=jnp.uint8)
        bits = (feas[:, :, None] >> shifts) & jnp.uint8(1)
        feas = bits.reshape(feas.shape[0], -1).astype(bool)
    # price normalized to [0, 1) so it can serve as a pure tie-break in the
    # "nodes" objective (reference FFD fits maximal pods, then picks the
    # cheapest type — designs/bin-packing.md:18-42 + instance.go:391-408)
    price_ceil = jnp.max(jnp.where(openable, price, 0.0)) + 1.0
    price_norm = price / price_ceil

    # ---- per-class NEW-NODE choice, hoisted out of the scan -------------
    # The best openable config for a class depends only on (feas, alloc,
    # price, maxper) — never on the scan carry — so it is one parallel
    # [G, C] pass instead of G sequential [C, R] passes inside the scan.
    # The scan's critical path is then pure [K]-sized work per class, which
    # is what makes the sequential FFD latency-viable on a real chip.
    cap_all = _per_node_cap(alloc[None, :, :], req[:, None, :])  # [G, C]
    cap_all = jnp.minimum(cap_all, maxper[:, None])
    ok_all = feas & openable[None, :] & (cap_all > 0)
    if objective == "cost":
        # minimize $/pod (may open more, smaller nodes)
        score_all = price[None, :] / cap_all.astype(jnp.float32)
    else:
        # minimize node count: max pods-per-node, price as tie-break
        score_all = -cap_all.astype(jnp.float32) + price_norm[None, :]
    score_all = jnp.where(ok_all, score_all, jnp.inf)
    c_star_all = jnp.argmin(score_all, axis=1).astype(jnp.int32)  # [G]
    g_idx = jnp.arange(req.shape[0])
    new_ok_all = ok_all[g_idx, c_star_all]  # [G]
    per_all = jnp.maximum(cap_all[g_idx, c_star_all], 1)  # [G]

    def step(carry, xs):
        used, cfg, npods, nxt, sigcnt = carry
        req_g, n_g, maxper_g, slot_g, feas_g, c_star, new_ok, per = xs

        # ---- fill open nodes, first-fit in slot order -------------------
        valid = cfg >= 0
        cfg_safe = jnp.maximum(cfg, 0)
        rem = alloc[cfg_safe] - used  # [K, R]
        cap = _per_node_cap(rem, req_g)  # [K]
        sig_room = jnp.maximum(maxper_g - sigcnt[slot_g], 0)
        cap = jnp.minimum(cap, sig_room)
        cap = jnp.where(valid & feas_g[cfg_safe], cap, 0)
        prefix = jnp.cumsum(cap) - cap  # exclusive cumsum
        take1 = jnp.clip(n_g - prefix, 0, cap)
        n2 = n_g - take1.sum()

        # ---- open new nodes on the precomputed best config ---------------
        need = jnp.where(new_ok, (n2 + per - 1) // per, 0)
        opened = jnp.minimum(need, K - nxt)
        window = (idx >= nxt) & (idx < nxt + opened)
        take2 = jnp.where(window, jnp.clip(n2 - (idx - nxt) * per, 0, per), 0)
        leftover = n2 - take2.sum()

        take = take1 + take2
        used = used + take[:, None].astype(jnp.float32) * req_g[None, :]
        cfg = jnp.where(window, c_star, cfg)
        npods = npods + take
        sigcnt = sigcnt.at[slot_g].add(take)
        nxt = nxt + opened
        return (used, cfg, npods, nxt, sigcnt), (take, leftover)

    carry0 = (used0, cfg0, npods0, next_slot0, sig0)
    (used, cfg, npods, _, _), (takes, leftovers) = jax.lax.scan(
        step,
        carry0,
        (req, cnt, maxper, slot, feas, c_star_all, new_ok_all, per_all),
        unroll=8,
    )
    return PackResult(
        take=takes, leftover=leftovers, node_cfg=cfg, node_pods=npods,
        node_used=used,
    )


# ---------------------------------------------------------------------------
# Host wrapper: padding / bucketing so jit compiles once per bucket
# ---------------------------------------------------------------------------


def _bucket(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


def _bucket_classes(n: int) -> int:
    """Class-axis bucket: the scan runs one sequential step per padded
    class, so padding waste is pure latency.  Below 64 use power-of-two
    buckets (few variants); above, round up to a multiple of 64 — at most
    ~1.25x more compile variants, but a 317-class solve runs 320 steps
    instead of 512."""
    if n <= 64:
        return _bucket(n)
    return ((n + 63) // 64) * 64


def node_slot_bound(prob: CompiledProblem) -> int:
    """Upper bound on node slots: existing nodes + worst case one node per
    *constrained* pod but bounded-by-capacity for the rest."""
    n_existing = len(prob.used0)
    n_pods = prob.total_pods()
    constrained = int(prob.cnt[prob.maxper < 2**20].sum()) if len(prob.cnt) else 0
    # every unconstrained pod could still need its own node if nothing else
    # fits; cap the bound at total pods to stay finite but tight in practice
    return n_existing + max(constrained, min(n_pods, max(256, constrained)))


def pad_problem(prob: CompiledProblem, k_slots: int = 0) -> Tuple[tuple, int]:
    """Pad a compiled problem to power-of-two bucket shapes.

    Returns the positional argument tuple for `pack_kernel` plus the padded
    slot count Kp (the kernel's static shape).  Bucketing means XLA compiles
    once per (G, C, K) bucket and replays for every solve that fits.
    """
    G, C = prob.feas.shape
    R = prob.req.shape[1] if prob.req.size else len(prob.axes)
    if k_slots <= 0:
        k_slots = node_slot_bound(prob)
    Gp = _bucket_classes(max(G, 1))
    Cp, Kp = _bucket(max(C, 1)), _bucket(max(k_slots, 1))
    Sp = _bucket(max(prob.n_track_slots, 1), floor=2)
    E = len(prob.used0)

    req = np.zeros((Gp, R), np.float32)
    req[:G] = prob.req
    cnt = np.zeros(Gp, np.int32)
    cnt[:G] = prob.cnt
    maxper = np.zeros(Gp, np.int32)
    maxper[:G] = prob.maxper
    slot = np.zeros(Gp, np.int32)
    slot[:G] = prob.slot
    feas = np.zeros((Gp, Cp), bool)
    feas[:G, :C] = prob.feas
    alloc = np.zeros((Cp, R), np.float32)
    alloc[:C] = prob.alloc
    price = np.full(Cp, np.inf, np.float32)
    price[:C] = prob.price
    openable = np.zeros(Cp, bool)
    openable[:C] = prob.openable
    used0 = np.zeros((Kp, R), np.float32)
    used0[:E] = prob.used0
    cfg0 = np.full(Kp, -1, np.int32)
    cfg0[:E] = prob.cfg0
    npods0 = np.zeros(Kp, np.int32)
    npods0[:E] = prob.npods0
    sig0 = np.zeros((Sp, Kp), np.int32)
    sig0[: prob.sig_used0.shape[0], :E] = prob.sig_used0

    args = (
        req, cnt, maxper, slot, feas, alloc, price, openable,
        used0, cfg0, npods0, jnp.int32(E), sig0,
    )
    return args, Kp


@jax.jit
def compact_take(take: jax.Array):
    """Sparse (values, flat indices, nnz) view of a take matrix
    ([G, K...] — trailing slot axes may be flat or tiled).

    FFD leaves take sparse — each class touches a prefix of partially
    filled slots plus its freshly opened window — and on a high-latency
    device link fetching the dense int32 matrix is the solve's largest
    transfer.  Callers fetch the sparse triple and fall back to the dense
    array iff nnz overflowed the static (heuristic) G + 2K buffer."""
    flat = take.reshape(-1)
    k = flat.shape[0] // take.shape[0]
    ncap = take.shape[0] + 2 * k
    (idx,) = jnp.nonzero(flat, size=ncap, fill_value=0)
    return flat[idx], idx, jnp.count_nonzero(flat)


def expand_take(
    vals: np.ndarray, idx: np.ndarray, nnz: int, take_dev: jax.Array
) -> np.ndarray:
    """Rebuild the dense take matrix from its fetched sparse triple,
    falling back to a dense fetch iff nnz overflowed the static buffer.
    Kept separate from the fetch so callers can bundle the sparse triple
    into ONE device_get with their other outputs (each device_get is a
    full round trip on a tunneled link)."""
    shape = take_dev.shape
    if int(nnz) > len(idx):
        return np.asarray(jax.device_get(take_dev))
    out = np.zeros(int(np.prod(shape)), np.int32)
    out[idx] = vals
    return out.reshape(shape)


# device-resident constant caches, keyed by source-array identity with the
# sources pinned in the entry so the id-based key stays sound (the same
# pattern as TensorScheduler's catalog cache)
def cached_device_put(cache: dict, srcs: tuple, extra_key: tuple, build, shardings=None):
    import jax as _jax

    key = tuple(id(s) for s in srcs) + extra_key
    ent = cache.get(key)
    if ent is not None and all(a is b for a, b in zip(ent[0], srcs)):
        return ent[1]
    built = build()
    dev = _jax.device_put(built, shardings) if shardings else _jax.device_put(built)
    if len(cache) > 32:
        cache.clear()
    cache[key] = (srcs, dev)
    return dev


_DEV_CONST_CACHE: dict = {}


def _device_constants(prob, alloc_p, price_p, openable_p):
    return cached_device_put(
        _DEV_CONST_CACHE,
        (prob.alloc, prob.price, prob.openable),
        (alloc_p.shape,),
        lambda: (alloc_p, price_p, openable_p),
    )


def run_pack(
    prob: CompiledProblem, k_slots: int = 0, objective: str = "nodes"
) -> PackResult:
    """Pad a compiled problem to bucket shapes and run the jitted kernel.

    Returns device arrays; the caller (scheduling/solver.py) decodes them
    back into nodes and placements.  If the solve overflows ``k_slots``
    (leftover pods while feasible configs remained), the caller should retry
    with a doubled bucket.

    Upload hygiene for high-latency device links: the feasibility matrix is
    shipped bit-packed (pack_kernel unpacks on device) and the config-axis
    constants are uploaded once per catalog snapshot and reused from the
    device cache.
    """
    args, Kp = pad_problem(prob, k_slots)
    (req, cnt, maxper, slot, feas, alloc, price, openable,
     used0, cfg0, npods0, e0, sig0) = args
    feas = np.packbits(feas, axis=1, bitorder="little")
    alloc, price, openable = _device_constants(prob, alloc, price, openable)
    return pack_kernel(
        req, cnt, maxper, slot, feas, alloc, price, openable,
        used0, cfg0, npods0, e0, sig0,
        k_slots=Kp, objective=objective,
    )
