"""Constraint compilation: pods x pools x instance-types -> dense tensors.

This is the front-end of the TPU scheduling solver.  The reference computes
feasibility pod-by-pod inside the FFD loop (karpenter-core bin-packing,
reference designs/bin-packing.md:18-42, with the instance-type pre-filter at
pkg/cloudprovider/cloudprovider.go:296-307).  We instead *compile* the
problem once per solve:

- **Pod classes** (axis G): pods grouped by (constraint signature, resource
  vector).  Pods in a class are interchangeable, so the packer places whole
  classes at once — the key to sub-200ms solves at 10k pods.
- **Node configs** (axis C): every launchable (pool, instance-type, zone,
  capacity-type) combination with an available offering, plus one row per
  existing node.  Each row carries an allocatable-resource vector (minus the
  pool's daemonset overhead) and a price.
- **Feasibility** `feas[G, C]`: computed EXACTLY with the Requirements
  algebra (api/requirements.py) — pool taints vs tolerations, the merged
  (pool ∧ pod) requirement conjunction vs the type's catalog labels, zone
  and capacity-type admission, offering availability (ICE cache already
  masked upstream by the instance-type provider).

The launchable half of the config axis is identical across solves for a
given (pools, instance-types) snapshot, so it is prebuilt once as a
`Catalog` and reused — the analogue of the reference's seqnum-keyed
instance-type cache (instancetype.go:97-104).

The resulting `CompiledProblem` is pure numpy; `ops/packer.py` moves it to
device and runs the packing scan under jit.

Constraint coverage: the tensor path handles resource requests, node
selectors/affinity (first OR-term; preferences compiled as required),
volume-derived zone requirements, taints/tolerations, zonal offerings,
capacity types, hostname anti-affinity — self-selecting AND mutual
cross-class (shared `_track_key` counter slots), hostname co-location —
self-selecting AND cross-class closures (macro units; node-INEQUIVALENT
members compile via ANDed feasibility rows — the group's feasible set is
the intersection of its members'), hostname topology spread (max
`maxSkew` per node while any empty node exists — exact in the scale-out
regime), zone topology spread — incl. mutual cross-class, split across
allowed zones against the shared per-group accumulator — and zone-keyed
pod affinity (compile-time domain anchoring).  Anything else — one-sided
cross-class couplings, zone-affinity+spread combos, exotic topology
keys, live-member co-location, closures whose members differ in
OR-terms or namespace — is reported via ``unsupported_reason`` and
routed to the pure-Python oracle (scheduling/scheduler.py), whole or as
the hybrid continuation of a split batch.  (Closures whose members
differ only in PREFERENCES compile: each member's preferences merge as
required into its own ANDed feasibility row, and the compile-time
relaxation ladder peels them when the strict intersection is empty —
see _coloc_component_mergeable.)

Routing-spec guard: tests/test_router_spec.py greps this docstring's
oracle-shape list against the router's actual behavior
(class_unsupported_reason / _coloc_component_mergeable / the cure
functions) — edit both together.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.api import (
    InstanceType,
    NodePool,
    Pod,
    Requirement,
    Requirements,
    Resources,
)
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import selector_matches, tolerates_all
from karpenter_tpu.api.requirements import Op
from karpenter_tpu.state.cluster import StateNode

# Resource canonical axes.  Byte-denominated axes are scaled to MiB so every
# quantity fits comfortably in float32 (f32 has a 24-bit mantissa; bytes
# counts overflow its integer range, MiB counts do not).
_MIB = 2.0**20
_SCALE = {L.RESOURCE_MEMORY: _MIB, L.RESOURCE_EPHEMERAL_STORAGE: _MIB}

BIG = 2**30  # "unbounded" per-node pod cap


def _axes_for(pods: Sequence[Pod]) -> Tuple[str, ...]:
    return _axes_for_requests([p.requests for p in pods])


def _axes_for_requests(requests_list: Sequence[Resources]) -> Tuple[str, ...]:
    """Resource axes for a solve, derived from per-GROUP request vectors.

    Pass each group's key requests (the SUMMED vector for merged
    co-location closures) rather than a representative pod's — a non-rep
    member may carry an extended resource the rep doesn't, and an axis
    missing here would silently go uncapacitated."""
    extra = sorted(
        {k for r in requests_list for k in r.keys()}
        - set(L.WELL_KNOWN_RESOURCES)
    )
    return tuple(L.WELL_KNOWN_RESOURCES) + tuple(extra)


def _vec(r: Resources, axes: Sequence[str]) -> np.ndarray:
    return np.array(
        [r.get(a) / _SCALE.get(a, 1.0) for a in axes], dtype=np.float32
    )


# ---------------------------------------------------------------------------
# Catalog: the launchable config axis, reusable across solves
# ---------------------------------------------------------------------------


@dataclass
class ConfigMeta:
    """Host-side description of one node-config row (C axis)."""

    pool: Optional[NodePool]
    instance_type: Optional[InstanceType]
    zone: str
    capacity_type: str
    price: float
    existing: Optional[StateNode] = None  # set for existing-node rows


@dataclass
class _PoolRows:
    """Per-pool config structure for vectorized feasibility assembly."""

    rows: np.ndarray  # [n] int32 — config row indices
    uniq_types: List[InstanceType]
    t_of: np.ndarray  # [n] int32 — row -> uniq_types index
    z_of: np.ndarray  # [n] int32
    ct_of: np.ndarray  # [n] int32
    zones: List[str]
    capacity_types: List[str]


@dataclass
class Catalog:
    """Prebuilt launchable config rows + tensors for one inventory snapshot."""

    axes: Tuple[str, ...]
    pools: List[NodePool]  # live, weight-sorted
    configs: List[ConfigMeta]
    alloc: np.ndarray  # [Cn, R] float32 (minus pool daemonset overhead)
    price: np.ndarray  # [Cn] float32
    pool_rank_of: np.ndarray  # [Cn] int32 — weight-order rank of each row
    pool_rows: Dict[str, _PoolRows]
    pool_overhead: Dict[str, Resources]
    zones: List[str]
    # (constraint-signature, pool) -> (type_ok, zone_ok, ct_ok) bool vectors
    # (None when the pod can't merge with the pool at all).  The exact
    # Requirements-algebra checks are the host-side compile's dominant cost
    # at many-class batches; they depend only on the signature and this
    # catalog snapshot, so they memoize for the catalog's lifetime.
    feas_memo: Dict = field(default_factory=dict)


_MEMO_MISS = object()


def _pool_feas(
    catalog: "Catalog",
    rep: Pod,
    sig: Tuple,
    pname: str,
    pools_by_name: Dict[str, NodePool],
    term: int = 0,
    keep_prefs: Optional[int] = None,
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Memoized per-(signature, pool) compatibility vectors over the pool's
    unique types / zones / capacity types.  Zone PINS are intentionally not
    part of the key: config rows exist only for a type's actual offerings,
    so pinning composes exactly as a per-row zone filter on top of these.
    ``(term, keep_prefs)`` select a relaxation step of the ladder
    (compile-time peel, see compile_problem) — the default strict shape
    keeps the compact two-part key."""
    memo = catalog.feas_memo
    key = (
        (sig, pname)
        if term == 0 and keep_prefs is None
        else (sig, pname, term, keep_prefs)
    )
    ent = memo.get(key, _MEMO_MISS)
    if ent is _MEMO_MISS:
        pr = catalog.pool_rows[pname]
        merged = _merge_pool(
            rep,
            rep.scheduling_requirements(
                preferred=True, term=term, keep_prefs=keep_prefs
            ),
            pools_by_name[pname],
        )
        if merged is None:
            ent = None
        else:
            type_ok = np.fromiter(
                (
                    it.requirements.compatible(merged, allow_undefined=True)
                    for it in pr.uniq_types
                ),
                bool,
                len(pr.uniq_types),
            )
            zr = merged.get(L.LABEL_ZONE)
            zone_ok = np.fromiter(
                (zr is None or zr.has(z) for z in pr.zones), bool, len(pr.zones)
            )
            cr = merged.get(L.LABEL_CAPACITY_TYPE)
            ct_ok = np.fromiter(
                (cr is None or cr.has(ct) for ct in pr.capacity_types),
                bool,
                len(pr.capacity_types),
            )
            ent = (type_ok, zone_ok, ct_ok)
        if len(memo) > 50_000:
            memo.clear()  # unbounded-workload backstop
        memo[key] = ent
    return ent


def build_catalog(
    pools: Sequence[NodePool],
    instance_types: Dict[str, List[InstanceType]],
    daemonsets: Sequence[Pod] = (),
    axes: Tuple[str, ...] = tuple(L.WELL_KNOWN_RESOURCES),
) -> Catalog:
    pools = sorted((p for p in pools if not p.deleted), key=lambda p: -p.weight)
    configs: List[ConfigMeta] = []
    pool_overhead: Dict[str, Resources] = {}
    pool_rank: List[int] = []
    for rank, pool in enumerate(pools):
        treqs = pool.template_requirements()
        pool_overhead[pool.name] = _daemon_overhead(pool, treqs, daemonsets)
        for it in instance_types.get(pool.name, []):
            for off in it.offerings.available():
                configs.append(
                    ConfigMeta(
                        pool=pool,
                        instance_type=it,
                        zone=off.zone,
                        capacity_type=off.capacity_type,
                        price=off.price,
                    )
                )
                pool_rank.append(rank)

    alloc_rows = []
    for cfg in configs:
        alloc = (
            cfg.instance_type.allocatable() - pool_overhead[cfg.pool.name]
        ).clamp_nonnegative()
        alloc_rows.append(_vec(alloc, axes))
    alloc_mat = (
        np.stack(alloc_rows) if alloc_rows else np.zeros((0, len(axes)), np.float32)
    )

    pool_rows: Dict[str, _PoolRows] = {}
    rows_by_pool: Dict[str, List[int]] = {}
    for c, cfg in enumerate(configs):
        rows_by_pool.setdefault(cfg.pool.name, []).append(c)
    for pname, rows in rows_by_pool.items():
        uniq_types: List[InstanceType] = []
        tindex: Dict[str, int] = {}
        zones_u: List[str] = []
        zindex: Dict[str, int] = {}
        cts_u: List[str] = []
        ctindex: Dict[str, int] = {}
        t_of = np.empty(len(rows), np.int32)
        z_of = np.empty(len(rows), np.int32)
        ct_of = np.empty(len(rows), np.int32)
        for i, c in enumerate(rows):
            cfg = configs[c]
            if cfg.instance_type.name not in tindex:
                tindex[cfg.instance_type.name] = len(uniq_types)
                uniq_types.append(cfg.instance_type)
            if cfg.zone not in zindex:
                zindex[cfg.zone] = len(zones_u)
                zones_u.append(cfg.zone)
            if cfg.capacity_type not in ctindex:
                ctindex[cfg.capacity_type] = len(cts_u)
                cts_u.append(cfg.capacity_type)
            t_of[i] = tindex[cfg.instance_type.name]
            z_of[i] = zindex[cfg.zone]
            ct_of[i] = ctindex[cfg.capacity_type]
        pool_rows[pname] = _PoolRows(
            rows=np.array(rows, np.int32),
            uniq_types=uniq_types,
            t_of=t_of,
            z_of=z_of,
            ct_of=ct_of,
            zones=zones_u,
            capacity_types=cts_u,
        )

    return Catalog(
        axes=axes,
        pools=list(pools),
        configs=configs,
        alloc=alloc_mat,
        price=np.array([c.price for c in configs], dtype=np.float32),
        pool_rank_of=np.array(pool_rank, dtype=np.int32),
        pool_rows=pool_rows,
        pool_overhead=pool_overhead,
        zones=sorted({c.zone for c in configs}),
    )


# ---------------------------------------------------------------------------
# Compiled problem
# ---------------------------------------------------------------------------


@dataclass
class ClassMeta:
    """Host-side description of one pod class (G axis)."""

    pods: List[Pod]
    requests: Resources
    signature: Tuple
    zone_pin: str = ""  # non-empty when zone-split / affinity-anchored
    max_per_node: int = BIG
    track_slot: int = 0  # sig-count slot for anti-affinity/hostname-spread
    infeasible: bool = False  # compile-time-proven unschedulable
    unsched_reason: str = ""  # decode reason when infeasible
    # hostname co-location macro: when > 0 the class is ONE placement unit
    # covering all `pods` (requests is their SUM); a take of 1 assigns the
    # whole group to that node, a leftover of 1 leaves the whole group
    # unschedulable (real-scheduler bind semantics: once the first member
    # binds, required hostname affinity forces every member to that node)
    group_size: int = 0
    # custom-topology-key split: a representative CLONE whose node
    # selector pins the class to its domain — feasibility rows compile
    # from this pod instead of pods[0] (the members keep their real spec)
    rep_override: Optional[Pod] = None
    # ...and the domain's pools: only pools DEFINING the key are valid
    # domains (the oracle's rule), which the requirement merge alone
    # cannot express because undefined keys pass at the pool level
    pool_allow: Optional[frozenset] = None


@dataclass
class CompiledProblem:
    axes: Tuple[str, ...]
    classes: List[ClassMeta]
    configs: List[ConfigMeta]
    # class tensors [G]
    req: np.ndarray  # [G, R] float32
    cnt: np.ndarray  # [G] int32
    maxper: np.ndarray  # [G] int32
    slot: np.ndarray  # [G] int32  (anti-affinity tracking slot)
    # config tensors [C]
    alloc: np.ndarray  # [C, R] float32 (minus pool daemonset overhead)
    price: np.ndarray  # [C] float32
    openable: np.ndarray  # [C] bool (False for existing-node rows)
    feas: np.ndarray  # [G, C] bool
    # per-pool daemonset overhead (already subtracted from alloc rows;
    # decode adds it back onto each new node's `used`)
    pool_daemon_overhead: Dict[str, Resources]
    # existing-node prefill
    used0: np.ndarray  # [E, R] float32
    cfg0: np.ndarray  # [E] int32 (config row index)
    npods0: np.ndarray  # [E] int32 — pods already bound per existing node
    sig_used0: np.ndarray  # [S, E] int32 — tracked-signature counts per node
    n_track_slots: int = 1
    unsupported_reason: str = ""
    # pods whose class was relaxed at COMPILE time (preference peel /
    # OR-term walk over globally-empty strict rows) — observability for
    # the solver's last_compile_relaxed and the bench's relax line
    compile_relaxed: int = 0

    @property
    def supported(self) -> bool:
        return not self.unsupported_reason

    def total_pods(self) -> int:
        return int(self.cnt.sum())


# ---------------------------------------------------------------------------
# Support detection + batch partitioning
# ---------------------------------------------------------------------------


def class_unsupported_reason(rep: Pod) -> str:
    """Constraint shapes of a single class the tensor kernel cannot express.

    Supported coupled shapes (compiled to masks/pins/splits):
    - zone-keyed REQUIRED pod affinity -> compile-time domain anchoring
      (the whole affinity component pins to one zone)
    - self-selecting zone-keyed anti-affinity -> per-zone singleton split
    - self-selecting hostname anti-affinity -> max-1-per-node cap
    - self-selecting hostname AFFINITY (same-node co-location) -> one
      macro placement unit carrying the whole group's summed requests
    - hostname/zone topology spread -> per-node caps / zone shares

    Cross-class shapes are cured at partition level when they are MUTUAL:
    node-equivalent co-location closures merge into one macro unit
    (_coloc_component_mergeable), identical-fingerprint hostname
    anti-affinity shares a counter slot (_track_key), and identical
    mutual zone spreads split against the shared group accumulator.
    Everything else (one-sided couplings; exotic topology keys) goes to
    the oracle half of a hybrid solve (scheduling/solver.py).
    """
    has_zone_aff = False
    has_zone_anti = False
    has_host_aff = False
    for t in rep.pod_affinity:
        if not t.anti:
            if t.topology_key == L.LABEL_HOSTNAME:
                if not t.selects(rep):
                    return "hostname affinity selector not matching own pods"
                has_host_aff = True
                continue
            if t.topology_key != L.LABEL_ZONE:
                return f"pod affinity on topology key {t.topology_key}"
            has_zone_aff = True
        elif t.topology_key == L.LABEL_HOSTNAME:
            if not t.selects(rep):
                return "hostname anti-affinity selector reaching other pods"
        elif t.topology_key == L.LABEL_ZONE:
            if not t.selects(rep):
                return "zone anti-affinity selector reaching other pods"
            has_zone_anti = True
        else:
            return f"anti-affinity on topology key {t.topology_key}"
    zone_spread = any(
        c.topology_key == L.LABEL_ZONE
        and c.selects(rep)
                for c in rep.topology_spread
    )
    if has_zone_aff and (zone_spread or has_zone_anti):
        return "zone affinity combined with another zone constraint"
    if has_zone_anti and zone_spread:
        return "zone anti-affinity combined with zone spread"
    if has_host_aff and (
        has_zone_aff
        or has_zone_anti
        or zone_spread
        or rep.topology_spread
        or any(t.anti for t in rep.pod_affinity)
    ):
        # the macro unit is a single opaque placement; combining it with
        # per-pod zone/spread/anti accounting needs the oracle
        return "hostname co-location combined with another constraint"
    for c in rep.topology_spread:
        if c.topology_key not in (L.LABEL_HOSTNAME, L.LABEL_ZONE):
            # provisional: partition_groups cures the single-constraint
            # self-selecting shape when the caller's pools give the key a
            # well-defined domain partition (_custom_spread_curable)
            return f"topology spread on key {c.topology_key}"
    return ""


def _custom_spread_curable(rep: Pod, pools: Sequence[NodePool]) -> str:
    """Domain partition for a CUSTOM-topology-key spread, or "" when the
    shape must keep the oracle.

    Compilable when the rep's only pod-level constraint is ONE
    self-selecting spread on the key, and every pool defining the key is
    SINGLE-VALUED for it (domains partition the pools, so each split
    class's pinned feasibility row maps to whole pools and two domains
    can never share a config row).  Returns the key when curable."""
    if not pools:
        return ""
    if rep.pod_affinity or len(rep.topology_spread) != 1:
        return ""
    c = rep.topology_spread[0]
    key = c.topology_key
    if key in (L.LABEL_HOSTNAME, L.LABEL_ZONE) or not c.selects(rep):
        return ""
    domains = set()
    for pool in pools:
        vr = pool.template_requirements().get(key)
        if vr is None:
            continue
        if vr.complement or len(vr.values) != 1:
            return ""  # multi-valued / negated template: oracle
        domains.update(vr.values)
    return key if domains else ""


def _pin_clone(rep: Pod, key: str, value: str) -> Pod:
    """Representative clone pinned to one domain via node selector; the
    reassignment invalidates the copied signature memo (Pod.__setattr__),
    so the clone groups and memoizes as its own shape."""
    import copy

    ov = copy.copy(rep)
    ov.node_selector = {**rep.node_selector, key: value}
    return ov


def _class_groups(pods: Sequence[Pod]) -> List[Tuple[Tuple, List[Pod]]]:
    groups: Dict[object, List[Pod]] = {}
    for p in pods:
        groups.setdefault(p.class_key(), []).append(p)
    return [(ck.key, members) for ck, members in groups.items()]


def _couples(a: Pod, b: Pod) -> bool:
    """Any selector of `a` (affinity term or spread constraint) selects `b`."""
    return any(t.selects(b) for t in a.pod_affinity) or any(
        c.selects(b) for c in a.topology_spread
    )


# cross-class hostname-co-location reasons that a node-equivalent closure
# merge cures (every other reason is structural and keeps the class oracle)
_HOST_CURABLE = frozenset(
    [
        "hostname affinity selector not matching own pods",
        "hostname co-location across multiple resource classes",
        "hostname co-location coupling distinct pod classes",
    ]
)


def _coloc_component_mergeable(
    comp: Sequence[int],
    sig_rep: Sequence[Pod],
    reasons: Sequence[str],
    live_labels: Sequence[dict],
    live_match=None,
) -> bool:
    """Whether a hostname-affinity coupled component compiles as ONE macro
    placement unit: every sig carries only hostname-affinity terms, every
    selector anchors inside the component, and no selector reaches pods
    already bound on live nodes (those groups must JOIN their node, which
    a macro can't express).

    Node-INEQUIVALENT closures (members differing in node selector,
    required node affinity, tolerations, volume requirements, or
    PREFERENCES) merge too: the whole group must land on ONE node, so
    the group's feasible config set is exactly the INTERSECTION of its
    members' sets — compile_problem ANDs the per-signature feasibility
    rows, with each member's preferences merged as required into its own
    row (and peeled per member by the compile-time relaxation ladder
    when the strict intersection is empty).  OR-terms and namespace must
    stay equal across members: the term walk is a single index into
    every member's term list, and selectors are namespace-scoped.  A
    closure that still proves unschedulable relaxes as a UNIT — the
    solver's relax pass pulls the whole closure to the oracle, whose
    gang machinery peels per member (solver.solve)."""
    cohesion_part = None
    for s in comp:
        if reasons[s] and reasons[s] not in _HOST_CURABLE:
            return False
        rep = sig_rep[s]
        if rep.topology_spread or not rep.pod_affinity:
            return False
        if any(
            t.anti or t.topology_key != L.LABEL_HOSTNAME
            for t in rep.pod_affinity
        ):
            return False
        sig = rep.constraint_signature()
        part = (sig[9], rep.namespace)
        if cohesion_part is None:
            cohesion_part = part
        elif part != cohesion_part:
            return False
    if live_match is None:
        live_match = lambda t: any(  # noqa: E731
            selector_matches(lbl, t.label_selector, t.match_expressions)
            for lbl in live_labels
        )
    for s in comp:
        for t in sig_rep[s].pod_affinity:
            if not any(t.selects(sig_rep[j]) for j in comp):
                return False
            if live_labels and live_match(t):
                return False
    return True


def partition_pods(
    pods: Sequence[Pod],
    pools: Sequence[NodePool] = (),
) -> Tuple[List[Pod], List[Pod], str]:
    """Split a batch into (tensor-solvable, oracle-only, reason); see
    `partition_groups` (which the solver uses directly so the class
    grouping is computed once per solve, not once here and again in
    `compile_problem`)."""
    sup_groups, unsupported, why = partition_groups(pods, pools=pools)
    supported = [p for _, members in sup_groups for p in members]
    return supported, unsupported, why


def partition_groups(
    pods: Sequence[Pod],
    existing: Sequence["StateNode"] = (),
    pools: Sequence[NodePool] = (),
) -> Tuple[List[Tuple[Tuple, List[Pod]]], List[Pod], str]:
    """Split a batch into (tensor-solvable class groups, oracle-only pods,
    reason).

    A class is oracle-only when its own constraint shape is unsupported,
    when an anti-affinity term couples it to a DIFFERENT class, or —
    transitively — when any selector couples it (either direction) to an
    oracle-only class.  The transitive closure guarantees the two halves
    share no constraint groups, so solving them sequentially (tensor first,
    oracle continuing on the tensor result) is sound: the only interaction
    left is capacity, which the oracle sees exactly.
    """
    group_list = _class_groups(pods)
    # every relation below (selector coupling, anti-affinity reach, the
    # unsupported-shape check) depends only on the constraint SIGNATURE
    # (labels + selectors + namespace), never on the request vector — so
    # the pairwise passes run over unique signatures, not classes.  Groups
    # sharing a signature are not "distinct classes" to the kernel: it
    # tracks them through one shared per-signature counter slot, so only
    # cross-SIG coupling needs the oracle.  Exception: zone anti-affinity's
    # per-zone singleton split is per (sig, requests) group, so a sig
    # spanning several request groups cannot share its <=1-per-zone cap.
    sig_index: Dict[Tuple, int] = {}
    sig_rep: List[Pod] = []
    sig_count: List[int] = []
    sig_of: List[int] = []
    for (sig, _), members in group_list:
        s = sig_index.get(sig)
        if s is None:
            s = sig_index[sig] = len(sig_rep)
            sig_rep.append(members[0])
            sig_count.append(0)
        sig_count[s] += 1
        sig_of.append(s)
    m = len(sig_rep)
    reasons = [class_unsupported_reason(r) for r in sig_rep]
    # cure custom-topology-key spreads the caller's pools can partition
    # (single-valued templates; see _custom_spread_curable).  Deleted
    # pools are filtered FIRST so this decision matches compile_problem,
    # whose catalog drops them (build_catalog).
    alive_pools = [p for p in pools if not p.deleted]
    if alive_pools:
        for i, r in enumerate(sig_rep):
            if reasons[i].startswith("topology spread on key") and \
                    _custom_spread_curable(r, alive_pools):
                reasons[i] = ""
    # built ONCE for the live-member checks below, with an inverted label
    # index so each selector scan is a set intersection over candidate
    # bound pods instead of an O(live pods) python loop — at 10k-pod /
    # hundreds-of-live-nodes batches the naive scan was a top-3 host cost
    live_labels = [dict(bp.labels) for sn in existing for bp in sn.pods]
    live_pair_index: Dict[Tuple[str, str], set] = {}
    for li, lbl in enumerate(live_labels):
        for kv in lbl.items():
            live_pair_index.setdefault(kv, set()).add(li)
    _live_match_memo: Dict[int, bool] = {}

    def live_matches(sel) -> bool:
        """Whether any live bound pod's labels satisfy `sel`."""
        got = _live_match_memo.get(id(sel))
        if got is not None:
            return got
        cand = None
        for kv in sel.label_selector:
            hit = live_pair_index.get(kv)
            if not hit:
                cand = ()
                break
            cand = set(hit) if cand is None else (cand & hit)
            if not cand:
                break
        if cand is None:  # no equality pairs to narrow on: scan everything
            cand = range(len(live_labels))
        got = any(
            selector_matches(
                live_labels[li], sel.label_selector, sel.match_expressions
            )
            for li in cand
        )
        _live_match_memo[id(sel)] = got
        return got
    # symmetric anti-affinity from LIVE carriers: a bound pod's anti term
    # repels incoming matching pods from its node — only the oracle's
    # per-node ban sets express that, so any selected class goes oracle
    live_anti = [
        t
        for sn in existing
        for bp in sn.pods
        for t in bp.pod_affinity
        if t.anti
    ]
    if live_anti:
        for i, r in enumerate(sig_rep):
            if any(t.selects(r) for t in live_anti):
                reasons[i] = reasons[i] or (
                    "repelled by a live pod's anti-affinity"
                )
    sel_idx = [
        i for i, r in enumerate(sig_rep) if r.pod_affinity or r.topology_spread
    ]

    # inverted label index: selector matching over unique signatures runs
    # as set intersections (a selector is a label conjunction) instead of
    # an O(sigs^2) python scan — the closure passes below all use it
    pair_index: Dict[Tuple[str, str], set] = {}
    for j, rep in enumerate(sig_rep):
        for kv in rep.labels.items():
            pair_index.setdefault(kv, set()).add(j)
    _no_sigs: set = set()
    _match_memo: Dict[int, frozenset] = {}

    def matches(sel) -> frozenset:
        """Sig indices whose pods `sel` selects (empty selector = all)."""
        got = _match_memo.get(id(sel))
        if got is not None:
            return got
        out = None
        for kv in sel.label_selector:
            hit = pair_index.get(kv)
            if not hit:
                out = _no_sigs
                break
            out = set(hit) if out is None else (out & hit)
            if not out:
                break
        # In-expressions narrow too (union of their value pairs); other
        # operators can't narrow and rely on the verify pass below
        if out is not _no_sigs:
            for expr in getattr(sel, "match_expressions", ()):
                if expr[1] != "In":
                    continue
                hit = set()
                for v in expr[2]:
                    hit |= pair_index.get((expr[0], v), _no_sigs)
                out = hit if out is None else (out & hit)
                if not out:
                    break
        if out is None:
            out = set(range(m))
        # full-selector verify: expressions and namespaces are exact here
        out = {j for j in out if sel.selects(sig_rep[j])}
        _match_memo[id(sel)] = got = frozenset(out)
        return got

    # union-find over hostname-affinity coupling: a connected component is
    # one CO-LOCATION CLOSURE; node-equivalent closures compile as a single
    # macro placement unit instead of falling to the oracle
    coloc_parent = list(range(m))

    def _find(x: int) -> int:
        while coloc_parent[x] != x:
            coloc_parent[x] = coloc_parent[coloc_parent[x]]
            x = coloc_parent[x]
        return x

    def _union(a: int, b: int) -> None:
        ra, rb = _find(a), _find(b)
        if ra != rb:
            coloc_parent[rb] = ra

    for i in sel_idx:
        rep = sig_rep[i]
        if sig_count[i] > 1 and any(
            t.anti and t.topology_key == L.LABEL_ZONE for t in rep.pod_affinity
        ):
            reasons[i] = reasons[i] or (
                "zone anti-affinity across multiple resource classes"
            )
        host_aff_terms = [
            t
            for t in rep.pod_affinity
            if not t.anti and t.topology_key == L.LABEL_HOSTNAME
        ]
        if host_aff_terms:
            # one (sig, requests) class per macro unless the closure merge
            # below proves the whole coupled component node-equivalent; a
            # selector reaching live members (the group must JOIN their
            # node, which the macro can't express) always needs the oracle
            if sig_count[i] > 1:
                reasons[i] = reasons[i] or (
                    "hostname co-location across multiple resource classes"
                )
            for t in host_aff_terms:
                for j in matches(t):
                    _union(i, j)
                    if j != i:
                        why = "hostname co-location coupling distinct pod classes"
                        reasons[i] = reasons[i] or why
                        reasons[j] = reasons[j] or why
            if live_labels and any(
                live_matches(t) for t in host_aff_terms
            ):
                reasons[i] = reasons[i] or (
                    "hostname co-location with members on live nodes"
                )
        for t in rep.pod_affinity:
            if not t.anti:
                continue
            # mutual cross-class HOSTNAME anti-affinity (variant labels
            # under one selector) compiles exactly: classes with identical
            # hostname fingerprints share one per-node counter slot
            # (compile_problem keys track_slots by _track_key), enforcing
            # <=1 of the union per node.  Anything asymmetric — the other
            # class missing the term, or carrying extra hostname
            # constraints — still needs the oracle.
            host_mutual = (
                t.topology_key == L.LABEL_HOSTNAME and t.selects(rep)
            )
            for j in matches(t):
                if j == i:
                    continue
                if (
                    host_mutual
                    and t in sig_rep[j].pod_affinity
                    and _track_key(sig_rep[j]) == _track_key(rep)
                ):
                    continue
                why = "anti-affinity coupling distinct pod classes"
                reasons[i] = reasons[i] or why
                reasons[j] = reasons[j] or why
        for c in rep.topology_spread:
            # zone-keyed DoNotSchedule spread across classes is exact on
            # the tensor path when the coupling is MUTUAL: every selected
            # class carries the identical constraint and self-selects, so
            # each splits itself against the shared per-group accumulator
            # (compile_problem's spread_assigned) and the summed shares
            # stay within maxSkew.  Anything one-sided (a class counted by
            # the group but not constrained by it, or vice versa) still
            # needs the oracle's runtime counts.
            zone_mutual = (
                c.topology_key == L.LABEL_ZONE
                                and c.selects(rep)
            )
            for j in matches(c):
                if j == i:
                    continue
                if (
                    zone_mutual
                    and c in sig_rep[j].topology_spread
                    # both classes must split over the SAME candidate
                    # zones, or the shared accumulator can't reconcile
                    # their shares
                    and sig_rep[j].scheduling_requirements(preferred=True).get(L.LABEL_ZONE)
                    == rep.scheduling_requirements(preferred=True).get(L.LABEL_ZONE)
                ):
                    continue
                # the spread group counts another class's pods; the
                # kernel's per-signature counters can't see them
                why = "topology spread coupling distinct pod classes"
                reasons[i] = reasons[i] or why
                reasons[j] = reasons[j] or why
        for t in rep.pod_affinity:
            if t.anti or t.topology_key != L.LABEL_ZONE:
                continue
            for j in matches(t):
                if j == i:
                    continue
                b = sig_rep[j]
                # anchoring pins the whole component to one zone, which is
                # only sound when the selected class has no zone-keyed
                # constraint of its own to honor (its own zone AFFINITY
                # merges into the same component and is fine)
                if any(
                    c.topology_key == L.LABEL_ZONE
                                        and c.selects(b)
                    for c in b.topology_spread
                ) or any(
                    tt.topology_key == L.LABEL_ZONE
                    and tt.anti
                    or tt.topology_key == L.LABEL_HOSTNAME
                    and not tt.anti
                    for tt in b.pod_affinity
                ):
                    why = "zone affinity coupling a zone-constrained class"
                    reasons[i] = reasons[i] or why
                    reasons[j] = reasons[j] or why

    # cure node-equivalent co-location closures: every sig in the component
    # differs only in pod labels / hostname-affinity selectors, so the whole
    # closure is ONE placement unit (summed requests) the kernel expresses
    # exactly — the cross-class reasons above were provisional
    comp_members: Dict[int, List[int]] = {}
    for j in range(m):
        comp_members.setdefault(_find(j), []).append(j)
    merge_root: Dict[int, int] = {}
    for root, comp in comp_members.items():
        if len(comp) == 1 and sig_count[comp[0]] == 1:
            continue  # the single-class macro path already handles it
        if not any(
            not t.anti and t.topology_key == L.LABEL_HOSTNAME
            for s in comp
            for t in sig_rep[s].pod_affinity
        ):
            continue
        if _coloc_component_mergeable(
            comp, sig_rep, reasons, live_labels, live_match=live_matches
        ):
            for s in comp:
                if reasons[s] in _HOST_CURABLE:
                    reasons[s] = ""
                merge_root[s] = root

    # transitive closure over selector coupling (either direction); a cured
    # component re-poisons WHOLE (its sigs stay mutually connected), so a
    # merge never splits across the tensor/oracle boundary
    edges: Dict[int, set] = {}
    for i in sel_idx:
        reach: set = set()
        for t in sig_rep[i].pod_affinity:
            reach |= matches(t)
        for c in sig_rep[i].topology_spread:
            reach |= matches(c)
        reach.discard(i)
        for j in reach:
            edges.setdefault(i, set()).add(j)
            edges.setdefault(j, set()).add(i)
    frontier = [i for i in range(m) if reasons[i]]
    while frontier:
        i = frontier.pop()
        for j in edges.get(i, ()):
            if not reasons[j]:
                reasons[j] = "coupled to an oracle-only pod class"
                frontier.append(j)
    sup_groups: List[Tuple[Tuple, List[Pod]]] = []
    unsupported: List[Pod] = []
    why = ""
    merged_members: Dict[int, List[Pod]] = {}
    for i, group in enumerate(group_list):
        s = sig_of[i]
        reason = reasons[s]
        if reason:
            unsupported.extend(group[1])
            why = why or reason
        elif s in merge_root:
            merged_members.setdefault(merge_root[s], []).extend(group[1])
        else:
            sup_groups.append(group)
    for members in merged_members.values():
        rep = members[0]
        total = Resources()
        for p in members:
            total = total + p.requests
        sup_groups.append(((rep.constraint_signature(), total), members))
    return sup_groups, unsupported, why


def _unsupported_reason(
    pods: Sequence[Pod],
    existing: Sequence["StateNode"] = (),
    pools: Sequence[NodePool] = (),
) -> str:
    """Whole-batch gate used by `compile_problem`: non-empty when ANY pod
    needs the oracle (callers that cannot hybrid-split fall back whole).
    `existing` matters: co-location groups with members already on live
    nodes must JOIN those nodes, which only the oracle expresses."""
    _, unsupported, why = partition_groups(pods, existing=existing, pools=pools)
    return why if unsupported else ""


# ---------------------------------------------------------------------------
# Compilation
# ---------------------------------------------------------------------------


def _max_per_node(pod: Pod) -> int:
    """Per-node cap induced by hostname-keyed constraints.

    Self-selecting hostname anti-affinity = 1 pod per node (the 500-node
    scale config, reference test/suites/scale/provisioning_test.go:92-135).
    Hostname spread with maxSkew m allows at most m per node while any
    empty candidate node exists — exact during scale-out.
    """
    cap = BIG
    for t in pod.pod_affinity:
        if t.anti and t.topology_key == L.LABEL_HOSTNAME and t.selects(pod):
            cap = 1
    for c in pod.topology_spread:
        if (
            c.topology_key == L.LABEL_HOSTNAME
            and c.selects(pod)
                    ):
            cap = min(cap, c.max_skew)
    return cap


def _track_key(pod: Pod) -> Tuple:
    """Fingerprint of a class's hostname-keyed tracked constraints.

    Classes with EQUAL fingerprints share one per-node counter slot; the
    counter counts every placed pod of those classes, which matches the
    selector semantics because partition_groups only admits cross-class
    sharing when the classes mutually carry identical selectors.  A class
    with several terms gets one OR-counter — exact for anti-affinity
    (any match bans), conservative for hostname spread."""
    sels = {
        ("a", t.label_selector, t.match_expressions, t.namespaces)
        for t in pod.pod_affinity
        if t.anti and t.topology_key == L.LABEL_HOSTNAME
    } | {
        ("s", c.label_selector, c.match_expressions)
        for c in pod.topology_spread
        if c.topology_key == L.LABEL_HOSTNAME and c.selects(pod)
    }
    return tuple(sorted(sels))


def _track_matches(key: Tuple, pod: Pod) -> bool:
    """Whether a bound pod counts against a tracking slot: any selector in
    the slot's fingerprint matches its labels (kube counts label matches,
    whether or not the bound pod carries the constraint itself)."""
    for entry in key:
        sel, exprs = entry[1], entry[2]
        if entry[0] == "a" and entry[3] and pod.namespace not in entry[3]:
            continue
        if selector_matches(pod.labels, sel, exprs):
            return True
    return False


def _zone_spread_zones(pod: Pod) -> bool:
    return any(
        c.topology_key == L.LABEL_ZONE
        and c.selects(pod)
                for c in pod.topology_spread
    )


def _daemon_overhead(
    pool: NodePool, reqs: Requirements, daemonsets: Sequence[Pod]
) -> Resources:
    out = Resources()
    for d in daemonsets:
        if not tolerates_all(d.tolerations, pool.taints):
            continue
        if not reqs.compatible(d.scheduling_requirements()):
            continue
        out = out + d.requests
    return out


def live_filter(existing) -> list:
    """The schedulable subset of `existing`: nodes neither marked for
    deletion nor cordoned.  The ONE definition — `compile_problem`'s
    existing-node rows and the resident delta planner (ops/resident.py)
    must agree on it exactly, or the resident path keeps columns a
    from-scratch compile would drop."""
    return [
        sn
        for sn in existing
        if not sn.marked_for_deletion()
        and not (sn.node is not None and sn.node.cordoned)
    ]


def compile_problem(
    pods: Sequence[Pod],
    pools: Sequence[NodePool],
    instance_types: Dict[str, List[InstanceType]],
    existing: Sequence[StateNode] = (),
    daemonsets: Sequence[Pod] = (),
    catalog: Optional[Catalog] = None,
    presplit: bool = False,
    groups: Optional[List[Tuple[Tuple, List[Pod]]]] = None,
) -> CompiledProblem:
    """Compile one scheduling problem to tensors.

    Pass a prebuilt ``catalog`` (from `build_catalog`) to skip re-deriving
    the launchable config rows — valid as long as the (pools,
    instance-types, daemonsets) snapshot is unchanged and the pods
    introduce no new extended-resource axes.  ``presplit=True`` promises
    the caller already ran `partition_pods` and kept only the supported
    half, skipping the (pure-overhead) re-check on the hot path.
    ``groups`` passes the caller's `partition_groups` output so the class
    grouping isn't recomputed (every member of a group shares the
    representative's requests and constraint signature by construction).
    """
    if groups is None:
        pods = list(pods)
        # merge-aware grouping: node-equivalent co-location closures arrive
        # as ONE macro group here exactly as they do on the solver's
        # presplit path
        sup_groups, unsupported, why = partition_groups(
            pods, existing=existing, pools=pools
        )
        if unsupported:
            groups = _class_groups(pods)
            reason = "" if presplit else why
        else:
            groups = sup_groups
            reason = ""
    else:
        reason = "" if presplit else _unsupported_reason(pods, existing, pools)
    axes = _axes_for_requests([key[1] for key, _ in groups])
    if catalog is None or catalog.axes != axes:
        catalog = build_catalog(pools, instance_types, daemonsets, axes)
    pools = catalog.pools
    R = len(axes)

    # ----------------------------------------------- existing-node rows
    live = live_filter(existing)
    first_existing = len(catalog.configs)
    configs = list(catalog.configs) + [
        ConfigMeta(
            pool=None,
            instance_type=None,
            zone=sn.zone,
            capacity_type=sn.capacity_type,
            price=0.0,
            existing=sn,
        )
        for sn in live
    ]
    C = len(configs)
    if live:
        alloc = np.concatenate(
            [catalog.alloc, np.stack([_vec(sn.allocatable, axes) for sn in live])]
        )
        price = np.concatenate([catalog.price, np.zeros(len(live), np.float32)])
    else:
        alloc = catalog.alloc
        price = catalog.price
    openable = np.zeros(C, bool)
    openable[:first_existing] = True

    # ------------------------------------------------------------- classes
    all_zones = sorted(set(catalog.zones) | {sn.zone for sn in live if sn.zone})
    group_list = groups

    # zone-keyed pod affinity: compile-time domain anchoring — each coupled
    # component of classes pins to ONE zone (the oracle anchors the domain
    # with the first matching placement; here the anchor is chosen up front
    # from existing placements, zone requirements, and per-zone feasibility)
    anchor_of = _anchor_zone_affinity(group_list, all_zones, catalog, pools, live)

    classes: List[ClassMeta] = []
    pools_by_name = {p.name: p for p in pools}
    track_slots: Dict[Tuple, int] = {}
    # per-SPREAD-GROUP shares already handed out in this compile: a
    # service whose pods span several request classes splits each class
    # against the group's accumulated counts, not a fresh zero — per-class
    # splits are individually balanced but their sum can skew past
    # maxSkew (e.g. three classes each putting their remainder in zone-a)
    spread_assigned: Dict[Tuple, Dict[str, int]] = {}
    for gi, ((sig, requests), members) in enumerate(group_list):
        rep = members[0]
        maxper = _max_per_node(rep)
        slot = 0
        if maxper < BIG:
            # slot key = the hostname-constraint FINGERPRINT, not the pod
            # signature: mutually-coupled classes carrying the identical
            # anti-affinity selector (variant labels under one selector)
            # share one per-node counter, which is exactly the <=1-of-the-
            # union semantics (partition_groups admits them only when the
            # fingerprints match)
            slot = track_slots.setdefault(
                _track_key(rep), len(track_slots) + 1
            )
        if any(
            not t.anti and t.topology_key == L.LABEL_HOSTNAME
            for t in rep.pod_affinity
        ):
            # self-selecting hostname co-location: the group is ONE
            # placement unit with summed requests (partition_groups
            # guarantees single-class, no live members).  If no single
            # node can hold the sum, the whole group is unschedulable —
            # real-scheduler bind semantics, where the first bound member
            # pins every other member to its node
            total = Resources()
            for m in members:
                total = total + m.requests
            classes.append(
                ClassMeta(
                    pods=members,
                    requests=total,
                    signature=sig,
                    group_size=len(members),
                )
            )
        elif gi in anchor_of:
            zone = anchor_of[gi]
            if zone is None:
                classes.append(
                    ClassMeta(
                        pods=members,
                        requests=requests,
                        signature=sig,
                        infeasible=True,
                        unsched_reason=(
                            "pod affinity has no admissible zone domain"
                        ),
                    )
                )
            else:
                classes.append(
                    ClassMeta(
                        pods=members,
                        requests=requests,
                        signature=sig,
                        zone_pin=zone,
                        max_per_node=maxper,
                        track_slot=slot,
                    )
                )
        elif any(
            t.anti and t.topology_key == L.LABEL_ZONE and t.selects(rep)
            for t in rep.pod_affinity
        ):
            # self-selecting zone anti-affinity: at most one matching pod per
            # zone -> one singleton class per remaining zone domain, pinned;
            # zones already holding a matching pod are off-limits
            terms = [
                t
                for t in rep.pod_affinity
                if t.anti and t.topology_key == L.LABEL_ZONE
            ]
            excluded = {
                sn.zone
                for sn in live
                if sn.zone
                and any(t.selects(bp) for t in terms for bp in sn.pods)
            }
            zr = rep.scheduling_requirements(preferred=True).get(L.LABEL_ZONE)
            allowed = [
                z
                for z in all_zones
                if z not in excluded and (zr is None or zr.has(z))
            ]
            feasz = _feasible_zones(rep, catalog, pools, live, requests)
            allowed.sort(key=lambda z: (z not in feasz, z))
            for i, m in enumerate(members[: len(allowed)]):
                classes.append(
                    ClassMeta(
                        pods=[m],
                        requests=requests,
                        signature=sig,
                        zone_pin=allowed[i],
                        max_per_node=maxper,
                        track_slot=slot,
                    )
                )
            extra = members[len(allowed):]
            if extra:
                classes.append(
                    ClassMeta(
                        pods=extra,
                        requests=requests,
                        signature=sig,
                        infeasible=True,
                        unsched_reason=(
                            "zone anti-affinity: no remaining zone domain"
                        ),
                    )
                )
        elif _zone_spread_zones(rep) and len(all_zones) > 1:
            # Split the class across zones, balancing against existing skew.
            # Candidate domains are filtered by the pod's own zone
            # requirements (Kubernetes counts skew only over nodes that
            # satisfy the pod's nodeAffinity/nodeSelector).
            c0 = next(
                c
                for c in rep.topology_spread
                if c.topology_key == L.LABEL_ZONE
                and c.selects(rep)
                            )
            zr = rep.scheduling_requirements(preferred=True).get(L.LABEL_ZONE)
            cand_zones = [z for z in all_zones if zr is None or zr.has(z)]
            # ...and by the POOLS' zone admission: spread domains are the
            # zones some pool could actually create nodes in
            # (karpenter-core builds domains from provisioner
            # requirements) — an all-zones universe would anchor the skew
            # floor at zones nothing can serve
            pool_zones = _pool_zone_domains(pools, catalog)
            narrowed = [z for z in cand_zones if z in pool_zones]
            if narrowed:
                cand_zones = narrowed
            if not cand_zones:
                cand_zones = all_zones
            # only split into zones where the class can actually land: at
            # least one label-feasible, resource-fitting openable config, or
            # an admitting existing node — a share pinned to a zone with no
            # feasible placement would come back unschedulable even when a
            # feasible near-balanced split exists
            feas_zones = _feasible_zones(rep, catalog, pools, live, requests)
            split_zones = [z for z in cand_zones if z in feas_zones]
            if not split_zones:
                split_zones = cand_zones
            # seed with bound pods the constraint's SELECTOR matches plus
            # the shares sibling classes of this group already took
            assigned = spread_assigned.setdefault(_spread_selkey(c0), {})
            share, guard = _split_shares(
                len(members), split_zones, cand_zones, assigned,
                _live_spread_counts(live, c0, lambda sn: sn.zone or None),
                c0.max_skew,
            )
            if guard and not reason:
                reason = "zone spread constrained by infeasible domains"
            cursor = 0
            for z in split_zones:
                take = share[z]
                if take == 0:
                    continue
                classes.append(
                    ClassMeta(
                        pods=members[cursor : cursor + take],
                        requests=requests,
                        signature=sig,
                        zone_pin=z,
                        max_per_node=maxper,
                        track_slot=slot,
                    )
                )
                cursor += take
        elif _custom_spread_curable(rep, pools):
            # CUSTOM-topology-key spread (scheduling.md:319-331): pool
            # templates are single-valued for the key, so the domains
            # partition the pools — split the class across them like
            # zones, each split pinned via a cloned representative whose
            # node selector carries the domain (decoded nodes inherit the
            # label from their pool template, so the oracle's accounting
            # matches).
            c0 = rep.topology_spread[0]
            key = c0.topology_key
            domain_pools: Dict[str, List[NodePool]] = {}
            for pool in pools:
                vr = pool.template_requirements().get(key)
                if vr is not None and not vr.complement and len(vr.values) == 1:
                    domain_pools.setdefault(
                        next(iter(vr.values)), []
                    ).append(pool)
            # live label values are domains too (the oracle's universe
            # includes them) — an orphaned domain with no serving pool
            # still anchors the skew floor
            live_doms = {
                v for sn in live if (v := sn.labels.get(key)) is not None
            }
            cand_domains = sorted(set(domain_pools) | live_doms)
            kr = rep.scheduling_requirements(preferred=True).get(key)
            if kr is not None:
                cand_domains = [d for d in cand_domains if kr.has(d)]
            # only split into domains the class can actually land in: a
            # label-feasible, resource-fitting openable config of the
            # domain's pools, or an admitting live node carrying the
            # label (a LIVE-ONLY domain is valid — its split class gets
            # an empty pool_allow, so its feasibility row holds only the
            # existing-node columns) — the zone split's _feasible_zones
            # filter
            ovs = {d: _pin_clone(rep, key, d) for d in cand_domains}
            feas_doms = [
                d
                for d in cand_domains
                if _pin_feasible(
                    ovs[d], domain_pools.get(d, ()), catalog,
                    pools_by_name, live, requests,
                )
            ]
            split_domains = feas_doms or [
                d for d in cand_domains if d in domain_pools
            ]
            if not split_domains:
                classes.append(
                    ClassMeta(
                        pods=members,
                        requests=requests,
                        signature=sig,
                        infeasible=True,
                        unsched_reason=(
                            "topology spread: no admissible domain"
                        ),
                    )
                )
                continue
            assigned = spread_assigned.setdefault(_spread_selkey(c0), {})
            share, guard = _split_shares(
                len(members), split_domains, cand_domains, assigned,
                _live_spread_counts(live, c0, lambda sn: sn.labels.get(key)),
                c0.max_skew,
            )
            if guard and not reason:
                reason = "topology spread constrained by infeasible domains"
            cursor = 0
            for d in split_domains:
                take = share[d]
                if take == 0:
                    continue
                classes.append(
                    ClassMeta(
                        pods=members[cursor : cursor + take],
                        requests=requests,
                        signature=ovs[d].constraint_signature(),
                        rep_override=ovs[d],
                        pool_allow=frozenset(
                            p.name for p in domain_pools.get(d, ())
                        ),
                        max_per_node=maxper,
                        track_slot=slot,
                    )
                )
                cursor += take
        elif any(
            c.topology_key not in (L.LABEL_HOSTNAME, L.LABEL_ZONE)
            for c in rep.topology_spread
        ):
            # partition cured the custom-key spread against a pool list
            # that differs from the catalog's (e.g. the defining pool was
            # deleted between the two): compiling the class PLAIN would
            # silently drop a hard constraint — match the oracle, where a
            # key no pool defines has no valid domain
            classes.append(
                ClassMeta(
                    pods=members,
                    requests=requests,
                    signature=sig,
                    infeasible=True,
                    unsched_reason=(
                        "topology spread: no pool defines the domain key"
                    ),
                )
            )
        else:
            classes.append(
                ClassMeta(
                    pods=members,
                    requests=requests,
                    signature=sig,
                    max_per_node=maxper,
                    track_slot=slot,
                )
            )

    # FFD order: constrained classes first, then descending size
    classes.sort(key=ffd_class_key)
    G = len(classes)

    # --------------------------------------------------------- feasibility
    # Vectorized assembly: exact Requirements-algebra checks run once per
    # (signature, pool) over the TYPE axis (and once per zone / capacity
    # type), then broadcast onto the full config axis with numpy — a
    # per-config Python loop would dominate the 200ms solve budget.
    # A node-INEQUIVALENT co-location macro (members spanning several
    # constraint signatures) gets the AND of its member rows: the whole
    # group lands on one node, so its feasible set is exactly the
    # intersection of the members' sets.
    feas = np.zeros((G, C), dtype=bool)
    classes_by_sig: Dict[Tuple, List[int]] = {}
    sig_reps_of: Dict[Tuple, Tuple] = {}
    for g, cm in enumerate(classes):
        if cm.infeasible:
            continue  # proven unschedulable at compile time: row stays 0
        if cm.group_size:
            seen: Dict[Tuple, Pod] = {}
            for p in cm.pods:
                s = p.constraint_signature()
                if s not in seen:
                    seen[s] = p
            pairs = tuple(seen.items())
        else:
            # a custom-spread split's override pod carries the domain
            # pin; its signature IS cm.signature by construction
            pairs = ((cm.signature, cm.rep_override or cm.pods[0]),)
        key = (tuple(s for s, _ in pairs), cm.zone_pin, cm.pool_allow)
        classes_by_sig.setdefault(key, []).append(g)
        sig_reps_of[key] = (pairs, cm.pool_allow)

    row_memo: Dict[Tuple, np.ndarray] = {}

    def _sig_row(
        sig: Tuple,
        rep: Pod,
        zone_pin: str,
        term: int = 0,
        keep: Optional[int] = None,
        pool_allow: Optional[frozenset] = None,
    ) -> np.ndarray:
        mkey = (sig, zone_pin, term, keep, pool_allow)
        row = row_memo.get(mkey)
        if row is not None:
            return row
        open_row = open_config_row(
            catalog, rep, sig, pools_by_name, zone_pin, term, keep, pool_allow
        )
        row = np.zeros(C, dtype=bool)
        row[:first_existing] = open_row
        if live:
            sched = rep.scheduling_requirements(
                preferred=True, term=term, keep_prefs=keep
            )
            if zone_pin:
                sched = Requirements(iter(sched))
                sched.add(Requirement(L.LABEL_ZONE, Op.IN, [zone_pin]))
            for e, sn in enumerate(live):
                row[first_existing + e] = _fits_existing(rep, sched, sn)
        row_memo[mkey] = row
        return row

    def _combined_row(
        pairs: Tuple,
        zone_pin: str,
        term: int,
        keep: Optional[int],
        pool_allow: Optional[frozenset] = None,
    ) -> np.ndarray:
        row = _sig_row(pairs[0][0], pairs[0][1], zone_pin, term, keep, pool_allow)
        for s, r in pairs[1:]:
            row = row & _sig_row(s, r, zone_pin, term, keep, pool_allow)
        return row

    compile_relaxed = 0
    for (sigs, zone_pin, _pa), g_idx in classes_by_sig.items():
        pairs, pool_allow = sig_reps_of[(sigs, zone_pin, _pa)]
        row = _combined_row(pairs, zone_pin, 0, None, pool_allow)
        if not row.any():
            # compile-time relaxation: when the STRICT shape admits no
            # config anywhere, walk the same (OR-term x preference-peel)
            # ladder the oracle walks per pod (scheduler._attempt_ladder)
            # — but once per class, on the compiled rows, so a
            # preference-heavy batch stays on the tensor path instead of
            # draining through the Python continuation.  Global row
            # emptiness is exactly the oracle's "proves unschedulable"
            # for these shapes: no node (new or live) admits the pod, so
            # the oracle would relax too.
            # a multi-signature class is a co-location macro: the merge
            # gate requires identical sig[9] (OR-terms) across members,
            # so rep0's term count holds for all.  Preference peeling is
            # walked here only when every member carries the SAME
            # preference list — a uniform keep index over DIFFERING lists
            # would peel one member's satisfiable preference because of
            # another's impossible one; those closures skip the ladder
            # and relax as a unit through the oracle (solver.solve pulls
            # the whole closure, whose gang machinery peels per member)
            rep0 = pairs[0][1]
            n_terms = len(rep0.node_affinity_terms())
            uniform_prefs = len({s[7] for s, _ in pairs}) == 1
            n_prefs = (
                len(rep0.preferred_affinity) if uniform_prefs else 0
            )
            for ti in range(n_terms):
                keeps = [None] if ti else []
                keeps += list(range(n_prefs - 1, -1, -1))
                found = False
                for keep in keeps:
                    cand = _combined_row(pairs, zone_pin, ti, keep, pool_allow)
                    if cand.any():
                        row = cand
                        compile_relaxed += sum(
                            len(classes[g].pods) for g in g_idx
                        )
                        found = True
                        break
                if found:
                    break
        feas[g_idx] = row

    req_mat = (
        np.stack([_vec(cm.requests, axes) for cm in classes])
        if classes
        else np.zeros((0, R), np.float32)
    )

    # pool weight priority (reference designs/provisioner-priority.md): the
    # oracle tries pools highest-weight-first and commits to the first that
    # admits the pod.  Enforce the same by restricting each class's
    # new-node feasibility to its highest-weight admitting TIER — pools
    # with EQUAL weight have no defined priority between them (the oracle
    # freely fills any open node regardless of pool), so restricting to a
    # single pool within a tier would fragment the pack.
    if len(pools) > 1:
        cat_tiers, n_tiers = catalog_tiers(catalog)
        tier_of = np.full(C, -1, np.int32)  # live columns carry no tier
        tier_of[:first_existing] = cat_tiers
        for g in range(G):
            fits = (req_mat[g][None, :] <= alloc + 1e-6).all(axis=1)
            for t in range(n_tiers):
                sel = (tier_of == t) & feas[g] & fits
                if sel.any():
                    feas[g] &= (tier_of == t) | (tier_of == -1)
                    break

    # seed per-signature counters with pods already bound to existing nodes
    # (so anti-affinity/hostname-spread caps see prior placements)
    S = len(track_slots) + 1
    sig_used0 = np.zeros((S, len(live)), np.int32)
    if track_slots:
        for e, sn in enumerate(live):
            for bound in sn.pods:
                # count by SELECTOR match, not signature equality: a bound
                # pod with matching labels blocks an anti-affinity class
                # even when it carries no constraint itself
                for key, s in track_slots.items():
                    if _track_matches(key, bound):
                        sig_used0[s, e] += 1

    return CompiledProblem(
        axes=axes,
        classes=classes,
        configs=configs,
        req=req_mat,
        cnt=np.array(
            # a co-location macro is ONE placement unit regardless of size
            [1 if cm.group_size else len(cm.pods) for cm in classes],
            dtype=np.int32,
        ),
        maxper=np.array(
            [min(cm.max_per_node, BIG) for cm in classes], dtype=np.int32
        ),
        slot=np.array([cm.track_slot for cm in classes], dtype=np.int32),
        alloc=alloc,
        price=price,
        openable=openable,
        feas=feas,
        pool_daemon_overhead=catalog.pool_overhead,
        used0=np.stack([_vec(sn.used, axes) for sn in live])
        if live
        else np.zeros((0, R), np.float32),
        cfg0=np.arange(first_existing, first_existing + len(live), dtype=np.int32),
        npods0=np.array([len(sn.pods) for sn in live], dtype=np.int32),
        sig_used0=sig_used0,
        n_track_slots=S,
        unsupported_reason=reason,
        compile_relaxed=compile_relaxed,
    )


def _memo_put(catalog: Catalog, key, value):
    """feas_memo insert with the shared unbounded-workload backstop."""
    if len(catalog.feas_memo) > 50_000:
        catalog.feas_memo.clear()
    catalog.feas_memo[key] = value
    return value


def ffd_class_key(cm: ClassMeta) -> Tuple:
    """The compile's FFD class sort key: constrained classes first, then
    descending size; ties keep list order (stable sort), which is the
    classes' first-occurrence order over the batch.  Shared with the
    resident delta planner (ops/resident.py), which must insert arriving
    classes at exactly the position a from-scratch compile would sort
    them to."""
    constrained = (
        cm.max_per_node < BIG
        or bool(cm.zone_pin)
        or cm.rep_override is not None
    )
    r = cm.requests
    return (
        not constrained,
        -(r.cpu + r.memory / (4 * 2**30)),
    )


def open_config_row(
    catalog: Catalog,
    rep: Pod,
    sig: Tuple,
    pools_by_name: Dict[str, NodePool],
    zone_pin: str = "",
    term: int = 0,
    keep: Optional[int] = None,
    pool_allow: Optional[frozenset] = None,
) -> np.ndarray:
    """The OPENABLE prefix of one class's feasibility row.

    Depends only on the signature shape and this catalog snapshot — never
    on the live nodes — so it memoizes for the CATALOG's lifetime
    ("catalog epoch": a new inventory snapshot builds a new Catalog with
    a fresh memo).  A warm re-compile of a recurring pending set
    assembles its rows from these cached prefixes and only re-checks the
    live columns.  THE single assembly path for openable rows: both
    `compile_problem` and the resident delta planner (ops/resident.py)
    call it, so an incrementally-scattered row is bit-identical to a
    from-scratch compile's by construction."""
    ckey = ("row", sig, zone_pin, term, keep, pool_allow)
    open_row = catalog.feas_memo.get(ckey)
    if open_row is None:
        open_row = np.zeros(len(catalog.configs), dtype=bool)
        for pname, pr in catalog.pool_rows.items():
            if pool_allow is not None and pname not in pool_allow:
                continue  # only the domain's pools DEFINE the spread key
            ent = _pool_feas(
                catalog, rep, sig, pname, pools_by_name, term, keep
            )
            if ent is None:
                continue
            type_ok, zone_ok, ct_ok = ent
            if zone_pin:
                zone_ok = zone_ok & np.fromiter(
                    (z == zone_pin for z in pr.zones), bool, len(pr.zones)
                )
            open_row[pr.rows] = (
                type_ok[pr.t_of] & zone_ok[pr.z_of] & ct_ok[pr.ct_of]
            )
        _memo_put(catalog, ckey, open_row)
    return open_row


def catalog_tiers(catalog: Catalog) -> Tuple[np.ndarray, int]:
    """(tier index per catalog config row, tier count) for the pool-weight
    priority restriction — pools are weight-desc ordered, equal weights
    share a tier.  Memoized per catalog; both `compile_problem`'s
    per-class loop and the resident path's `restrict_open_tier` read it,
    so the tier rule has exactly one definition (live columns carry tier
    -1 in the compile and never participate in tier CHOICE, which is why
    the per-class restriction below can run on the openable prefix
    alone)."""
    ent = catalog.feas_memo.get("tiers")
    if ent is None:
        pools = catalog.pools
        tier_of_rank = np.zeros(max(len(pools), 1), np.int32)
        tier = 0
        for r in range(1, len(pools)):
            if pools[r].weight != pools[r - 1].weight:
                tier += 1
            tier_of_rank[r] = tier
        tier_of = (
            tier_of_rank[catalog.pool_rank_of]
            if len(catalog.pool_rank_of)
            else np.zeros(0, np.int32)
        )
        ent = _memo_put(catalog, "tiers", (tier_of, tier + 1))
    return ent


def restrict_open_tier(
    catalog: Catalog, open_row: np.ndarray, req_vec: np.ndarray
) -> np.ndarray:
    """Per-class pool-weight tier restriction on the OPENABLE prefix —
    the single-class equivalent of `compile_problem`'s pool-priority
    loop.  Sound to run without the live columns: in the compile, live
    columns carry tier -1, so they never influence which tier is chosen
    and are never masked by the restriction.  The delta-correctness fuzz
    suite (tests/test_resident_fuzz.py) pins the equivalence."""
    if len(catalog.pools) <= 1:
        return open_row
    tier_of, n_tiers = catalog_tiers(catalog)
    fits = (req_vec[None, :] <= catalog.alloc + 1e-6).all(axis=1)
    for t in range(n_tiers):
        if ((tier_of == t) & open_row & fits).any():
            return open_row & (tier_of == t)
    return open_row


def _pool_zone_domains(pools: Sequence[NodePool], catalog: Catalog) -> set:
    """Zone domain universe: offering zones admitted by some pool's
    TEMPLATE zone requirement.  Pool-side only — no taint or type
    filtering, matching karpenter-core's domain construction and the
    Kubernetes default of nodeTaintsPolicy: Ignore (the oracle's
    Scheduler.__init__ builds the identical universe).  Pod-independent,
    so it memoizes once per catalog."""
    out = catalog.feas_memo.get("domains")
    if out is None:
        out = set()
        for pool in pools:
            zr = pool.template_requirements().get(L.LABEL_ZONE)
            pr = catalog.pool_rows.get(pool.name)
            if pr is None:
                continue
            out.update(z for z in pr.zones if zr is None or zr.has(z))
        _memo_put(catalog, "domains", out)
    return out


def _feasible_zones(
    rep: Pod,
    catalog: Catalog,
    pools: Sequence[NodePool],
    live: Sequence[StateNode],
    requests: Resources,
) -> set:
    """Zones where `rep`'s class has >=1 feasible placement: a
    label-compatible, resource-fitting openable config, or an admitting
    existing node with room for the request.

    The OPENABLE half depends only on (signature, requests) and the
    catalog snapshot, so it memoizes for the catalog's lifetime (the
    same reasoning as `_pool_feas`); only the live-node half is
    recomputed per solve."""
    sig = rep.constraint_signature()
    memo_key = ("zones", sig, tuple(sorted(requests.items())))
    zones = catalog.feas_memo.get(memo_key)
    if zones is None:
        req_vec = _vec(requests, catalog.axes)
        pools_by_name = {p.name: p for p in pools}
        zones = set()
        for pname, pr in catalog.pool_rows.items():
            ent = _pool_feas(catalog, rep, sig, pname, pools_by_name)
            if ent is None:
                continue
            type_ok, zone_ok, ct_ok = ent
            fits = (req_vec[None, :] <= catalog.alloc[pr.rows] + 1e-6).all(axis=1)
            # the FULL admission mask, same as the feas[G, C] assembly: a
            # pool zone-restricted to zone-a must not report b/c feasible
            ok_rows = (
                type_ok[pr.t_of] & zone_ok[pr.z_of] & ct_ok[pr.ct_of] & fits
            )
            zones.update(pr.zones[z] for z in set(pr.z_of[ok_rows].tolist()))
        _memo_put(catalog, memo_key, zones)
    out = set(zones)
    if live:
        sched = rep.scheduling_requirements(preferred=True)
        for sn in live:
            if sn.zone and sn.zone not in out and _fits_existing(rep, sched, sn):
                if (sn.used + requests).fits(sn.allocatable):
                    out.add(sn.zone)
    return out


def _pin_feasible(
    ov: Pod,
    pool_list: Sequence[NodePool],
    catalog: Catalog,
    pools_by_name: Dict[str, NodePool],
    live: Sequence[StateNode],
    requests: Resources,
) -> bool:
    """Whether a domain-pinned representative has at least one
    label-compatible, resource-fitting openable config among its domain's
    pools, or an admitting live node with room — the custom-topology-key
    analogue of `_feasible_zones`."""
    req_vec = _vec(requests, catalog.axes)
    sig = ov.constraint_signature()
    for pool in pool_list:
        ent = _pool_feas(catalog, ov, sig, pool.name, pools_by_name)
        if ent is None:
            continue
        type_ok, zone_ok, ct_ok = ent
        pr = catalog.pool_rows[pool.name]
        fits = (req_vec[None, :] <= catalog.alloc[pr.rows] + 1e-6).all(axis=1)
        if (type_ok[pr.t_of] & zone_ok[pr.z_of] & ct_ok[pr.ct_of] & fits).any():
            return True
    if live:
        sched = ov.scheduling_requirements(preferred=True)
        for sn in live:
            if _fits_existing(ov, sched, sn) and (
                sn.used + requests
            ).fits(sn.allocatable):
                return True
    return False


def _spread_selkey(c0) -> Tuple:
    """Identity of a spread group's share accumulator — must mirror the
    oracle tracker's group key (topology.py:_spread_group): topology key,
    selector, expressions, max_skew; when_unsatisfiable deliberately
    omitted (the tracker shares counts across DNS/SA variants too)."""
    return (
        c0.topology_key,
        tuple(sorted(c0.label_selector)),
        c0.match_expressions,
        c0.max_skew,
    )


def _live_spread_counts(
    live: Sequence[StateNode], c0, domain_of
) -> Dict[str, int]:
    """Per-domain counts of live bound pods the constraint's selector
    matches (the oracle replays placements the same way)."""
    out: Dict[str, int] = {}
    for sn in live:
        d = domain_of(sn)
        if d is None:
            continue
        n_sel = sum(1 for bp in sn.pods if c0.selects(bp))
        if n_sel:
            out[d] = out.get(d, 0) + n_sel
    return out


def _split_shares(
    n_members: int,
    split_doms: Sequence[str],
    cand_doms: Sequence[str],
    assigned: Dict[str, int],
    live_counts: Dict[str, int],
    max_skew: int,
) -> Tuple[Dict[str, int], bool]:
    """Balanced shares over ``split_doms``, seeded with the shares sibling
    classes of the group already took (``assigned``, updated in place) and
    with live placements the constraint's selector matches.

    The second return is the infeasible-domain GUARD: skew is measured
    against ALL candidate domains, so when an unservable domain anchors
    the global minimum and the hard-pinned shares would push a served
    domain past min+maxSkew, the caller must route the class to the
    oracle (which caps per-domain instead of pre-splitting)."""
    counts = {
        d: assigned.get(d, 0) + live_counts.get(d, 0) for d in split_doms
    }
    share = _balanced_split(n_members, counts)
    guard = False
    if len(split_doms) < len(cand_doms):
        finals = {
            d: assigned.get(d, 0) + live_counts.get(d, 0) for d in cand_doms
        }
        for d, take in share.items():
            finals[d] = finals.get(d, 0) + take
        floor = min(finals.values(), default=0)
        guard = any(finals[d] > floor + max_skew for d in split_doms)
    for d, take in share.items():
        if take:
            assigned[d] = assigned.get(d, 0) + take
    return share, guard


def _anchor_zone_affinity(
    group_list: List[Tuple[Tuple, List[Pod]]],
    all_zones: Sequence[str],
    catalog: Catalog,
    pools: Sequence[NodePool],
    live: Sequence[StateNode],
) -> Dict[int, Optional[str]]:
    """Choose one anchor zone per zone-affinity component.

    Returns {group index -> zone} for every group in a component that
    carries zone-keyed required pod affinity (None = no admissible zone,
    i.e. compile-time unschedulable).  Components are the transitive
    closure of "some affinity term selects the other class" — every class
    in a component pins to the same zone, the compile-time-sound rendering
    of the oracle's first-placement domain anchoring (scheduling.md:124-430
    interPodAffinity semantics; scheduling/topology.py _AffinityGroup)."""
    aff_terms: Dict[int, List] = {}
    for gi, (_, members) in enumerate(group_list):
        rep = members[0]
        terms = [
            t
            for t in rep.pod_affinity
            if not t.anti and t.topology_key == L.LABEL_ZONE
        ]
        if terms:
            aff_terms[gi] = terms
    if not aff_terms:
        return {}

    n = len(group_list)
    reps = [members[0] for _, members in group_list]
    parent = list(range(n))

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def union(a: int, b: int) -> None:
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[rb] = ra

    for gi, terms in aff_terms.items():
        for t in terms:
            for gj in range(n):
                if gj != gi and t.selects(reps[gj]):
                    union(gi, gj)

    comps: Dict[int, List[int]] = {}
    for gi in range(n):
        comps.setdefault(find(gi), []).append(gi)

    out: Dict[int, Optional[str]] = {}
    for idxs in comps.values():
        if not any(gi in aff_terms for gi in idxs):
            continue
        # candidates: intersection of every member's own zone requirements
        cand = set(all_zones)
        for gi in idxs:
            zr = reps[gi].scheduling_requirements(preferred=True).get(L.LABEL_ZONE)
            if zr is not None:
                cand &= {z for z in all_zones if zr.has(z)}
        # existing matching placements anchor the domain (followers must
        # join the zone that already holds matching pods)
        for gi in idxs:
            for t in aff_terms.get(gi, ()):
                dom = {
                    sn.zone
                    for sn in live
                    if sn.zone and any(t.selects(bp) for bp in sn.pods)
                }
                if dom:
                    cand &= dom
        # prefer a zone feasible for every class in the component
        feas = set(cand)
        for gi in idxs:
            feas &= _feasible_zones(
                reps[gi], catalog, pools, live, group_list[gi][0][1]
            )
        if feas:
            pick: Optional[str] = sorted(feas)[0]
        elif cand:
            pick = sorted(cand)[0]
        else:
            pick = None
        for gi in idxs:
            out[gi] = pick
    return out


def _balanced_split(n: int, existing_counts: Dict[str, int]) -> Dict[str, int]:
    """Distribute n pods over zones so final (existing + new) counts are as
    level as possible — the maxSkew>=1 optimum a spread constraint wants."""
    zones = sorted(existing_counts)
    counts = dict(existing_counts)
    out = {z: 0 for z in zones}
    for _ in range(n):
        z = min(zones, key=lambda z: (counts[z], z))
        counts[z] += 1
        out[z] += 1
    return out


def _merge_pool(
    rep: Pod, sched: Requirements, pool: NodePool
) -> Optional[Requirements]:
    """Pool template ∧ pod requirements, or None if structurally infeasible."""
    if not tolerates_all(rep.tolerations, pool.taints):
        return None
    merged = pool.template_requirements().union(sched)
    if merged.is_unsatisfiable():
        return None
    return merged


def _fits_existing(rep: Pod, sched: Requirements, sn: StateNode) -> bool:
    if not tolerates_all(rep.tolerations, sn.taints):
        return False
    node_reqs = Requirements.from_labels(sn.labels)
    return node_reqs.compatible(sched)
