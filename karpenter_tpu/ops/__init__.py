"""Device-side kernels and constraint compilation for the scheduling solver."""

from karpenter_tpu.ops.packer import PackResult, pack_kernel, run_pack
from karpenter_tpu.ops.tensorize import CompiledProblem, compile_problem

__all__ = [
    "CompiledProblem",
    "compile_problem",
    "PackResult",
    "pack_kernel",
    "run_pack",
]
