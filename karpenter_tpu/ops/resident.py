"""Device-resident incremental cluster tensors (ROADMAP item 2).

Every reconcile tick used to rebuild the pods x classes x configs tensors
on the host and ship them device-ward, so warm-tick latency was dominated
by re-tensorize + transfer rather than the solve itself.  This module
keeps the PADDED solve tensors **resident on device across ticks** and
updates them with **scatter deltas** instead of re-tensorization — the
analogue of karpenter-core's in-memory cluster-state cache, which exists
precisely so each scheduling pass starts from deltas, not a cold snapshot.

Architecture (docs/designs/resident-tensors.md):

- `ResidentState` owns one padded problem: host numpy MIRRORS (the source
  of truth the delta planner edits) plus DEVICE buffers kept bit-identical
  to them by replaying every edit through one jitted gather+scatter step
  (`_delta_fn`) with **donated buffers**, so a warm update allocates no
  new device memory and uploads only the changed rows/columns.
- The **delta planner** diffs the incoming (pods, live nodes) against the
  resident epoch using the PR-3 identity+epoch fingerprints — pod and
  pool objects key by ``(id, _mut)``, live nodes by content — and turns
  the diff into: a class-axis permutation (arrivals insert at their FFD
  sort position, departures compact), a live-column permutation over the
  config and node-slot axes, and scatter payloads for new/changed rows.
- **Equivalence discipline**: the delta path must produce tensors
  bit-equal to a from-scratch `compile_problem` at every step
  (tests/test_resident_fuzz.py enforces it on single-device AND mesh
  backends).  Row assembly is therefore SHARED with the compiler
  (`tensorize.open_config_row` / `restrict_open_tier` /
  `ffd_class_key`), and anything the planner cannot prove equivalent —
  catalog roll, pool shape change, constraint carriers, axis changes,
  bucket overflow — falls back to the full tensorize (counted in
  ``karpenter_solver_resident_rebuilds_total``).
- **Sharding**: when the scheduler's pack_fn is the mesh backend
  (parallel/mesh.py), the buffers are placed with the SAME shardings the
  sharded pack expects — feasibility and the config catalog over
  "model", the node-slot state over "data" — so the resident path is the
  same code single-device and 8-device.

Eligibility (the "plain" subset — deliberately the same guard set as the
batched-consolidation base in `TensorScheduler._build_removal_base`, so
`_removal_base` can read these tensors directly): every batch pod free of
pod affinity / topology spread / preferences / multi-OR-term node
affinity / volume claims, and no bound pod on ANY existing node — live,
cordoned, or draining — carrying pod affinity (partition_groups keys its
symmetric-anti-affinity repel on all of them).  Everything else takes
the ordinary compile path unchanged.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.api import Pod
from karpenter_tpu.api import labels as L
from karpenter_tpu.obs.device import OBSERVATORY
from karpenter_tpu.ops.tensorize import (
    BIG,
    Catalog,
    ClassMeta,
    CompiledProblem,
    ConfigMeta,
    _fits_existing,
    _vec,
    ffd_class_key,
    live_filter,
    open_config_row,
    restrict_open_tier,
)
from karpenter_tpu.ops.packer import PackResult, _bucket, _bucket_classes
from karpenter_tpu.utils.trace import phase

# a delta touching more than this fraction of the batch rebuilds instead:
# past the midpoint the full compile is cheaper than planning and
# scattering most of the tensor anyway (the +8 grace keeps tiny batches
# from thrashing on integer effects)
REBUILD_FRACTION = 0.5

# node-slot headroom replicated from packer.node_slot_bound for the plain
# shape (no constrained classes): E + min(n_pods, 256)
_SLOT_HEADROOM = 256


def _plain_pod(p: Pod) -> bool:
    """The resident-expressible pod shape: no pod-level coupling, no
    relax-eligible soft constraints, no volume claims — the same guard
    set as the batched-removal base, so every delta is provably
    order-independent at the class level."""
    return not (
        p.pod_affinity
        or p.topology_spread
        or p.preferred_affinity
        or p.volume_claims
        or len(p.node_affinity_terms()) > 1
    )


def _carrier_free(existing) -> bool:
    """No bound pod anywhere in `existing` — live, cordoned, or draining —
    may carry a pod-affinity term.  partition_groups routes batch classes
    SELECTED by any existing carrier's anti term to the oracle (symmetric
    anti-affinity repels incoming pods), a decision keyed to ALL existing
    nodes that the delta planner cannot replay; `_compact_guard`'s
    carrier clause reads the same set.  Live carriers additionally change
    feasibility columns.  One rule covers all three — and it is why the
    resident-hit path may store compact_ok=True without re-running the
    guard."""
    return not any(bp.pod_affinity for sn in existing for bp in sn.pods)


def resident_capable(pack_fn) -> bool:
    """Resident buffers can only serve pack backends that run in-process
    on this host's devices: the default auto_pack dispatch or the
    mesh-sharded kernel.  Sidecar/forced/custom pack_fns keep the plain
    upload path (their transfer contract is their own)."""
    from karpenter_tpu.ops.pallas_packer import auto_pack

    return pack_fn is auto_pack or getattr(pack_fn, "mesh", None) is not None


def _catalog_key(solver) -> tuple:
    """Identity+epoch fingerprint of everything the catalog derives from
    (the PR-3 invalidation contract): a rolled inventory list, a mutated
    pool, or a changed daemonset set obsoletes every resident tensor."""
    return (
        tuple((id(p), p.__dict__.get("_mut", 0)) for p in solver.pools),
        tuple(sorted((k, id(v)) for k, v in solver.instance_types.items())),
        tuple((id(d), d.__dict__.get("_mut", 0)) for d in solver.daemonsets),
    )


def _node_sched_fp(sn) -> tuple:
    """The node content that drives ADMISSION (the feasibility column and
    the allocatable row): labels, taints, allocatable."""
    return (
        tuple(sorted(sn.labels.items())),
        tuple(map(repr, sn.taints)),
        tuple(sorted(sn.allocatable.items())),
    )


def _node_usage_fp(sn) -> tuple:
    """The node content that drives PREFILL (used0/npods0): usage plus
    the bound-pod identity+epoch set (a mutated bound pod could grow
    pod affinity, which the eligibility guard must re-check)."""
    return (
        tuple(sorted(sn.used.items())),
        tuple((id(bp), bp.__dict__.get("_mut", 0)) for bp in sn.pods),
    )


class _Cls:
    """One resident class: the compile's ClassMeta plus planner caches."""

    __slots__ = ("cm", "key", "req_vec", "sched", "sort_key")

    def __init__(self, cm: ClassMeta, key, axes):
        self.cm = cm
        self.key = key  # the interned ClassKey
        self.req_vec = _vec(cm.requests, axes)
        rep = cm.pods[0]
        # signature-determined, so any member's is equivalent — computed
        # once per class and kept even if the original rep departs
        self.sched = rep.scheduling_requirements(preferred=True)
        self.sort_key = ffd_class_key(cm)


# (mesh-or-None) -> jitted delta step; one entry per mesh object (plus the
# single-device None entry), retraced per padded-shape bucket
_DELTA_JITS: dict = {}


def _mesh_shardings(mesh) -> dict:
    """The ONE axis-spec table for every resident buffer — `_delta_fn`'s
    in/out shardings and `_device_seed`'s placements must agree exactly,
    or the donated jit reshards (a silent copy per warm tick) instead of
    reusing the buffers in place."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from karpenter_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    return dict(
        repl=NamedSharding(mesh, P()),
        on_c=NamedSharding(mesh, P(MODEL_AXIS)),
        on_c2=NamedSharding(mesh, P(MODEL_AXIS, None)),
        on_gc=NamedSharding(mesh, P(None, MODEL_AXIS)),
        on_k=NamedSharding(mesh, P(DATA_AXIS)),
        on_k2=NamedSharding(mesh, P(DATA_AXIS, None)),
        on_sk=NamedSharding(mesh, P(None, DATA_AXIS)),
    )


def _delta_fn(mesh):
    fn = _DELTA_JITS.get(mesh)
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    def step(
        req, cnt, feas, alloc, price, used0, npods0,
        g_perm, c_perm, k_perm,
        g_idx, g_req, g_cnt, g_feas,
        col_idx, col_alloc, col_price, col_feas,
        k_idx, k_used, k_np,
        e_new, fe,
    ):
        # 1) permutations: class rows compact/insert to the new FFD
        #    order, live columns follow the new snapshot order; fresh and
        #    vacated positions gather from the reserved scratch slot,
        #    which permanently holds canonical pad values
        req = req[g_perm]
        cnt = cnt[g_perm]
        feas = feas[g_perm][:, c_perm]
        alloc = alloc[c_perm]
        price = price[c_perm]
        used0 = used0[k_perm]
        npods0 = npods0[k_perm]
        # 2) scatters: new/changed class rows, then new/changed live
        #    columns (payloads come from the final host mirror, so the
        #    row/column overlap cells agree by construction; padded
        #    payload entries target the scratch slots with canonical pad
        #    values, leaving them invariant)
        req = req.at[g_idx].set(g_req)
        cnt = cnt.at[g_idx].set(g_cnt)
        feas = feas.at[g_idx].set(g_feas)
        alloc = alloc.at[col_idx].set(col_alloc)
        price = price.at[col_idx].set(col_price)
        feas = feas.at[:, col_idx].set(col_feas)
        used0 = used0.at[k_idx].set(k_used)
        npods0 = npods0.at[k_idx].set(k_np)
        kp = used0.shape[0]
        iota = jnp.arange(kp, dtype=jnp.int32)
        cfg0 = jnp.where(iota < e_new, fe + iota, -1)
        return req, cnt, feas, alloc, price, used0, npods0, cfg0

    donate = tuple(range(7))  # the seven resident buffers reuse in place
    if mesh is None:
        fn = jax.jit(step, donate_argnums=donate)
    else:
        sh = _mesh_shardings(mesh)
        repl, on_c, on_c2, on_gc, on_k, on_k2 = (
            sh["repl"], sh["on_c"], sh["on_c2"], sh["on_gc"], sh["on_k"],
            sh["on_k2"],
        )
        fn = jax.jit(
            step,
            donate_argnums=donate,
            in_shardings=(
                repl, repl, on_gc, on_c2, on_c, on_k2, on_k,  # buffers
                repl, repl, repl,  # permutations
                repl, repl, repl, repl,  # class scatters
                repl, repl, repl, repl,  # column scatters
                repl, repl, repl,  # slot scatters
                repl, repl,  # e_new, fe
            ),
            out_shardings=(
                repl, repl, on_gc, on_c2, on_c, on_k2, on_k, on_k
            ),
        )
    _DELTA_JITS[mesh] = fn
    return fn


def _pad_idx(idx: List[int], scratch: int, floor: int = 4) -> np.ndarray:
    """Pad a scatter index list to its power-of-two bucket with the
    scratch slot (whose payload entries are canonical pad values), so the
    delta jit compiles once per bucket instead of once per delta size."""
    n = _bucket(max(len(idx), 1), floor=floor)
    return np.asarray(idx + [scratch] * (n - len(idx)), np.int32)


class ResidentState:
    """One device-resident padded problem plus the metadata to diff it."""

    def __init__(self):
        # which solver purpose seeded this state ("solve" = the pending
        # batch, "removal" = the consolidation base universe) — the
        # consumer label on karpenter_device_resident_bytes
        self.consumer = "solve"
        # identity / catalog epoch
        self.cat_key: tuple = ()
        self.axes: Tuple[str, ...] = ()
        self.catalog: Optional[Catalog] = None
        self.pools: list = []
        self.pools_by_name: dict = {}
        self.fe = 0  # first_existing == len(catalog.configs)
        self.pack_fn_ref = None
        self.mesh = None
        self.pins: tuple = ()  # keep every id-keyed object allocated
        # classes / pods
        self.cls: List[_Cls] = []
        self.slot_of: Dict[object, int] = {}  # ClassKey -> g
        self.pod_entry: Dict[int, tuple] = {}  # id -> (pod, mut, ClassKey)
        self.extra_axes: Dict[str, int] = {}  # extra axis -> using classes
        # live nodes
        self.live: list = []
        self.node_pos: Dict[str, int] = {}
        self.node_fp: Dict[str, tuple] = {}
        self.configs_live: List[ConfigMeta] = []
        # padded host mirrors (source of truth; device mirrors them)
        self.Gp = self.Cp = self.Kp = self.R = 0
        self.h_req = self.h_cnt = self.h_feas = None
        self.h_alloc = self.h_price = self.h_openable = None
        self.h_used0 = self.h_npods0 = None
        # device buffers
        self.d_req = self.d_cnt = self.d_feas = None
        self.d_alloc = self.d_price = self.d_openable = None
        self.d_used0 = self.d_npods0 = self.d_cfg0 = None
        self.d_maxper = self.d_slot = self.d_sig0 = None
        # current snapshot (what the solver's compile cache stores)
        self.prob: Optional[CompiledProblem] = None
        self.last_delta_rows = 0

    # -------------------------------------------------------------- build
    @classmethod
    def build(
        cls, solver, pods: List[Pod], prob: CompiledProblem, catalog,
        consumer: str = "solve",
    ):
        """Seed a state from a freshly-compiled problem, or None when the
        problem falls outside the resident-expressible shape."""
        if prob is None or not prob.supported or prob.compile_relaxed:
            return None
        if prob.n_track_slots != 1:
            return None
        for cm in prob.classes:
            if (
                cm.group_size
                or cm.zone_pin
                or cm.rep_override is not None
                or cm.pool_allow is not None
                or cm.infeasible
            ):
                return None
        if len(set(map(id, pods))) != len(pods):
            return None  # duplicate objects would double-count a class
        for p in pods:
            if not _plain_pod(p):
                return None
        fe = len(catalog.configs)
        live = [cfg.existing for cfg in prob.configs[fe:]]
        if not _carrier_free(solver.existing):
            return None  # carriers (even on non-live nodes) change the partition
        st = cls()
        st.consumer = consumer
        st.cat_key = _catalog_key(solver)
        st.axes = prob.axes
        st.catalog = catalog
        st.pools = list(solver.pools)
        st.pools_by_name = {p.name: p for p in catalog.pools}
        st.fe = fe
        st.pack_fn_ref = solver.pack_fn
        st.mesh = getattr(solver.pack_fn, "mesh", None)
        st.pins = (
            tuple(solver.pools),
            tuple(solver.instance_types.values()),
            tuple(solver.daemonsets),
        )
        G, C = prob.feas.shape
        E = C - fe
        n_pods = prob.total_pods()
        st.R = len(prob.axes)
        st.Gp = _bucket_classes(G + 1)
        st.Cp = _bucket(C + 1)
        st.Kp = _bucket(E + min(n_pods, _SLOT_HEADROOM) + 1)
        for g, cm in enumerate(prob.classes):
            key = cm.pods[0].class_key()
            st.cls.append(_Cls(cm, key, st.axes))
            st.slot_of[key] = g
            for ax in cm.requests.keys():
                if ax not in L.WELL_KNOWN_RESOURCES:
                    st.extra_axes[ax] = st.extra_axes.get(ax, 0) + 1
            for p in cm.pods:
                st.pod_entry[id(p)] = (p, p.__dict__.get("_mut", 0), key)
        st.live = list(live)
        st.configs_live = list(prob.configs[fe:])
        for e, sn in enumerate(live):
            st.node_pos[sn.name] = e
            st.node_fp[sn.name] = (_node_sched_fp(sn), _node_usage_fp(sn))
        # padded mirrors (pad_problem's conventions: price inf, cfg -1)
        st.h_req = np.zeros((st.Gp, st.R), np.float32)
        st.h_req[:G] = prob.req
        st.h_cnt = np.zeros(st.Gp, np.int32)
        st.h_cnt[:G] = prob.cnt
        st.h_feas = np.zeros((st.Gp, st.Cp), bool)
        st.h_feas[:G, :C] = prob.feas
        st.h_alloc = np.zeros((st.Cp, st.R), np.float32)
        st.h_alloc[:C] = prob.alloc
        st.h_price = np.full(st.Cp, np.inf, np.float32)
        st.h_price[:C] = prob.price
        st.h_openable = np.zeros(st.Cp, bool)
        st.h_openable[:C] = prob.openable
        st.h_used0 = np.zeros((st.Kp, st.R), np.float32)
        st.h_used0[:E] = prob.used0
        st.h_npods0 = np.zeros(st.Kp, np.int32)
        st.h_npods0[:E] = prob.npods0
        st._device_seed()
        st.prob = prob
        st.last_delta_rows = 0
        return st

    def _device_seed(self) -> None:
        """Upload the mirrors once (the rebuild's one full transfer) with
        the pack backend's shardings, plus the pack-time constants the
        plain shape never mutates (maxper=BIG, slot=0, sig0=0).  Every
        upload rides the counted seam (obs/device.py) under the
        ``resident_seed`` site, and the fresh allocation is what the
        ``seed`` entry of karpenter_device_resident_updates_total counts
        — vs ``donated`` warm updates that allocate nothing."""
        E = len(self.live)
        cfg0 = np.full(self.Kp, -1, np.int32)
        cfg0[:E] = np.arange(self.fe, self.fe + E, dtype=np.int32)
        maxper = np.full(self.Gp, BIG, np.int32)
        slot = np.zeros(self.Gp, np.int32)
        sig0 = np.zeros((2, self.Kp), np.int32)  # Sp bucket floor is 2
        if self.mesh is None:
            names = ("repl", "on_c", "on_c2", "on_gc", "on_k", "on_k2",
                     "on_sk")
            put = {
                k: (lambda a: OBSERVATORY.put("resident_seed", a))
                for k in names
            }
        else:
            sh = _mesh_shardings(self.mesh)
            put = {
                k: (lambda a, s=s: OBSERVATORY.put("resident_seed", a, s))
                for k, s in sh.items()
            }
        self.d_req = put["repl"](self.h_req)
        self.d_cnt = put["repl"](self.h_cnt)
        self.d_feas = put["on_gc"](self.h_feas)
        self.d_alloc = put["on_c2"](self.h_alloc)
        self.d_price = put["on_c"](self.h_price)
        self.d_openable = put["on_c"](self.h_openable)
        self.d_used0 = put["on_k2"](self.h_used0)
        self.d_npods0 = put["on_k"](self.h_npods0)
        self.d_cfg0 = put["on_k"](cfg0)
        self.d_maxper = put["repl"](maxper)
        self.d_slot = put["repl"](slot)
        self.d_sig0 = put["on_sk"](sig0)
        OBSERVATORY.count_resident_update("seed")

    def device_bytes(self) -> int:
        """Live device-buffer footprint of this state (logical bytes;
        sharded buffers report their global size)."""
        total = 0
        for a in (
            self.d_req, self.d_cnt, self.d_feas, self.d_alloc,
            self.d_price, self.d_openable, self.d_used0, self.d_npods0,
            self.d_cfg0, self.d_maxper, self.d_slot, self.d_sig0,
        ):
            if a is not None:
                total += int(a.nbytes)
        return total

    # ------------------------------------------------------------ refresh
    def try_refresh(
        self, solver, pods: List[Pod], cat_key, live_new, node_fps,
        nodes_same: bool = False,
    ) -> bool:
        """Two-phase delta: PLAN validates eligibility and computes the
        permutations/scatters without touching any state (so a bail-out
        leaves the state coherent), APPLY edits the mirrors and replays
        the identical edit on device through the donated jit.  cat_key /
        live_new / node_fps are the tick-wide invariants `refresh`
        computed once for every candidate state.  ``nodes_same`` is the
        cache's tick-window attestation that THIS state's node columns
        were already refreshed against the identical live set (same list
        object, same node identities) inside the current trust window —
        the node half of the plan is then the identity and only pod rows
        can differ (the sub-millisecond admission case)."""
        plan = self._plan(solver, pods, cat_key, live_new, node_fps,
                          nodes_same)
        if plan is None:
            return False
        self._apply(plan, pods)
        return True

    def _plan(self, solver, pods: List[Pod], cat_key, live_new, node_fps,
              nodes_same: bool = False):
        if solver.pack_fn is not self.pack_fn_ref:
            return None
        if cat_key != self.cat_key:
            return None  # catalog roll / pool mutation: full rebuild
        # ---- live nodes --------------------------------------------------
        if nodes_same:
            # tick trust window (ResidentCache.note_sync): node columns
            # are bit-identical to this state's — skip the per-node diff
            E_new = len(self.live)
            node_plan = None
        else:
            E_new = len(live_new)
            if self.fe + E_new + 1 > self.Cp:
                return None  # live-column bucket overflow
            node_plan = []  # (sn, old_pos_or_None, sched_changed, usage_changed)
            names_new = set()
            for sn, (sched_fp, usage_fp) in zip(live_new, node_fps):
                if sn.name in names_new:
                    return None  # duplicate names would alias columns
                names_new.add(sn.name)
                old = self.node_pos.get(sn.name)
                if old is None:
                    sched_ch = usage_ch = True
                else:
                    prev_sched, prev_usage = self.node_fp[sn.name]
                    sched_ch = sched_fp != prev_sched
                    usage_ch = usage_fp != prev_usage
                node_plan.append(
                    (sn, old, sched_ch, usage_ch, sched_fp, usage_fp)
                )
        # ---- pods --------------------------------------------------------
        cur_ids = set()
        adds: List[Tuple[Pod, object]] = []
        drops: List[Tuple[Pod, object]] = []
        first_occ: Dict[object, int] = {}
        for i, p in enumerate(pods):
            pid = id(p)
            if pid in cur_ids:
                return None  # duplicate pod object
            cur_ids.add(pid)
            ent = self.pod_entry.get(pid)
            mut = p.__dict__.get("_mut", 0)
            if ent is not None and ent[1] == mut:
                ck = ent[2]
            else:
                if not _plain_pod(p):
                    return None
                ck = p.class_key()
                if ent is not None:
                    drops.append((p, ent[2]))
                adds.append((p, ck))
            if ck not in first_occ:
                first_occ[ck] = i
        for pid, ent in self.pod_entry.items():
            if pid not in cur_ids:
                drops.append((ent[0], ent[2]))
        churn = len(adds) + len(drops)
        if churn > REBUILD_FRACTION * max(len(pods), 1) + 8:
            return None  # past the midpoint a full compile is cheaper
        # ---- axis stability ---------------------------------------------
        # an arriving extended resource (or the departure of the only
        # class carrying one) changes the axis set, which re-shapes every
        # tensor: full rebuild
        extra = dict(self.extra_axes)
        add_by_class: Dict[object, List[Pod]] = {}
        for p, ck in adds:
            add_by_class.setdefault(ck, []).append(p)
        drop_by_class: Dict[object, set] = {}
        for p, ck in drops:
            drop_by_class.setdefault(ck, set()).add(id(p))

        def class_extras(requests) -> list:
            return [
                ax for ax in requests.keys()
                if ax not in L.WELL_KNOWN_RESOURCES
            ]

        touched = set(add_by_class) | set(drop_by_class)
        survivors: List[Tuple[_Cls, List[Pod]]] = []  # (cls, new members)
        removed_keys = set()
        for c in self.cls:
            if c.key not in touched:
                survivors.append((c, c.cm.pods))
                continue
            dropset = drop_by_class.get(c.key, ())
            members = [p for p in c.cm.pods if id(p) not in dropset]
            members += add_by_class.pop(c.key, [])
            if members:
                survivors.append((c, members))
            else:
                removed_keys.add(c.key)
                for ax in class_extras(c.cm.requests):
                    extra[ax] -= 1
                    if extra[ax] == 0:
                        del extra[ax]
        fresh: List[Tuple[object, List[Pod]]] = []
        for ck, members in add_by_class.items():
            fresh.append((ck, members))
            for ax in class_extras(members[0].requests):
                if ax not in self.axes:
                    return None  # new axis: tensors re-shape
                extra[ax] = extra.get(ax, 0) + 1
        if set(extra) != set(self.extra_axes):
            # the axis SET must stay exactly the state's (a vanished axis
            # would make a from-scratch compile narrower than our tensors)
            return None
        G_new = len(survivors) + len(fresh)
        if G_new + 1 > self.Gp:
            return None  # class bucket overflow
        n_pods = len(pods)
        if E_new + min(n_pods, _SLOT_HEADROOM) + 1 > self.Kp:
            return None  # node-slot bucket overflow
        return dict(
            node_plan=node_plan,
            survivors=survivors,
            fresh=fresh,
            removed_keys=removed_keys,
            adds=adds,
            drops=drops,
            first_occ=first_occ,
            extra=extra,
            E_new=E_new,
        )

    def _apply(self, plan: dict, pods: List[Pod]) -> None:
        fe, Gp, Cp, Kp = self.fe, self.Gp, self.Cp, self.Kp
        first_occ = plan["first_occ"]
        # ---- new class order: exactly the from-scratch compile's -------
        # stable FFD sort over first-occurrence order == sort by the
        # (ffd key, first occurrence) pair, which is total per class
        entries: List[Tuple[tuple, int, Optional[_Cls], object, list]] = []
        old_pos = {id(c): g for g, c in enumerate(self.cls)}
        for c, members in plan["survivors"]:
            entries.append(
                (c.sort_key, first_occ[c.key], c, c.key, members)
            )
        for ck, members in plan["fresh"]:
            rep = members[0]
            cm = ClassMeta(
                pods=members,
                requests=rep.requests,
                signature=rep.constraint_signature(),
            )
            nc = _Cls(cm, ck, self.axes)
            entries.append((nc.sort_key, first_occ[ck], nc, ck, members))
        entries.sort(key=lambda e: (e[0], e[1]))

        g_perm = np.full(Gp, Gp - 1, np.int32)  # scratch = canonical pad
        class_scatter: List[int] = []
        new_cls: List[_Cls] = []
        meta_changed = False
        for gnew, (_, _, c, ck, members) in enumerate(entries):
            src = old_pos.get(id(c))
            if src is None:
                class_scatter.append(gnew)  # brand-new class
            else:
                g_perm[gnew] = src
                if len(members) != len(c.cm.pods):
                    class_scatter.append(gnew)  # count changed
            if members is not c.cm.pods:
                # REBIND a fresh ClassMeta rather than edit in place:
                # snapshots stored in the solver's compile cache share
                # these meta objects, and an in-place edit would desync a
                # cached problem's copied cnt from its class membership
                c.cm = replace(c.cm, pods=members)
                meta_changed = True
            new_cls.append(c)
        # ---- live-column order: the new snapshot's ----------------------
        node_plan = plan["node_plan"]
        E_new = plan["E_new"]
        if node_plan is None:
            # tick-window identity: same nodes, same order, same content
            c_perm = np.full(Cp, Cp - 1, np.int32)
            c_perm[: fe + E_new] = np.arange(fe + E_new, dtype=np.int32)
            k_perm = np.full(Kp, Kp - 1, np.int32)
            k_perm[:E_new] = np.arange(E_new, dtype=np.int32)
            col_scatter: List[int] = []
            used_scatter: List[int] = []
            live_new = self.live
            configs_new = self.configs_live
            identity_c = True
        else:
            c_perm = np.full(Cp, Cp - 1, np.int32)
            c_perm[:fe] = np.arange(fe, dtype=np.int32)
            k_perm = np.full(Kp, Kp - 1, np.int32)
            col_scatter = []  # NEW-order positions e
            used_scatter = []
            live_new = []
            configs_new = []
            for e, (sn, old, sched_ch, usage_ch, _, _) in enumerate(node_plan):
                if old is not None:
                    c_perm[fe + e] = fe + old
                    k_perm[e] = old
                if sched_ch:
                    col_scatter.append(e)
                if usage_ch:
                    used_scatter.append(e)
                live_new.append(sn)
                if old is not None and not sched_ch:
                    # same column, same content: when the column still
                    # wraps this very node object the wrapper is reused
                    # outright; otherwise a fresh ConfigMeta re-points
                    # `existing` — older snapshots keep the wrapper they
                    # compiled against (content-equal wrappers are
                    # interchangeable — the compile-cache doctrine), the
                    # next snapshot reads the current one
                    prev = self.configs_live[old]
                    configs_new.append(
                        prev if prev.existing is sn
                        else replace(prev, existing=sn)
                    )
                else:
                    configs_new.append(
                        ConfigMeta(
                            pool=None,
                            instance_type=None,
                            zone=sn.zone,
                            capacity_type=sn.capacity_type,
                            price=0.0,
                            existing=sn,
                        )
                    )
            identity_c = bool(
                (c_perm[fe : fe + E_new] ==
                 np.arange(fe, fe + E_new)).all()
            ) and E_new == len(self.live)
        identity_g = bool((g_perm[: len(entries)] ==
                           np.arange(len(entries))).all()) and len(
            entries
        ) == len(self.cls)
        # ---- host mirror: permutations ----------------------------------
        if not (identity_g and identity_c):
            self.h_req = self.h_req[g_perm]
            self.h_cnt = self.h_cnt[g_perm]
            self.h_feas = self.h_feas[g_perm][:, c_perm]
            self.h_alloc = self.h_alloc[c_perm]
            self.h_price = self.h_price[c_perm]
            self.h_used0 = self.h_used0[k_perm]
            self.h_npods0 = self.h_npods0[k_perm]
        G_new = len(entries)
        # ---- host mirror: class-row scatters ----------------------------
        catalog = self.catalog
        for gnew in class_scatter:
            c = new_cls[gnew]
            cm = c.cm
            self.h_req[gnew] = c.req_vec
            self.h_cnt[gnew] = len(cm.pods)
            if g_perm[gnew] == Gp - 1:  # brand-new: assemble the full row
                rep = cm.pods[0]
                open_row = open_config_row(
                    catalog, rep, cm.signature, self.pools_by_name
                )
                open_row = restrict_open_tier(catalog, open_row, c.req_vec)
                row = np.zeros(Cp, bool)
                row[:fe] = open_row
                for e, sn in enumerate(live_new):
                    row[fe + e] = _fits_existing(rep, c.sched, sn)
                self.h_feas[gnew] = row
        # ---- host mirror: live-column scatters --------------------------
        for e in col_scatter:
            sn = live_new[e]
            col = fe + e
            self.h_alloc[col] = _vec(sn.allocatable, self.axes)
            self.h_price[col] = 0.0
            for g in range(G_new):
                self.h_feas[g, col] = _fits_existing(
                    new_cls[g].cm.pods[0], new_cls[g].sched, sn
                )
            self.h_feas[G_new:, col] = False
        for e in used_scatter:
            sn = live_new[e]
            self.h_used0[e] = _vec(sn.used, self.axes)
            self.h_npods0[e] = len(sn.pods)
        # ---- device: one donated gather+scatter step --------------------
        n_delta = len(class_scatter) + len(col_scatter) + len(used_scatter)
        if n_delta or not (identity_g and identity_c):
            g_idx = _pad_idx(class_scatter, Gp - 1)
            col_idx = _pad_idx([fe + e for e in col_scatter], Cp - 1)
            k_idx = _pad_idx(used_scatter, Kp - 1)
            fn = _delta_fn(self.mesh)
            import warnings

            with warnings.catch_warnings():
                # CPU XLA occasionally declines a donation; the fallback
                # is a copy, not an error — keep the log surface quiet
                warnings.filterwarnings(
                    "ignore", message=".*donated.*", category=UserWarning
                )
                # the counted seam attributes the scatter-payload upload
                # (the permutations + changed rows/cols — the ONLY host
                # arrays here; the seven buffers are device-resident and
                # transfer nothing)
                (
                    self.d_req, self.d_cnt, self.d_feas, self.d_alloc,
                    self.d_price, self.d_used0, self.d_npods0, self.d_cfg0,
                ) = OBSERVATORY.dispatch(
                    "resident_delta", fn,
                    self.d_req, self.d_cnt, self.d_feas, self.d_alloc,
                    self.d_price, self.d_used0, self.d_npods0,
                    g_perm, c_perm, k_perm,
                    g_idx, self.h_req[g_idx], self.h_cnt[g_idx],
                    self.h_feas[g_idx],
                    col_idx, self.h_alloc[col_idx], self.h_price[col_idx],
                    self.h_feas[:, col_idx],
                    k_idx, self.h_used0[k_idx], self.h_npods0[k_idx],
                    np.int32(E_new), np.int32(fe),
                )
            OBSERVATORY.count_resident_update("donated")
        else:
            OBSERVATORY.count_resident_update("noop")
        # ---- bookkeeping -------------------------------------------------
        self.cls = new_cls
        self.slot_of = {c.key: g for g, c in enumerate(new_cls)}
        for p, ck in plan["drops"]:
            self.pod_entry.pop(id(p), None)
        for p, ck in plan["adds"]:
            self.pod_entry[id(p)] = (p, p.__dict__.get("_mut", 0), ck)
        self.extra_axes = plan["extra"]
        if node_plan is not None:
            self.live = live_new
            self.configs_live = configs_new
            self.node_pos = {sn.name: e for e, sn in enumerate(live_new)}
            self.node_fp = {
                sn.name: (fp_s, fp_u)
                for (sn, _, _, _, fp_s, fp_u) in node_plan
            }
        self.last_delta_rows = n_delta
        # meta_changed alone (an equal-count membership swap) produces no
        # tensor delta but DOES change which pod objects decode assigns —
        # the snapshot must refresh for it too
        self.prob = self._snapshot() if meta_changed or n_delta or not (
            identity_g and identity_c
        ) else self.prob

    # ----------------------------------------------------------- snapshot
    def _snapshot(self) -> CompiledProblem:
        """A CompiledProblem over COPIES of the unpadded mirror regions —
        decode (and its lazy widen thunks) must never alias mirrors a
        later delta will edit in place."""
        G = len(self.cls)
        E = len(self.live)
        C = self.fe + E
        return CompiledProblem(
            axes=self.axes,
            classes=[c.cm for c in self.cls],
            configs=list(self.catalog.configs) + list(self.configs_live),
            req=self.h_req[:G].copy(),
            cnt=self.h_cnt[:G].copy(),
            maxper=np.full(G, BIG, np.int32),
            slot=np.zeros(G, np.int32),
            alloc=self.h_alloc[:C].copy(),
            price=self.h_price[:C].copy(),
            openable=self.h_openable[:C].copy(),
            feas=self.h_feas[:G, :C].copy(),
            pool_daemon_overhead=self.catalog.pool_overhead,
            used0=self.h_used0[:E].copy(),
            cfg0=np.arange(self.fe, self.fe + E, dtype=np.int32),
            npods0=self.h_npods0[:E].copy(),
            sig_used0=np.zeros((1, E), np.int32),
            n_track_slots=1,
        )

    def problem(self) -> CompiledProblem:
        if self.prob is None:
            self.prob = self._snapshot()
        return self.prob

    def groups(self) -> list:
        """partition_groups-shaped (key, members) list for the solver's
        compile-cache entry (consumed only for re-storage; resident
        batches never have an oracle half)."""
        return [
            ((c.cm.signature, c.cm.requests), list(c.cm.pods))
            for c in self.cls
        ]

    # ---------------------------------------------------------------- pack
    @property
    def pack(self):
        """A pack_fn over the RESIDENT buffers: zero per-solve upload (the
        tensors are already on device; only the scalar slot cursor
        travels).  An explicit k_slots (the solver's overflow retry, or a
        caller sizing its own padding) falls back to the ordinary upload
        path over the snapshot problem."""
        fn = self.__dict__.get("_pack_fn")
        if fn is None:

            def pack(prob, k_slots: int = 0, objective: str = "nodes"):
                if k_slots and k_slots != self.Kp:
                    return self._fallback_pack(prob, k_slots, objective)
                return self._device_pack(objective)

            pack.kernel_name = (
                "scan-sharded" if self.mesh is not None else "scan"
            )
            pack.resident = True
            fn = self.__dict__["_pack_fn"] = pack
        return fn

    def _device_pack(self, objective: str) -> PackResult:
        E = np.int32(len(self.live))
        if self.mesh is not None:
            from karpenter_tpu.parallel.mesh import _sharded_pack

            fn = _sharded_pack(self.mesh, self.Kp, objective)
            return OBSERVATORY.dispatch(
                "mesh_pack", fn,
                self.d_req, self.d_cnt, self.d_maxper, self.d_slot,
                self.d_feas, self.d_alloc, self.d_price, self.d_openable,
                self.d_used0, self.d_cfg0, self.d_npods0, E, self.d_sig0,
            )
        from karpenter_tpu.ops.packer import pack_kernel

        return OBSERVATORY.dispatch(
            "pack_kernel", pack_kernel,
            self.d_req, self.d_cnt, self.d_maxper, self.d_slot,
            self.d_feas, self.d_alloc, self.d_price, self.d_openable,
            self.d_used0, self.d_cfg0, self.d_npods0, E, self.d_sig0,
            k_slots=self.Kp, objective=objective,
        )

    def _fallback_pack(self, prob, k_slots: int, objective: str):
        if self.mesh is not None:
            from karpenter_tpu.parallel.mesh import mesh_pack_fn

            return mesh_pack_fn(self.mesh)(prob, k_slots, objective)
        from karpenter_tpu.ops.packer import run_pack

        return run_pack(prob, k_slots, objective)


# distinguishes "caller did not pass a window" from "caller validated and
# found no window" in ResidentCache.refresh
_WIN_UNSET = object()


class ResidentCache:
    """A small LRU of resident states (the provisioner's pending set and
    the deprovisioner's repack/base universes alternate on one scheduler;
    two slots keep both warm without letting device buffers accumulate)."""

    CAP = 2

    def __init__(self):
        self.states: List[ResidentState] = []
        # open tick trust window (note_sync): (witness, token, carrier_ok,
        # cat_key, live_new, node_fps) — or None
        self._tick = None

    def note_sync(self, solver) -> None:
        """Open a tick trust window: compute the tick-wide invariants
        (carrier scan, live filter, per-node fingerprints, catalog key)
        ONCE for the solver's current ``existing`` snapshot, so every
        refresh inside the window — each admission of a trickle, every
        candidate state — skips the O(cluster) rescan.  The caller's
        contract (Provisioner._sync_scheduler; the bench harness) is
        that ``existing`` and its nodes are NOT mutated inside the
        window; re-sync after any mutation.  The window self-invalidates
        when the node set changes (the witness below: a saved reference
        list compared with ``==``, which CPython resolves per element by
        identity first — C speed for the all-same case — and by field
        value otherwise, so a swapped-in node invalidates unless it is
        field-for-field equal, in which case every cached invariant is
        equal too).  Raw solver callers that never note_sync keep the
        rigorous per-call scan — including in-place node mutation
        detection, which tests/test_resident_fuzz.py pins."""
        witness = (id(solver), list(solver.existing))
        carrier_ok = _carrier_free(solver.existing)
        live_new = live_filter(solver.existing)
        node_fps = [
            (_node_sched_fp(sn), _node_usage_fp(sn)) for sn in live_new
        ]
        self._tick = (
            witness, object(), carrier_ok, _catalog_key(solver),
            live_new, node_fps,
        )

    def _window(self, solver):
        """The open trust window's payload when its witness still matches
        this solver's existing snapshot, else None."""
        t = self._tick
        if (
            t is not None
            and t[0][0] == id(solver)
            and t[0][1] == solver.existing
        ):
            return t
        return None

    def carrier_free(self, solver) -> bool:
        t = self._window(solver)
        if t is not None:
            return t[2]
        return _carrier_free(solver.existing)

    def catalog_key(self, solver):
        t = self._window(solver)
        if t is not None:
            return t[3]
        return _catalog_key(solver)

    def refresh(
        self, solver, pods: List[Pod], _win=_WIN_UNSET
    ) -> Optional[ResidentState]:
        """Delta-update the first state that can absorb this tick's diff;
        None when every state misses (the caller runs the full compile
        and seeds a state via `rebuild`).  ``_win`` lets a caller that
        already validated the trust window this call (fastpath.try_admit)
        hand it over instead of paying the witness build again."""
        if not self.states:
            return None
        # tick-wide invariants — identical for every candidate state, so
        # the O(existing bound pods) carrier scan and the per-live-node
        # fingerprint tuples are built once per call, not once per slot
        # (and, under an open trust window, once per TICK)
        win = self._window(solver) if _win is _WIN_UNSET else _win
        if win is not None:
            _, token, carrier_ok, cat_key, live_new, node_fps = win
        else:
            token = None
            carrier_ok = _carrier_free(solver.existing)
            cat_key = _catalog_key(solver)
            live_new = live_filter(solver.existing)
            node_fps = [
                (_node_sched_fp(sn), _node_usage_fp(sn)) for sn in live_new
            ]
        if not carrier_ok:
            # a carrier appeared — possibly on a NON-live node the live
            # filter hides (a cordoned node's bound anti term still
            # repels batch pods in the full compile's partition)
            return None
        for st in list(self.states):
            nodes_same = (
                token is not None
                and st.__dict__.get("_tick_token") is token
            )
            if st.try_refresh(
                solver, pods, cat_key, live_new, node_fps, nodes_same
            ):
                st.__dict__["_tick_token"] = token
                self.states.remove(st)
                self.states.append(st)  # most-recently-used last
                return st
        return None

    def rebuild(
        self, solver, pods: List[Pod], prob: CompiledProblem, catalog,
        consumer: str = "solve",
    ) -> Optional[ResidentState]:
        if catalog is None or not resident_capable(solver.pack_fn):
            return None
        with phase("delta"):
            st = ResidentState.build(
                solver, pods, prob, catalog, consumer=consumer
            )
        if st is None:
            return None
        while len(self.states) >= self.CAP:
            self.states.pop(0)
        self.states.append(st)
        self._report_footprint()
        return st

    def footprint(self) -> Dict[str, int]:
        """Live device-buffer bytes per consumer across the cache's
        states — the karpenter_device_resident_bytes{consumer} truth."""
        out: Dict[str, int] = {}
        for st in self.states:
            out[st.consumer] = out.get(st.consumer, 0) + st.device_bytes()
        return out

    def _report_footprint(self) -> None:
        OBSERVATORY.set_resident_footprint(self, self.footprint())

    def match(self, prob: CompiledProblem, pack_fn=None) -> Optional[ResidentState]:
        """The state whose CURRENT snapshot is exactly `prob` (identity):
        a compile-cache hit re-serving that snapshot may pack straight
        from the resident buffers with no delta at all.  ``pack_fn``
        fences against a backend swap between ticks — a state built for
        one backend must not serve another's solve."""
        for st in self.states:
            if st.prob is prob and (
                pack_fn is None or st.pack_fn_ref is pack_fn
            ):
                return st
        return None


# ---------------------------------------------------------------------------
# Tenant-keyed resident pool (docs/designs/solver-service.md)
#
# ResidentCache above keeps TWO states warm for ONE operator.  The
# multi-tenant SolverService generalizes the same discipline across a fleet:
# each tenant's upload-heavy solve tensors stay device-resident between its
# solves, keyed by CONTENT fingerprint (the wire arrays are fresh numpy
# objects every RPC, so identity keys — the in-process caches' trick — can
# never hit).  A global device-bytes budget bounds the accelerator footprint;
# crossing it evicts whole tenants least-recently-used first, never a tenant
# currently being served.
# ---------------------------------------------------------------------------


def _content_fp(arr: np.ndarray) -> tuple:
    """Content fingerprint of a wire array: shape + dtype + payload hash.
    sha1 over the raw bytes — collision-safe at cache-key strength, and
    cheap next to the device upload it saves."""
    import hashlib

    arr = np.ascontiguousarray(arr)
    return (
        arr.shape,
        arr.dtype.str,
        hashlib.sha1(arr.tobytes()).digest(),
    )


class _TenantEntry:
    """One tenant's resident arrays: name -> (fingerprint, device array,
    nbytes).  The pinned numpy source is NOT kept — the fingerprint is
    content-based, so a re-sent identical array hits without it."""

    __slots__ = ("arrays", "nbytes")

    def __init__(self):
        self.arrays: Dict[str, tuple] = {}
        self.nbytes = 0


class TenantResidentPool:
    """Device-resident per-tenant array cache with a global bytes budget.

    ``get(tenant, name, arr)`` returns a device array for ``arr``: a
    fingerprint hit reuses the resident buffer (zero transfer), a miss
    uploads through the counted seam and replaces the tenant's entry for
    ``name``.  ``budget_bytes <= 0`` disables caching entirely (every get
    returns the host array untouched — the legacy single-tenant upload
    path).  Eviction is tenant-granular LRU: python dicts iterate in
    insertion order and hits re-insert, the same discipline as
    cached_device_put.  NOT thread-safe — the service serializes access
    under its own admission lock.
    """

    def __init__(self, budget_bytes: int, site: str = "tenant_resident"):
        self.budget_bytes = int(budget_bytes)
        self.site = site
        self.tenants: Dict[str, _TenantEntry] = {}
        # lifetime counters the service exports per tenant
        self.hits = 0
        self.misses = 0
        self.evictions: List[str] = []  # evicted tenant names, in order

    # ------------------------------------------------------------- access
    def get(self, tenant: str, name: str, arr: np.ndarray):
        if self.budget_bytes <= 0:
            return arr
        ent = self.tenants.get(tenant)
        if ent is None:
            ent = self.tenants[tenant] = _TenantEntry()
        else:
            # mark most-recently-used (insertion-order LRU)
            del self.tenants[tenant]
            self.tenants[tenant] = ent
        fp = _content_fp(arr)
        cached = ent.arrays.get(name)
        if cached is not None and cached[0] == fp:
            self.hits += 1
            return cached[1]
        self.misses += 1
        dev = OBSERVATORY.put(self.site, np.ascontiguousarray(arr))
        nbytes = int(dev.nbytes)
        if cached is not None:
            ent.nbytes -= cached[2]
        ent.arrays[name] = (fp, dev, nbytes)
        ent.nbytes += nbytes
        self.evict_to_budget(active={tenant})
        return dev

    # ----------------------------------------------------------- eviction
    def total_bytes(self) -> int:
        return sum(e.nbytes for e in self.tenants.values())

    def bytes_of(self, tenant: str) -> int:
        ent = self.tenants.get(tenant)
        return ent.nbytes if ent is not None else 0

    def evict_to_budget(self, active=()) -> List[str]:
        """Drop least-recently-used tenants until the pool fits the
        budget; tenants in ``active`` (currently being served) are never
        dropped, so a single oversized tenant can transiently exceed the
        budget rather than thrash its own working set mid-solve.  Returns
        the tenant names evicted by THIS call (also appended to
        ``self.evictions`` for the service's counters)."""
        dropped: List[str] = []
        while self.total_bytes() > self.budget_bytes:
            victim = next(
                (t for t in self.tenants if t not in active), None
            )
            if victim is None:
                break
            del self.tenants[victim]
            dropped.append(victim)
        self.evictions.extend(dropped)
        return dropped

    def drop(self, tenant: str) -> None:
        self.tenants.pop(tenant, None)

    # ---------------------------------------------------------- reporting
    def footprint(self) -> Dict[str, int]:
        """Per-tenant resident bytes — the karpenter_service_resident_bytes
        truth, and the consumer-labeled observatory report."""
        return {t: e.nbytes for t, e in self.tenants.items()}

    def report_footprint(self) -> None:
        OBSERVATORY.set_resident_footprint(
            self, {f"tenant:{t}": b for t, b in self.footprint().items()}
        )
