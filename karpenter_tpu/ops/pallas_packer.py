"""Pallas TPU packing kernel: the whole FFD scan fused into one kernel.

The `lax.scan` kernel (ops/packer.py) materializes its carry through XLA
loop machinery every class step.  This kernel keeps ALL solver state
resident in VMEM scratch across a sequential grid over classes — residual
capacities, config commitments, per-signature admissions, per-signature
placement counters — so each step is pure VPU/MXU work with zero HBM
round-trips for state.

TPU-shaped reformulations (the axon Mosaic lowering has no cumsum and no
vector gather, and silently miscompiles take_along_axis):

- **first-fit prefix allocation** = exclusive prefix-sum over the flat
  (KR, 128) slot grid, computed as two triangular-mask matmuls on the MXU
  at ``Precision.HIGHEST`` (exact for integer counts < 2^24).
- **per-slot feasibility without gather**: ``feas[g, cfg[k]]`` would need
  a vector gather.  Instead the kernel carries ``sig_ok[s, k]`` — does a
  pod of signature s fit slot k's committed config — seeded from the
  signature x config admission table when a slot opens (a masked
  broadcast, not a gather) and read back per class by a dynamic row index.
  This caps the supported signature count at S_MAX; wider problems use
  the scan kernel (scheduling/solver.py dispatches).
- **argmin over configs** = min + first-match-index via masked flat iota;
  the chosen config's column (allocatable vector, admission column) is
  extracted with one-hot masked reductions — again no gather.

Semantics match `pack_kernel` exactly; tests/test_pallas.py asserts
bit-equality of placements on shared problems.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_tpu.ops.packer import (
    PackResult,
    _bucket,
    cached_device_put,
    compact_take,
    expand_take,
    node_slot_bound,
)
from karpenter_tpu.ops.tensorize import CompiledProblem
from karpenter_tpu.utils.trace import phase

# max distinct (signature, zone-pin) rows the VMEM state holds.  The
# budget: sigfeas (S, C/128, 128) f32 + sig_ok (S, K/128, 128) f32 must fit
# VMEM next to the residual state; at C=4096, K=1024 that is 4 MiB + 1 MiB
# at S=256 — comfortably inside a v5e core's 16 MiB.  The update is a
# masked broadcast over the whole S axis (no per-row loop), so raising this
# costs VMEM, not compile time.
S_MAX = 256
T_MAX = 64  # max tracked anti-affinity counter rows
R_FIX = 8  # fixed resource-axis width (padded)
LANES = 128
BIGF = float(2**30)
BIGI = 2**30


def _flat_iota(rows: int) -> jax.Array:
    return (
        jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0) * LANES
        + jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1)
    )


def _exclusive_prefix(x: jax.Array) -> jax.Array:
    """Exclusive prefix-sum in flat row-major order over (rows, 128).

    Two triangular matmuls on the MXU: intra-row prefix + row offsets.
    HIGHEST precision keeps integer-valued f32 exact (counts < 2^24).
    """
    rows = x.shape[0]
    li = jax.lax.broadcasted_iota(jnp.int32, (LANES, LANES), 0)
    lj = jax.lax.broadcasted_iota(jnp.int32, (LANES, LANES), 1)
    upper = (li < lj).astype(jnp.float32)
    intra = jax.lax.dot_general(
        x, upper, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    rowsum = jnp.sum(x, axis=1, keepdims=True)
    ri = jax.lax.broadcasted_iota(jnp.int32, (rows, rows), 0)
    rj = jax.lax.broadcasted_iota(jnp.int32, (rows, rows), 1)
    lower = (rj < ri).astype(jnp.float32)
    roff = jax.lax.dot_general(
        lower, rowsum, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    return intra + roff


def _pack_step(
    # scalar-prefetch args (SMEM, full arrays indexed by program id)
    cnt_ref, maxper_ref, slot_ref, sig_ref, reqf_ref, next0_ref,
    # resident tables
    sigfeas_ref, alloc_ref, price_ref, open_ref,
    # initial state
    rem0_ref, cfg0_ref, npods0_ref, sigok0_ref, trk0_ref,
    # outputs
    take_ref, cfg_out_ref, npods_out_ref, rem_out_ref,
    # scratch state
    rem_s, cfg_s, npods_s, sigok_s, trk_s, nxt_s,
    *, objective: str, n_steps: int,
):
    g = pl.program_id(0)

    @pl.when(g == 0)
    def _init():
        rem_s[:] = rem0_ref[:]
        cfg_s[:] = cfg0_ref[:]
        npods_s[:] = npods0_ref[:]
        sigok_s[:] = sigok0_ref[:]
        trk_s[:] = trk0_ref[:]
        nxt_s[0] = next0_ref[0]

    kr = rem_s.shape[1]
    cr = alloc_ref.shape[1]
    n = cnt_ref[g].astype(jnp.float32)
    maxper = maxper_ref[g].astype(jnp.float32)
    tslot = slot_ref[g]
    srow = sig_ref[g]
    req = [reqf_ref[g * R_FIX + r] for r in range(R_FIX)]
    # the class's config-admission row IS its signature's row (classes of a
    # signature share the feasibility row by construction), so the kernel
    # reads sigfeas instead of a per-class [G, C] input — that input was
    # the largest host->device upload of the whole solve
    feas_g = sigfeas_ref[pl.ds(srow, 1)][0]  # (CR, 128)

    # ---- fill open slots (first-fit in slot order) ----------------------
    ok = sigok_s[pl.ds(srow, 1)][0]  # (KR, 128)
    cap = jnp.full((kr, LANES), BIGF)
    for r in range(R_FIX):
        per_r = jnp.floor(rem_s[r] / jnp.maximum(req[r], 1e-9) + 1e-4)
        cap = jnp.where(req[r] > 0, jnp.minimum(cap, per_r), cap)
    trk_row = trk_s[pl.ds(tslot, 1)][0].astype(jnp.float32)
    cap = jnp.minimum(cap, jnp.maximum(maxper - trk_row, 0.0))
    cap = jnp.where(ok > 0, jnp.maximum(cap, 0.0), 0.0)
    prefix = _exclusive_prefix(cap)
    take1 = jnp.clip(n - prefix, 0.0, cap)
    n2 = n - jnp.sum(take1)

    # ---- open new slots on the best config ------------------------------
    capc = jnp.full((cr, LANES), BIGF)
    for r in range(R_FIX):
        per_r = jnp.floor(alloc_ref[r] / jnp.maximum(req[r], 1e-9) + 1e-4)
        capc = jnp.where(req[r] > 0, jnp.minimum(capc, per_r), capc)
    capc = jnp.minimum(jnp.maximum(capc, 0.0), maxper)
    okc = (feas_g > 0) & (open_ref[:] > 0) & (capc > 0)
    if objective == "cost":
        score = jnp.where(okc, price_ref[:] / jnp.maximum(capc, 1.0), BIGF)
    else:
        score = jnp.where(okc, -capc + price_ref[:], BIGF)
    smin = jnp.min(score)
    feasible_new = smin < BIGF * 0.5
    ciota = _flat_iota(cr)
    c_star = jnp.min(jnp.where(score == smin, ciota, BIGI))
    sel = (ciota == c_star).astype(jnp.float32)
    per = jnp.sum(sel * capc)
    per_safe = jnp.maximum(per, 1.0)
    need = jnp.where(feasible_new, jnp.ceil(n2 / per_safe), 0.0)
    nxt = nxt_s[0]
    slots_left = (kr * LANES - nxt).astype(jnp.float32)
    opened = jnp.minimum(need, jnp.maximum(slots_left, 0.0))
    kiota = _flat_iota(kr)
    wmask = (kiota >= nxt) & (kiota < nxt + opened.astype(jnp.int32))
    offset = (kiota - nxt).astype(jnp.float32) * per_safe
    take2 = jnp.where(wmask, jnp.clip(n2 - offset, 0.0, per_safe), 0.0)
    # f32 ceil-division can overshoot an exact quotient by one slot; a
    # phantom zero-take slot would silently shift every later slot index
    # away from the scan kernel's (which ceil-divides in exact ints).
    # Masking on take2>0 makes the opened window exact.
    wmask = wmask & (take2 > 0)
    opened = jnp.sum(wmask.astype(jnp.float32))
    take = take1 + take2

    # ---- state updates --------------------------------------------------
    for r in range(R_FIX):
        alloc_star_r = jnp.sum(sel * alloc_ref[r])
        rem_s[r] = jnp.where(wmask, alloc_star_r, rem_s[r]) - take * req[r]
    cfg_s[:] = jnp.where(wmask, c_star, cfg_s[:])
    take_i = take.astype(jnp.int32)
    npods_s[:] = npods_s[:] + take_i
    trk_s[pl.ds(tslot, 1)] = trk_s[pl.ds(tslot, 1)] + take_i[None]
    # newly-opened slots adopt config c_star's admission column for EVERY
    # signature at once: extract column c_star of sigfeas via the one-hot
    # `sel` reduction, then a masked broadcast over (S, K) — no per-row
    # loop, so the signature capacity S_MAX is a VMEM budget, not a compile
    # budget.  All intermediates stay >=2-D (Mosaic's layout inference
    # aborts on 1-D reshapes of 3-D reductions).
    sig_col = jnp.sum(
        jnp.sum(sigfeas_ref[:] * sel[None], axis=2), axis=1, keepdims=True
    )  # (S, 1)
    sigok_s[:] = jnp.where(
        wmask[None], sig_col[:, :, None], sigok_s[:]
    )
    nxt_s[0] = nxt + opened.astype(jnp.int32)

    take_ref[0] = take_i

    @pl.when(g == n_steps - 1)
    def _finalize():
        cfg_out_ref[:] = cfg_s[:]
        npods_out_ref[:] = npods_s[:]
        rem_out_ref[:] = rem_s[:]


# deferred import so module import never initializes a backend
from jax.experimental import pallas as pl  # noqa: E402
from jax.experimental.pallas import tpu as pltpu  # noqa: E402


@functools.partial(
    jax.jit, static_argnames=("g_steps", "kr", "cr", "s8", "t8", "objective", "interpret")
)
def _pallas_pack(
    req, cnt, maxper, slot, sig, sigfeas_packed, alloc_t, price_n, openable,
    rem0, cfg0, npods0, sigok0, trk0, next0,
    *, g_steps: int, kr: int, cr: int, s8: int, t8: int, objective: str,
    interpret: bool,
):
    # sigfeas ships bit-packed (32x smaller upload than f32) and unpacks
    # on device with plain XLA ops before the pallas launch
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (sigfeas_packed[:, :, None] >> shifts) & jnp.uint8(1)
    sigfeas = bits.reshape(s8, cr, LANES).astype(jnp.float32)
    kernel = functools.partial(
        _pack_step, objective=objective, n_steps=g_steps
    )
    full = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,  # cnt, maxper, slot, sig, req_flat, next0
        grid=(g_steps,),
        in_specs=[
            full(),  # sigfeas
            full(),  # alloc_t
            full(),  # price_n
            full(),  # openable
            full(),  # rem0
            full(),  # cfg0
            full(),  # npods0
            full(),  # sigok0
            full(),  # trk0
        ],
        out_specs=[
            pl.BlockSpec((1, kr, LANES), lambda g, *_: (g, 0, 0)),  # take
            pl.BlockSpec((kr, LANES), lambda g, *_: (0, 0)),  # cfg_out
            pl.BlockSpec((kr, LANES), lambda g, *_: (0, 0)),  # npods_out
            pl.BlockSpec((R_FIX, kr, LANES), lambda g, *_: (0, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((R_FIX, kr, LANES), jnp.float32),  # rem
            pltpu.VMEM((kr, LANES), jnp.int32),  # cfg
            pltpu.VMEM((kr, LANES), jnp.int32),  # npods
            pltpu.VMEM((s8, kr, LANES), jnp.float32),  # sig_ok
            pltpu.VMEM((t8, kr, LANES), jnp.int32),  # trk counts
            pltpu.SMEM((1,), jnp.int32),  # next slot
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((g_steps, kr, LANES), jnp.int32),
            jax.ShapeDtypeStruct((kr, LANES), jnp.int32),
            jax.ShapeDtypeStruct((kr, LANES), jnp.int32),
            jax.ShapeDtypeStruct((R_FIX, kr, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(
        cnt, maxper, slot, sig, req.reshape(-1), next0,
        sigfeas, alloc_t, price_n, openable,
        rem0, cfg0, npods0, sigok0, trk0,
    )
    # sparse compaction of the take matrix on device (ops.packer
    # compact_take): the dense [G, K] int32 output is the solve's largest
    # device->host transfer.  The dense array is still returned un-fetched
    # for the rare overflow fallback.
    take_dense = out[0]
    vals, idx, nnz = compact_take(take_dense)
    return take_dense, vals, idx, nnz, out[1], out[2], out[3]


def supports(prob: CompiledProblem) -> bool:
    """Whether the VMEM-resident formulation fits this problem."""
    return (
        prob.supported
        and len(prob.axes) <= R_FIX
        and _n_signatures(prob) <= S_MAX
        and prob.n_track_slots <= T_MAX
    )


def _sig_key(prob: CompiledProblem, gidx: int) -> Tuple:
    """Admission-row key for a class: the (signature, zone_pin) pair PLUS
    the feasibility row content.  Classes of one signature usually share
    their row, but the pool-weight priority pass restricts feas per class
    by request size, and compile-time-infeasible classes carry all-zero
    rows — collapsing those onto one signature row would let the kernel
    open/fill configs the class may not use."""
    cm = prob.classes[gidx]
    return (cm.signature, cm.zone_pin, prob.feas[gidx].tobytes())


def _n_signatures(prob: CompiledProblem) -> int:
    return len({_sig_key(prob, g) for g in range(len(prob.classes))}) or 1


# device-resident (alloc_t, price_n, openable) per catalog snapshot
_PALLAS_CONST_CACHE: dict = {}


def _pallas_device_constants(prob: CompiledProblem, cr: int, R: int):
    def build():
        C = len(prob.price)
        alloc_t = np.zeros((R_FIX, cr, LANES), np.float32)
        alloc_t.reshape(R_FIX, -1)[:R, :C] = prob.alloc.T
        finite = prob.price[np.isfinite(prob.price)]
        ceil = float(finite.max()) + 1.0 if finite.size else 1.0
        price_n = np.full((cr, LANES), BIGF, np.float32)
        price_n.reshape(-1)[:C] = np.where(
            np.isfinite(prob.price), prob.price / ceil, np.float32(BIGF)
        )
        openable = np.zeros((cr, LANES), np.float32)
        openable.reshape(-1)[:C] = prob.openable.astype(np.float32)
        return alloc_t, price_n, openable

    return cached_device_put(
        _PALLAS_CONST_CACHE,
        (prob.alloc, prob.price, prob.openable),
        (cr,),
        build,
        site="pallas_constants",
    )


def run_pack_pallas(
    prob: CompiledProblem, k_slots: int = 0, objective: str = "nodes",
    interpret: bool | None = None,
) -> PackResult:
    """Drop-in for run_pack via the fused Pallas kernel.

    ``interpret`` defaults to True off-TPU (tests on the virtual CPU mesh
    run the same kernel through the Pallas interpreter)."""
    out, ctx = dispatch_pack_pallas(prob, k_slots, objective, interpret)
    return finish_pack_pallas(out, ctx)


def dispatch_pack_pallas(
    prob: CompiledProblem, k_slots: int = 0, objective: str = "nodes",
    interpret: bool | None = None,
):
    """ENQUEUE one fused-kernel solve and return (device outputs, host
    context) without synchronizing — `finish_pack_pallas` performs the
    one fetch.  Split out so the bench can chain dispatches back-to-back
    and measure the marginal per-solve cost with the link round trip
    amortized away (the in-function `jax.device_get` of the plain entry
    would otherwise serialize a round trip per call)."""
    if not supports(prob):
        raise ValueError(
            "problem exceeds the Pallas formulation "
            f"(signatures={_n_signatures(prob)}>{S_MAX} or axes>{R_FIX}); "
            "use ops.packer.run_pack"
        )
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    with phase("pad"):
        pos, statics, ctx = _pad_pallas(prob, k_slots)
    from karpenter_tpu.obs.device import OBSERVATORY

    out = OBSERVATORY.dispatch(
        "pallas_pack", _pallas_pack, *pos,
        objective=objective, interpret=interpret, **statics,
    )
    return out, ctx


def _pad_pallas(prob: CompiledProblem, k_slots: int):
    """Host-side padding/bit-packing for one fused-kernel dispatch
    (recorded as the solve's `pad` phase).  Returns the positional kernel
    arguments, the static shape kwargs, and the finish context."""
    G, C = prob.feas.shape
    R = prob.req.shape[1] if prob.req.size else len(prob.axes)
    if k_slots <= 0:
        k_slots = node_slot_bound(prob)
    Gp = _bucket(max(G, 1))
    Cp = max(_bucket(max(C, 1)), 8 * LANES)
    Kp = max(_bucket(max(k_slots, 1)), 8 * LANES)
    kr, cr = Kp // LANES, Cp // LANES
    E = len(prob.used0)

    # signature rows: map each class to its admission row (see _sig_key —
    # feas-row content is part of the key, so every class's row is exact)
    sig_keys = {}
    sig_of = np.zeros(Gp, np.int32)
    sig_first_class = {}
    for gidx in range(G):
        key = _sig_key(prob, gidx)
        srow = sig_keys.setdefault(key, len(sig_keys))
        sig_of[gidx] = srow
        sig_first_class.setdefault(srow, gidx)
    s8 = max(_bucket(max(len(sig_keys), 1), floor=8), 8)
    t8 = max(_bucket(max(prob.n_track_slots, 1), floor=8), 8)

    req = np.zeros((Gp, R_FIX), np.float32)
    req[:G, :R] = prob.req
    cnt = np.zeros(Gp, np.int32)
    cnt[:G] = prob.cnt
    maxper = np.zeros(Gp, np.int32)
    maxper[:G] = np.minimum(prob.maxper, 2**20)
    slot = np.zeros(Gp, np.int32)
    slot[:G] = prob.slot
    # signature x config admission (class rows of one signature are equal),
    # shipped bit-packed: the f32 per-class admission inputs were ~12 MB of
    # host->device upload per solve — pure latency on a tunneled device
    sigfeas_rows = np.zeros((s8, cr * LANES), bool)
    for gidx in range(G):
        sigfeas_rows[sig_of[gidx], :C] = prob.feas[gidx]
    sigfeas_packed = np.packbits(sigfeas_rows, axis=1, bitorder="little")
    alloc_t, price_n, openable = _pallas_device_constants(prob, cr, R)

    rem0 = np.zeros((R_FIX, kr, LANES), np.float32)
    cfg0 = np.full((kr, LANES), -1, np.int32)
    npods0 = np.zeros((kr, LANES), np.int32)
    sigok0 = np.zeros((s8, kr, LANES), np.float32)
    trk0 = np.zeros((t8, kr, LANES), np.int32)
    if E:
        # existing nodes: remaining capacity + per-signature admission
        rem_e = (prob.alloc[prob.cfg0] - prob.used0).astype(np.float32)  # [E,R]
        rem0.reshape(R_FIX, -1)[:R, :E] = rem_e.T
        cfg0.reshape(-1)[:E] = prob.cfg0
        npods0.reshape(-1)[:E] = prob.npods0
        for srow, gidx in sig_first_class.items():
            sigok0[srow].reshape(-1)[:E] = prob.feas[
                gidx, len(prob.configs) - E :
            ].astype(np.float32)
        trk0.reshape(t8, -1)[: prob.sig_used0.shape[0], :E] = prob.sig_used0

    pos = (
        req, cnt, maxper, slot, sig_of, sigfeas_packed, alloc_t, price_n,
        openable, rem0, cfg0, npods0, sigok0, trk0,
        np.array([E], np.int32),
    )
    statics = dict(g_steps=Gp, kr=kr, cr=cr, s8=s8, t8=t8)
    return pos, statics, (prob, cnt, Gp, Kp, R)


def finish_pack_pallas(out, ctx) -> PackResult:
    """The one synchronizing fetch for a dispatched fused-kernel solve."""
    prob, cnt, Gp, Kp, R = ctx
    # one transfer for all outputs (the device link may be high-latency);
    # take arrives sparse unless the nonzero count overflowed the buffer
    take_dense, vals, idx, nnz, cfg_out, npods_out, rem_out = out
    nnz_v, vals_v, idx_v, cfg_out, npods_out, rem_out = jax.device_get(
        (nnz, vals, idx, cfg_out, npods_out, rem_out)
    )
    take_flat = expand_take(vals_v, idx_v, nnz_v, take_dense).reshape(Gp, Kp)
    leftover = cnt - take_flat.sum(axis=1).astype(np.int32)
    node_cfg = np.asarray(cfg_out).reshape(Kp)
    node_pods = np.asarray(npods_out).reshape(Kp)
    rem_np = np.asarray(rem_out).reshape(R_FIX, Kp).T[:, :R]  # [Kp, R]
    # node_used = alloc[cfg] - remaining (zero for unopened slots)
    alloc_by_cfg = np.zeros((Kp, R), np.float32)
    opened_mask = node_cfg >= 0
    alloc_by_cfg[opened_mask] = prob.alloc[node_cfg[opened_mask]]
    node_used = np.where(opened_mask[:, None], alloc_by_cfg - rem_np, 0.0)
    return PackResult(
        take=take_flat,
        leftover=leftover,
        node_cfg=node_cfg,
        node_pods=node_pods,
        node_used=node_used.astype(np.float32),
    )


# --- dispatch crossover model (calibrated, not guessed) --------------------
#
# The fused kernel's fixed launch + host-prep cost outweighs its per-step
# win over the scan kernel until the class axis is deep.  End-to-end wall
# clock through the tunneled driver link cannot separate the kernels (the
# ~100ms fixed round trip buries a few-ms delta in run-to-run jitter);
# the calibration inputs are bench.py's `device_ms` — the marginal
# per-solve cost with the round trip amortized out (chained dispatches,
# one fetch) — and the solver's per-phase profile (`pad` + `dispatch`
# self-times, utils/trace.phase), which attribute the gap to fixed
# host-prep/launch overhead rather than per-step work:
#
#   BENCH r5, config 2 (~320 classes, v5e): scan device_ms 0.71,
#   pallas device_ms ~ fixed-overhead-dominated and parity-or-worse
#   (reported -1.4, i.e. below the measurement noise floor after the
#   marginal subtraction — clamped to 0 at the measurement site since).
#
# Model: pallas wins when per-step gain x steps > fixed overhead, i.e.
# classes > PALLAS_FIXED_OVERHEAD_MS / PALLAS_PER_STEP_GAIN_US.  The
# measured constants put the break-even near 900 classes; production
# batches (config 2 is the deepest at ~320) sit well below it, so
# auto_pack correctly never dispatches the fused kernel in production —
# that is the calibrated regime, not a bug.  tests/test_pallas.py pins
# the dispatch decision to this model on both sides of the crossover.
PALLAS_FIXED_OVERHEAD_MS = 20.0  # fused-kernel launch + host-prep (pad)
PALLAS_PER_STEP_GAIN_US = 22.0  # per-class-step win over the scan kernel


def pallas_crossover_classes() -> int:
    """Class depth where the fused kernel's per-step win repays its fixed
    overhead (the measured break-even, ~900 steps)."""
    return int(PALLAS_FIXED_OVERHEAD_MS * 1000.0 / PALLAS_PER_STEP_GAIN_US)


# dispatch threshold: the break-even rounded up to the class-axis bucket
# the kernel would actually compile for (ops.packer._bucket), so the
# threshold sits on a compile-shape boundary
PALLAS_MIN_CLASSES = _bucket(pallas_crossover_classes())

# which kernel the last auto_pack dispatch ran ("pallas" | "scan") —
# observability for the bench harness and the scheduler's metrics
LAST_KERNEL = "scan"


def choose_kernel(prob: CompiledProblem, platform: str | None = None) -> str:
    """The auto_pack dispatch decision, separated so tests can pin it to
    the measured crossover regime without a TPU attached."""
    if platform is None:
        platform = jax.devices()[0].platform
    if (
        len(prob.classes) >= PALLAS_MIN_CLASSES
        and supports(prob)
        and platform == "tpu"
    ):
        return "pallas"
    return "scan"


def auto_pack(
    prob: CompiledProblem, k_slots: int = 0, objective: str = "nodes"
) -> PackResult:
    """Backend dispatch: the fused Pallas kernel for large heterogeneous
    batches on real TPUs, the lax.scan kernel otherwise."""
    global LAST_KERNEL
    LAST_KERNEL = choose_kernel(prob)
    if LAST_KERNEL == "pallas":
        return run_pack_pallas(prob, k_slots, objective)
    from karpenter_tpu.ops.packer import run_pack

    return run_pack(prob, k_slots, objective)
