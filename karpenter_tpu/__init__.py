"""karpenter-tpu: a TPU-native cluster-autoscaling framework.

A from-scratch re-creation of the capabilities of Karpenter
(reference: preflightsiren/karpenter — the AWS provider half plus the
karpenter-core engine it drives), re-designed TPU-first: scheduling and
consolidation are compiled into dense pod x instance-type x zone tensors and
solved in batched JAX/XLA/Pallas passes instead of the reference's greedy
first-fit-decreasing loop (reference designs/bin-packing.md:18-42) and
sequential consolidation scans (reference designs/consolidation.md).

Layer map (mirrors reference SURVEY.md section 1):
  api/          data model: requirements algebra, resources, CRD-like objects
  scheduling/   constraint tensorization, FFD oracle, JAX/Pallas solver
  ops/          device kernels (annealing sweeps, feasibility)
  parallel/     device-mesh sharding of large solves (shard_map + collectives)
  cloud/        CloudProvider plugin boundary + fake cloud backend
  providers/    instance-type / instance / pricing / subnet / ... providers
  controllers/  provisioning, deprovisioning, interruption, GC, nodeclass
  state/        in-memory cluster state (reference: karpenter-core state.Cluster)
  batcher/      request coalescing (reference pkg/batcher)
  cache/        TTL + unavailable-offerings caches (reference pkg/cache)
  metrics/      prometheus-style metrics registry
"""

__version__ = "0.1.0"
