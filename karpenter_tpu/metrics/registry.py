"""In-process metrics registry (reference: controller-runtime Prometheus
registry; metric names mirror website v0.31 concepts/metrics.md).

Counters, gauges, and histograms keyed by (name, sorted labels).  The
registry is inspectable in tests and exportable as a Prometheus-style text
dump — the reference's ~50 published metrics map onto these names, e.g.
`karpenter_provisioner_scheduling_duration_seconds`,
`karpenter_nodeclaims_launched`, `karpenter_interruption_received_messages`,
`karpenter_cloudprovider_duration_seconds`, batcher batch size/time.
"""

from __future__ import annotations

import threading
from collections import defaultdict, deque
from typing import Dict, Iterable, List, Mapping, Optional, Tuple

# per-series sample window kept for test/debug inspection; count/sum run
# unbounded so dump() stays exact while memory stays O(1) per series
_HIST_WINDOW = 1024


class _Hist:
    __slots__ = ("count", "total", "samples")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.samples: deque = deque(maxlen=_HIST_WINDOW)

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.samples.append(value)


def _key(labels: Optional[Mapping[str, str]]) -> Tuple:
    return tuple(sorted((labels or {}).items()))


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, Dict[Tuple, float]] = defaultdict(
            lambda: defaultdict(float)
        )
        self.gauges: Dict[str, Dict[Tuple, float]] = defaultdict(dict)
        self.histograms: Dict[str, Dict[Tuple, _Hist]] = defaultdict(
            lambda: defaultdict(_Hist)
        )

    # ------------------------------------------------------------- recording
    def inc(self, name: str, labels: Optional[Mapping[str, str]] = None, by: float = 1.0):
        with self._lock:
            self.counters[name][_key(labels)] += by

    def set(self, name: str, value: float, labels: Optional[Mapping[str, str]] = None):
        with self._lock:
            self.gauges[name][_key(labels)] = value

    def observe(self, name: str, value: float, labels: Optional[Mapping[str, str]] = None):
        with self._lock:
            self.histograms[name][_key(labels)].observe(value)

    def reset_gauge(self, name: str):
        """Drop every series of a gauge family — used by collectors that
        re-emit their full set each reconcile so vanished nodes/pools do
        not leave stale series behind."""
        with self._lock:
            self.gauges.pop(name, None)

    def unset(self, name: str, labels: Optional[Mapping[str, str]] = None):
        """Drop ONE gauge series (collectors that prune their own emitted
        key set instead of resetting the whole family)."""
        with self._lock:
            series = self.gauges.get(name)
            if series is not None:
                series.pop(_key(labels), None)

    class _Timer:
        def __init__(self, registry: "Registry", name: str, labels):
            self.registry, self.name, self.labels = registry, name, labels

        def __enter__(self):
            import time

            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            import time

            self.registry.observe(
                self.name, time.perf_counter() - self._t0, self.labels
            )
            return False

    def time(
        self, name: str, labels: Optional[Mapping[str, str]] = None
    ) -> "Registry._Timer":
        return Registry._Timer(self, name, labels)

    # ------------------------------------------------------------- reading
    def counter(self, name: str, labels: Optional[Mapping[str, str]] = None) -> float:
        return self.counters.get(name, {}).get(_key(labels), 0.0)

    def gauge(self, name: str, labels: Optional[Mapping[str, str]] = None) -> Optional[float]:
        return self.gauges.get(name, {}).get(_key(labels))

    def histogram(self, name: str, labels: Optional[Mapping[str, str]] = None) -> List[float]:
        """Recent samples of a series (bounded window; see _HIST_WINDOW)."""
        h = self.histograms.get(name, {}).get(_key(labels))
        return list(h.samples) if h is not None else []

    def dump(self) -> str:
        """Prometheus-text-style dump (for the /metrics analogue)."""
        lines: List[str] = []
        with self._lock:
            for name, series in sorted(self.counters.items()):
                for labels, v in sorted(series.items()):
                    lines.append(f"{name}{_fmt(labels)} {v:g}")
            for name, series in sorted(self.gauges.items()):
                for labels, v in sorted(series.items()):
                    lines.append(f"{name}{_fmt(labels)} {v:g}")
            for name, series in sorted(self.histograms.items()):
                for labels, h in sorted(series.items()):
                    lines.append(f"{name}_count{_fmt(labels)} {h.count}")
                    lines.append(f"{name}_sum{_fmt(labels)} {h.total:g}")
        return "\n".join(lines)


def _fmt(labels: Tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


def export_compile_cache_counters(
    registry: "Registry", scheduler, consumer: str, exported: Tuple[int, int]
) -> Tuple[int, int]:
    """Mirror a TensorScheduler's monotonic compile-cache hit/miss counts
    into `karpenter_solver_compile_cache_{hits,misses}_total{consumer=}`.

    The scheduler counts across its whole lifetime; each caller keeps the
    pair it last exported and this bumps the registry by the delta, so the
    registry counter stays a well-formed monotonic _total series even with
    two consumers (provisioner, disruption) exporting independently.
    Returns the new exported pair."""
    hits, misses = scheduler.compile_cache_hits, scheduler.compile_cache_misses
    prev_h, prev_m = exported
    if hits > prev_h:
        registry.inc(
            "karpenter_solver_compile_cache_hits_total",
            {"consumer": consumer},
            by=hits - prev_h,
        )
    if misses > prev_m:
        registry.inc(
            "karpenter_solver_compile_cache_misses_total",
            {"consumer": consumer},
            by=misses - prev_m,
        )
    return (hits, misses)


# process-global default registry (controllers accept an override)
REGISTRY = Registry()
