"""In-process metrics registry (reference: controller-runtime Prometheus
registry; metric names mirror website v0.31 concepts/metrics.md).

Counters, gauges, and histograms keyed by (name, sorted labels).  The
registry is inspectable in tests and exportable as a Prometheus-style text
dump — the reference's ~50 published metrics map onto these names, e.g.
`karpenter_provisioner_scheduling_duration_seconds`,
`karpenter_nodeclaims_launched`, `karpenter_interruption_received_messages`,
`karpenter_cloudprovider_duration_seconds`, batcher batch size/time.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, Iterable, List, Mapping, Optional, Tuple


def _key(labels: Optional[Mapping[str, str]]) -> Tuple:
    return tuple(sorted((labels or {}).items()))


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, Dict[Tuple, float]] = defaultdict(
            lambda: defaultdict(float)
        )
        self.gauges: Dict[str, Dict[Tuple, float]] = defaultdict(dict)
        self.histograms: Dict[str, Dict[Tuple, List[float]]] = defaultdict(
            lambda: defaultdict(list)
        )

    # ------------------------------------------------------------- recording
    def inc(self, name: str, labels: Optional[Mapping[str, str]] = None, by: float = 1.0):
        with self._lock:
            self.counters[name][_key(labels)] += by

    def set(self, name: str, value: float, labels: Optional[Mapping[str, str]] = None):
        with self._lock:
            self.gauges[name][_key(labels)] = value

    def observe(self, name: str, value: float, labels: Optional[Mapping[str, str]] = None):
        with self._lock:
            self.histograms[name][_key(labels)].append(value)

    class _Timer:
        def __init__(self, registry: "Registry", name: str, labels):
            self.registry, self.name, self.labels = registry, name, labels

        def __enter__(self):
            import time

            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            import time

            self.registry.observe(
                self.name, time.perf_counter() - self._t0, self.labels
            )
            return False

    def time(self, name: str, labels: Optional[Mapping[str, str]] = None) -> "_Timer":
        return Registry._Timer(self, name, labels)

    # ------------------------------------------------------------- reading
    def counter(self, name: str, labels: Optional[Mapping[str, str]] = None) -> float:
        return self.counters.get(name, {}).get(_key(labels), 0.0)

    def gauge(self, name: str, labels: Optional[Mapping[str, str]] = None) -> Optional[float]:
        return self.gauges.get(name, {}).get(_key(labels))

    def histogram(self, name: str, labels: Optional[Mapping[str, str]] = None) -> List[float]:
        return list(self.histograms.get(name, {}).get(_key(labels), ()))

    def dump(self) -> str:
        """Prometheus-text-style dump (for the /metrics analogue)."""
        lines: List[str] = []
        with self._lock:
            for name, series in sorted(self.counters.items()):
                for labels, v in sorted(series.items()):
                    lines.append(f"{name}{_fmt(labels)} {v:g}")
            for name, series in sorted(self.gauges.items()):
                for labels, v in sorted(series.items()):
                    lines.append(f"{name}{_fmt(labels)} {v:g}")
            for name, series in sorted(self.histograms.items()):
                for labels, vs in sorted(series.items()):
                    lines.append(f"{name}_count{_fmt(labels)} {len(vs)}")
                    lines.append(f"{name}_sum{_fmt(labels)} {sum(vs):g}")
        return "\n".join(lines)


def _fmt(labels: Tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


# process-global default registry (controllers accept an override)
REGISTRY = Registry()
