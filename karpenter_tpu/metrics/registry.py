"""In-process metrics registry (reference: controller-runtime Prometheus
registry; metric names mirror website v0.31 concepts/metrics.md).

Counters, gauges, and histograms keyed by (name, sorted labels).  The
registry is inspectable in tests and exportable as a Prometheus-style text
dump — the reference's ~50 published metrics map onto these names, e.g.
`karpenter_provisioner_scheduling_duration_seconds`,
`karpenter_nodeclaims_launched`, `karpenter_interruption_received_messages`,
`karpenter_cloudprovider_duration_seconds`, batcher batch size/time.
"""

from __future__ import annotations

import bisect
import threading
from collections import defaultdict, deque
from typing import Dict, Iterable, List, Mapping, Optional, Tuple
from karpenter_tpu.analysis.sanitizer import make_lock

# per-series sample window kept for test/debug inspection; count/sum run
# unbounded so dump() stays exact while memory stays O(1) per series
_HIST_WINDOW = 1024

# fixed cumulative bucket bounds (seconds for latency series, plain
# counts for size series), Prometheus-style with an implicit +Inf: wide
# enough to span sub-ms solver phases and multi-minute time-to-schedule.
# Buckets are the UNBOUNDED percentile source: the sample window above
# only holds the last 1024 observations, so past that point window
# percentiles describe the tail of the run, not the run — `quantile`
# switches to bucket interpolation exactly there.
BUCKET_BOUNDS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
)


def _nearest_rank(ordered: List[float], q: float) -> float:
    """The sim report's percentile formula (sim/report.py), shared so the
    exact path of `_Hist.quantile` reproduces it bit-for-bit."""
    if not ordered:
        return 0.0
    rank = max(0, min(len(ordered) - 1, int(round(q * (len(ordered) - 1)))))
    return ordered[rank]


class _Hist:
    __slots__ = ("count", "total", "samples", "buckets", "vmax")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.samples: deque = deque(maxlen=_HIST_WINDOW)
        # per-bound observation counts + one overflow slot (+Inf);
        # rendered CUMULATIVE by the exposition
        self.buckets: List[int] = [0] * (len(BUCKET_BOUNDS) + 1)
        self.vmax = 0.0

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.samples.append(value)
        self.buckets[bisect.bisect_left(BUCKET_BOUNDS, value)] += 1
        if value > self.vmax:
            self.vmax = value

    def quantile(self, q: float) -> float:
        """Percentile that stays honest past the sample window: exact
        nearest-rank while the window still holds every observation,
        bucket interpolation (deterministic, monotone) once it doesn't.
        The exact path reuses the sim report's formula so small runs are
        unchanged by the bucket machinery."""
        if self.count == 0:
            return 0.0
        if self.count <= len(self.samples):
            return _nearest_rank(sorted(self.samples), q)
        target = q * self.count
        cum = 0
        for i, n in enumerate(self.buckets):
            if n == 0:
                continue
            if cum + n >= target:
                if i >= len(BUCKET_BOUNDS):
                    return self.vmax  # +Inf bucket: the tracked max
                lo = BUCKET_BOUNDS[i - 1] if i > 0 else 0.0
                hi = BUCKET_BOUNDS[i]
                return lo + (hi - lo) * max(0.0, target - cum) / n
            cum += n
        return self.vmax


def _key(labels: Optional[Mapping[str, str]]) -> Tuple:
    return tuple(sorted((labels or {}).items()))


class Registry:
    def __init__(self):
        self._lock = make_lock("Registry._lock")
        self.counters: Dict[str, Dict[Tuple, float]] = defaultdict(
            lambda: defaultdict(float)
        )
        self.gauges: Dict[str, Dict[Tuple, float]] = defaultdict(dict)
        self.histograms: Dict[str, Dict[Tuple, _Hist]] = defaultdict(
            lambda: defaultdict(_Hist)
        )
        # optional cluster event ledger (obs/events.py): the operator
        # attaches its per-process ledger here so every layer that
        # already holds a registry can emit decision events without new
        # constructor plumbing; None = events are dropped (bare tests)
        self.ledger = None
        # streaming sketch taps (load/sketch.py): histogram families a
        # consumer wants summarized over the WHOLE stream, not the
        # _Hist sample window — the sim runner attaches one for
        # time-to-schedule so the fleet report's p99.9 stays exact-ish
        # at millions of observations
        self._sketches: Dict[str, List[object]] = {}

    # ------------------------------------------------------------- recording
    def inc(self, name: str, labels: Optional[Mapping[str, str]] = None, by: float = 1.0):
        with self._lock:
            self.counters[name][_key(labels)] += by

    def set(self, name: str, value: float, labels: Optional[Mapping[str, str]] = None):
        with self._lock:
            self.gauges[name][_key(labels)] = value

    def observe(self, name: str, value: float, labels: Optional[Mapping[str, str]] = None):
        with self._lock:
            self.histograms[name][_key(labels)].observe(value)
            for sketch in self._sketches.get(name, ()):
                sketch.observe(value)

    def attach_sketch(self, name: str, sketch) -> None:
        """Feed every observation of histogram family `name` (all label
        sets) into `sketch` as well (anything with an ``observe(float)``
        method, e.g. load/sketch.py's QuantileSketch)."""
        with self._lock:
            self._sketches.setdefault(name, []).append(sketch)

    def event(self, type_: str, **attrs) -> None:
        """Emit a cluster event through the attached ledger (no-op when
        none is attached).  The ledger stamps the injected clock + the
        current trace ID and bumps ``karpenter_events_total{type}``."""
        led = self.ledger
        if led is not None:
            led.emit(type_, **attrs)

    def reset_gauge(self, name: str):
        """Drop every series of a gauge family — used by collectors that
        re-emit their full set each reconcile so vanished nodes/pools do
        not leave stale series behind."""
        with self._lock:
            self.gauges.pop(name, None)

    def unset(self, name: str, labels: Optional[Mapping[str, str]] = None):
        """Drop ONE gauge series (collectors that prune their own emitted
        key set instead of resetting the whole family)."""
        with self._lock:
            series = self.gauges.get(name)
            if series is not None:
                series.pop(_key(labels), None)

    class _Timer:
        def __init__(self, registry: "Registry", name: str, labels):
            self.registry, self.name, self.labels = registry, name, labels

        def __enter__(self):
            import time

            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            import time

            self.registry.observe(
                self.name, time.perf_counter() - self._t0, self.labels
            )
            return False

    def time(
        self, name: str, labels: Optional[Mapping[str, str]] = None
    ) -> "Registry._Timer":
        return Registry._Timer(self, name, labels)

    # ------------------------------------------------------------- reading
    def counter(self, name: str, labels: Optional[Mapping[str, str]] = None) -> float:
        return self.counters.get(name, {}).get(_key(labels), 0.0)

    def gauge(self, name: str, labels: Optional[Mapping[str, str]] = None) -> Optional[float]:
        return self.gauges.get(name, {}).get(_key(labels))

    def histogram(self, name: str, labels: Optional[Mapping[str, str]] = None) -> List[float]:
        """Recent samples of a series (bounded window; see _HIST_WINDOW)."""
        h = self.histograms.get(name, {}).get(_key(labels))
        return list(h.samples) if h is not None else []

    def quantile(
        self, name: str, q: float, labels: Optional[Mapping[str, str]] = None
    ) -> float:
        """Window-exact / bucket-estimated percentile of a histogram
        series — unlike ``percentile(registry.histogram(...))`` this does
        NOT silently degrade to the last-1024-samples tail once a series
        outgrows its window (tests/test_obs.py pins the regression)."""
        h = self.histograms.get(name, {}).get(_key(labels))
        return h.quantile(q) if h is not None else 0.0

    def dump(self) -> str:
        """Prometheus-text-style dump (for the /metrics analogue)."""
        lines: List[str] = []
        with self._lock:
            for name, series in sorted(self.counters.items()):
                for labels, v in sorted(series.items()):
                    lines.append(f"{name}{_fmt(labels)} {v:g}")
            for name, series in sorted(self.gauges.items()):
                for labels, v in sorted(series.items()):
                    lines.append(f"{name}{_fmt(labels)} {v:g}")
            for name, series in sorted(self.histograms.items()):
                for labels, h in sorted(series.items()):
                    lines.append(f"{name}_count{_fmt(labels)} {h.count}")
                    lines.append(f"{name}_sum{_fmt(labels)} {h.total:g}")
        return "\n".join(lines)


def _fmt(labels: Tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


# --------------------------------------------------------------- exposition
def _num(v: float) -> str:
    """Full-precision exposition value: %g truncates to 6 significant
    digits, which corrupts large counters on the wire (1_234_567 ->
    1.23457e+06); round-trip formatting keeps every digit while still
    rendering integral floats as '1'."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _escape(value: str) -> str:
    """Prometheus label-value escaping (exposition format spec)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _fmt_esc(labels: Tuple, extra: Tuple = ()) -> str:
    pairs = tuple(labels) + tuple(extra)
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in pairs)
    return "{" + inner + "}"


def exposition(registry: "Registry") -> str:
    """REAL Prometheus text exposition (format 0.0.4): HELP/TYPE headers
    from the shared metric catalog (metrics/catalog.py — the same source
    docs/metrics.md renders from) and cumulative ``_bucket{le=}`` series
    for histograms, so an actual Prometheus server can scrape the
    telemetry endpoint (obs/http.py) and ``histogram_quantile`` works.

    Unlike ``dump()`` (the in-repo test/debug surface, shape-stable on
    purpose), this is the wire format: one family header per name, then
    every series of that family."""
    from karpenter_tpu.metrics.catalog import METRIC_DETAILS

    def header(name: str, kind: str) -> List[str]:
        detail = METRIC_DETAILS.get(name)
        help_text = detail[2] if detail is not None else name
        return [
            f"# HELP {name} {_escape(help_text)}",
            f"# TYPE {name} {kind}",
        ]

    lines: List[str] = []
    with registry._lock:
        for name, series in sorted(registry.counters.items()):
            lines += header(name, "counter")
            for labels, v in sorted(series.items()):
                lines.append(f"{name}{_fmt_esc(labels)} {_num(v)}")
        for name, series in sorted(registry.gauges.items()):
            lines += header(name, "gauge")
            for labels, v in sorted(series.items()):
                lines.append(f"{name}{_fmt_esc(labels)} {_num(v)}")
        for name, series in sorted(registry.histograms.items()):
            lines += header(name, "histogram")
            for labels, h in sorted(series.items()):
                cum = 0
                for bound, n in zip(BUCKET_BOUNDS, h.buckets):
                    cum += n
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_esc(labels, (('le', f'{bound:g}'),))} {cum}"
                    )
                lines.append(
                    f"{name}_bucket{_fmt_esc(labels, (('le', '+Inf'),))} "
                    f"{h.count}"
                )
                lines.append(f"{name}_sum{_fmt_esc(labels)} {_num(h.total)}")
                lines.append(f"{name}_count{_fmt_esc(labels)} {h.count}")
    return "\n".join(lines) + "\n"


def export_compile_cache_counters(
    registry: "Registry", scheduler, consumer: str, exported: Tuple[int, int]
) -> Tuple[int, int]:
    """Mirror a TensorScheduler's monotonic compile-cache hit/miss counts
    into `karpenter_solver_compile_cache_{hits,misses}_total{consumer=}`.

    The scheduler counts across its whole lifetime; each caller keeps the
    pair it last exported and this bumps the registry by the delta, so the
    registry counter stays a well-formed monotonic _total series even with
    two consumers (provisioner, disruption) exporting independently.
    Returns the new exported pair."""
    hits, misses = scheduler.compile_cache_hits, scheduler.compile_cache_misses
    prev_h, prev_m = exported
    if hits > prev_h:
        registry.inc(
            "karpenter_solver_compile_cache_hits_total",
            {"consumer": consumer},
            by=hits - prev_h,
        )
    if misses > prev_m:
        registry.inc(
            "karpenter_solver_compile_cache_misses_total",
            {"consumer": consumer},
            by=misses - prev_m,
        )
    return (hits, misses)


def export_resident_counters(
    registry: "Registry", scheduler, consumer: str, exported: Tuple[int, int]
) -> Tuple[int, int]:
    """Mirror a TensorScheduler's monotonic resident-tensor hit/rebuild
    counts into ``karpenter_solver_resident_{hits,rebuilds}_total
    {consumer=}`` — the same delta-export contract as
    :func:`export_compile_cache_counters` (two consumers, one scheduler
    counter each, registry bumps by the delta)."""
    hits, rebuilds = scheduler.resident_hits, scheduler.resident_rebuilds
    prev_h, prev_r = exported
    if hits > prev_h:
        registry.inc(
            "karpenter_solver_resident_hits_total",
            {"consumer": consumer},
            by=hits - prev_h,
        )
    if rebuilds > prev_r:
        registry.inc(
            "karpenter_solver_resident_rebuilds_total",
            {"consumer": consumer},
            by=rebuilds - prev_r,
        )
    return (hits, rebuilds)


# process-global default registry (controllers accept an override)
REGISTRY = Registry()
