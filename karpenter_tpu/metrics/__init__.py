"""Metrics registry (reference: Prometheus metric set, website v0.31 metrics.md)."""

from karpenter_tpu.metrics.registry import REGISTRY, Registry

__all__ = ["REGISTRY", "Registry"]
