"""Metric metadata: the ONE source of truth for metric type / labels /
semantics, consumed by BOTH the generated reference page
(tools/gen_metrics_doc.py -> docs/metrics.md) and the live /metrics
endpoint's Prometheus exposition (metrics/registry.py `exposition` ->
HELP/TYPE lines).  An entry here renders identically in the doc and on
the wire, so the two can never disagree about what a series means.

Entries: name -> (type, labels, description).  Families without an entry
still expose and document — type inferred from the registry family they
live in, description defaulting to the name — so the catalog grows as
families gain documentation, never as a precondition.
"""

from __future__ import annotations

from typing import Dict, Tuple

METRIC_DETAILS: Dict[str, Tuple[str, str, str]] = {
    "karpenter_cloud_api_retries_total": (
        "counter",
        "api, classification",
        "bumped each time RetryingCloud retries a cloud call classified "
        "throttle or transient; terminal errors (ICE, NotFound) never move it",
    ),
    "karpenter_cloud_api_circuit_state": (
        "gauge",
        "api",
        "0 closed / 1 half-open / 2 open; opens after "
        "cloud_circuit_failure_threshold consecutive classified failures, "
        "half-opens when cloud_circuit_reset_timeout elapses, closes on the "
        "next success",
    ),
    "karpenter_provider_cache_stale_seconds": (
        "gauge",
        "provider",
        "age of the last-good data a degraded provider (pricing / subnet / "
        "securitygroup / image / version) is serving while its refresh API "
        "fails; reset to 0 by the next successful refresh",
    ),
    "karpenter_tpu_controller_healthy": (
        "gauge",
        "controller",
        "1 after a clean reconcile; 0 while the controller is "
        "crash-contained in per-controller requeue backoff after raising",
    ),
    "karpenter_pods_time_to_schedule_seconds": (
        "histogram",
        "(none)",
        "pod first-seen-pending -> nominated onto a node/claim, observed "
        "by the provisioning controller on the injected clock; the "
        "simulator's SLO report (sim/report.py) aggregates its samples "
        "into p50/p95/p99 time-to-schedule",
    ),
    "karpenter_admission_latency_seconds": (
        "histogram",
        "path",
        "pod first-seen-pending -> nominated, split by the admission "
        "path that nominated it: fast (the single-pod resident admit "
        "dispatch) vs batch (the authoritative coalesced solve).  Same "
        "clock and endpoints as karpenter_pods_time_to_schedule_seconds "
        "— that legacy series keeps the unsplit stream",
    ),
    "karpenter_admission_fastpath_total": (
        "counter",
        "outcome",
        "admission fast-path attempts: nominated (pods placed onto live "
        "nodes in one admit dispatch), fallback (ineligible or no fit — "
        "the batched solve runs, reason on the fallback counter), "
        "mismatch (device score refuted by the sequential host oracle)",
    ),
    "karpenter_admission_fastpath_fallback_total": (
        "counter",
        "reason",
        "fast-path declines by reason (docs/designs/admission-fastpath"
        ".md taxonomy): burst_too_large, mixed_burst, pod_shape, "
        "affinity_carrier, catalog_roll, resident_cold, resident_miss, "
        "sharded_backend, needs_new_node, unschedulable, no_pools — "
        "every one lands in the batched solve, never a mis-nomination",
    ),
    "karpenter_admission_fastpath_mismatch_total": (
        "counter",
        "(none)",
        "admit-dispatch verdicts refuted by the sequential host oracle "
        "(bit-equality over the take vector, placed count, and "
        "open-capacity bit).  The convergence contract requires this to "
        "stay 0 — the sim/load invariant planes fail the run otherwise; "
        "a mismatch never nominates (the batched solve decides)",
    ),
    "karpenter_sim_events_injected_total": (
        "counter",
        "kind",
        "scenario events the simulator applied (pod_create, pod_delete, "
        "instance_kill, spot_interruption, chaos, az_down/az_up, "
        "image_roll, image_deprecate, price_shock, pool_update)",
    ),
    "karpenter_sim_phase_seconds": (
        "histogram",
        "phase",
        "host wall time of one sim-tick phase (generate = workload/tape "
        "event materialization, apply = event application, reconcile = "
        "kubelet + operator, invariants = the per-tick invariant suite); "
        "feeds the --profile sim_phases section and the bench's "
        "harness-overhead fraction ((generate+invariants)/total must stay "
        "under 20% on the million-events anchor) — wall clock, so never "
        "part of the byte-compared trace/report surface",
    ),
    "karpenter_sim_time_to_settle_seconds": (
        "gauge",
        "(none)",
        "last simulated moment the cluster had pending pods, relative to "
        "run start — the scale anchors' acceptance signal; exceeding the "
        "scenario's settle_budget_s raises a settle-budget invariant "
        "violation",
    ),
    "karpenter_load_vector_checked_ticks_total": (
        "counter",
        "(none)",
        "ticks whose invariant suite ran on the vectorized plane "
        "(load/invariants.py VectorInvariantChecker) instead of the "
        "scalar one — cross-validation tests prove both planes emit "
        "byte-identical violations",
    ),
    "karpenter_sim_ticks_total": (
        "counter",
        "phase",
        "simulated ticks executed per phase (run / drain / settle)",
    ),
    "karpenter_sim_pending_pods": (
        "gauge",
        "(none)",
        "pending-pod depth at the end of the last simulated tick; the "
        "report's pending.peak is the max this gauge reached",
    ),
    "karpenter_sim_invariant_violations_total": (
        "counter",
        "invariant",
        "invariant checks that failed (no-double-launch, "
        "registered-eq-launched, budgets, no-leaked-instances, "
        "schedule-deadline, all-pods-scheduled, no-wedged-controller); "
        "any movement fails the run",
    ),
    "karpenter_solver_phase_seconds": (
        "histogram",
        "phase",
        "per-solve wall time of one solver phase (partition / compile / "
        "pad / dispatch / device_block / oracle / decode / delta / other; "
        "delta is the resident-tensor plan+scatter that replaces "
        "compile+pad on warm ticks) — "
        "disjoint self-times that sum to the solve's wall clock, observed "
        "by the provisioning controller after every scheduling solve; see "
        "the 'solve latency anatomy' section in the README for how to "
        "read them",
    ),
    "karpenter_solver_compile_cache_hits_total": (
        "counter",
        "consumer",
        "solves served from the TensorScheduler's incremental compile "
        "cache, per consuming controller (provisioner, disruption); "
        "exported as the delta of the scheduler's lifetime counter each "
        "reconcile",
    ),
    "karpenter_solver_compile_cache_misses_total": (
        "counter",
        "consumer",
        "solves that had to run the full host-side compile; a warm "
        "steady-state cluster should see hits dominate — misses every "
        "tick mean something (pods, pools, live nodes) is being mutated "
        "in place",
    ),
    "karpenter_solver_resident_hits_total": (
        "counter",
        "consumer",
        "solves (and consolidation base builds) served from the "
        "device-resident cluster tensors (ops/resident.py) — the compiled "
        "problem stayed on device and this tick's cluster diff applied as "
        "donated scatter deltas (or no delta at all), skipping both the "
        "host re-tensorize and the host->device upload",
    ),
    "karpenter_solver_resident_rebuilds_total": (
        "counter",
        "consumer",
        "full tensorize+upload passes while the resident layer was "
        "eligible to serve: the delta planner could not prove equivalence "
        "(catalog roll, pool/daemonset mutation, constraint carriers, "
        "extended-resource axis change, padded-bucket overflow, >50% "
        "churn) or the state was cold; a warm steady cluster should see "
        "hits dominate",
    ),
    "karpenter_solver_resident_delta_rows": (
        "histogram",
        "(none)",
        "scattered tensor rows+columns of one resident warm tick (class "
        "rows + live-node columns + usage rows; 0 = a pure no-change "
        "hit), observed by the provisioner per resident solve — the delta "
        "sizes the sim report's solver.resident section summarizes",
    ),
    "karpenter_consolidation_eval_batch_size": (
        "histogram",
        "",
        "candidate-subset elements per batched what-if dispatch "
        "(TensorScheduler.evaluate_removals): the single-node scan is one "
        "batch, each drop-one descent level is one batch",
    ),
    "karpenter_consolidation_phase_seconds": (
        "histogram",
        "phase",
        "per-dispatch wall time of one batched-evaluation phase "
        "(partition / compile / pad / dispatch / device_block / decode / "
        "other) — kept separate from karpenter_solver_phase_seconds so "
        "verdict batches don't skew the provisioner's per-solve "
        "percentiles",
    ),
    "karpenter_consolidation_evals_total": (
        "counter",
        "path",
        "consolidation what-if simulations by evaluation path: 'batched' "
        "elements were answered on-device from one shared compile, "
        "'sequential' elements ran the per-subset solver round-trip "
        "(fallback conditions: docs/designs/consolidation-batching.md)",
    ),
    "karpenter_consolidation_search_rounds": (
        "histogram",
        "",
        "propose→score→select rounds executed by one multi-node "
        "consolidation pass's population search "
        "(controllers/disruption.py + scheduling/popsearch.py); fewer "
        "than consolidation_search_rounds means the universe ran out of "
        "fresh subsets early",
    ),
    "karpenter_consolidation_population_size": (
        "histogram",
        "",
        "distinct candidate subsets (removal masks) a pass's population "
        "search scored across all of its rounds — structured seeds plus "
        "random diversity plus annealed mutations, each round one "
        "vmapped device dispatch",
    ),
    "karpenter_consolidation_search_phase_seconds": (
        "histogram",
        "phase",
        "per-round wall time of one population-search phase (propose / "
        "pad / dispatch / device_block / decode / select / other) — the "
        "search analogue of karpenter_consolidation_phase_seconds, kept "
        "separate so population rounds don't skew the per-subset batch "
        "distribution",
    ),
    "karpenter_consolidation_search_winners_total": (
        "counter",
        "action",
        "how population-search passes concluded: a multi-node 'delete' "
        "or 'replace' action was taken, or 'none' (no acceptable subset, "
        "or the sequential re-derivation declined the winner)",
    ),
    "karpenter_consolidation_verdict_mismatch_total": (
        "counter",
        "",
        "batched verdicts contradicted by the winner's sequential decode "
        "— must stay 0 (the parity suite enforces it); any movement is a "
        "bug in the batched path",
    ),
    # ---- observability plane (docs/designs/observability.md)
    "karpenter_events_total": (
        "counter",
        "type",
        "cluster event ledger entries by type (PodNominated, NodeLaunched, "
        "NodeDisrupted, RetryBackoff, CircuitOpen, StaleServed, "
        "VerdictFallback, CatalogRolled, SLOBreach, SLORecovered, "
        "AnomalyDetected) — emitted at the controllers' decision sites, "
        "deterministic under the simulator's FakeClock; the ring itself is "
        "readable at /events and in the sim trace's `led` lines",
    ),
    "karpenter_telemetry_scrapes_total": (
        "counter",
        "endpoint",
        "HTTP requests served by the telemetry server "
        "(metrics / healthz / events / trace / debug/flight), per endpoint "
        "— the scrape heartbeat a dead-man's-switch alert can sit on",
    ),
    "karpenter_store_requests_total": (
        "counter",
        "method",
        "store-server RPCs dispatched, per method (put / delete / "
        "bind_pod / evict_pod / lease_* / watch / hello / ...); served "
        "from the store process's own registry on ITS telemetry endpoint",
    ),
    # ---- fleet-scale store plane (docs/designs/store-scale.md)
    "karpenter_store_request_seconds": (
        "histogram",
        "method",
        "server-side wall time of one store RPC dispatch (fence + verb "
        "+ broadcast), per method — the store process's latency anatomy, "
        "on ITS telemetry endpoint",
    ),
    "karpenter_store_rpc_seconds": (
        "histogram",
        "method",
        "client-side wall time of one store RPC including retries "
        "(state/remote.py), per method — the operator's view of store "
        "latency; watched by the anomaly detector and baselined by "
        "doctor like a solver phase",
    ),
    "karpenter_store_watch_clients": (
        "gauge",
        "(none)",
        "watch subscribers currently registered on this store server "
        "(operator replicas, read replicas, passive mirrors)",
    ),
    "karpenter_store_watch_queue_depth": (
        "gauge",
        "(none)",
        "deepest per-subscriber broadcast queue after the last commit; "
        "queues are BOUNDED (store_watch_queue_batches) — a subscriber "
        "that hits the bound is coalesced onto a forced resync instead "
        "of growing server memory",
    ),
    "karpenter_store_bytes_sent_total": (
        "counter",
        "codec",
        "bytes written to store-plane sockets (frames + length prefix), "
        "per negotiated payload codec — on the server AND on each "
        "client's own registry; the bin1/json split is the negotiated "
        "binary codec's adoption in one glance",
    ),
    "karpenter_store_bytes_received_total": (
        "counter",
        "codec",
        "bytes read off store-plane sockets (frames + length prefix), "
        "per negotiated payload codec, both halves of the plane",
    ),
    "karpenter_store_resync_total": (
        "counter",
        "kind",
        "watch resyncs: 'replay' (a reconnect gap served from the "
        "replay log — events only, no snapshot), 'snapshot' (the log "
        "was compacted past the client's seq; full state), 'overflow' "
        "(a slow subscriber's bounded queue filled and was coalesced "
        "onto a forced resync), 'epoch' (the store's own continuity "
        "broke under its watchers — a read replica full-resynced from "
        "its primary); servers count what they served, clients count "
        "what they underwent",
    ),
    "karpenter_store_compactions_total": (
        "counter",
        "log",
        "bounded-log trims on the store server: 'replay' (the delta "
        "resync log dropped its oldest batch — clients older than "
        "compacted_seq now snapshot), 'events' (the durable "
        "cluster-event ledger dropped its oldest entries)",
    ),
    # ---- durable log + sharding (docs/designs/store-scale.md, PR 17)
    "karpenter_store_log_records_total": (
        "counter",
        "(none)",
        "records appended to the durable replay log (batch and "
        "checkpoint alike), each length-prefixed, encoded, and fsynced "
        "per the log's fsync policy before the commit acks",
    ),
    "karpenter_store_log_bytes_total": (
        "counter",
        "(none)",
        "bytes appended to the durable replay log segment, length "
        "prefixes included",
    ),
    "karpenter_store_log_checkpoints_total": (
        "counter",
        "(none)",
        "full-snapshot checkpoints written to a fresh segment "
        "(tmp + fsync + atomic rename); recovery reads the LAST "
        "checkpoint plus its contiguous batch tail",
    ),
    "karpenter_store_log_torn_records_total": (
        "counter",
        "(none)",
        "records discarded at recovery because the segment tail was "
        "torn mid-write (truncated length prefix, short payload, or "
        "undecodable bytes); everything before the tear is kept — a "
        "torn tail is a crash artifact, never an error",
    ),
    "karpenter_store_log_failures_total": (
        "counter",
        "(none)",
        "append/fsync failures after which the log failed CLOSED "
        "(inert for the rest of the process) while the in-memory "
        "store kept serving; a restart from a failed log loses the "
        "un-fsynced suffix, so alert on any nonzero delta",
    ),
    "karpenter_store_epoch_rotations_total": (
        "counter",
        "reason",
        "store epoch rotations ('recovery_tail_lost' — the durable "
        "log could not prove continuity at restart; 'shard_import' / "
        "'shard_drop' — a key migration changed this shard's key set); "
        "every rotation forces connected watchers onto a full snapshot "
        "resync, which is exactly the safety the rotation buys",
    ),
    "karpenter_store_shard_migration_begun_total": (
        "counter",
        "shard",
        "reshard export fences raised on a source shard by the "
        "coordinator (service/shardrouter.py); pairs with "
        "..._committed_total — a begun without a commit is a shard "
        "stuck in migration (the doctor names it)",
    ),
    "karpenter_store_shard_migration_committed_total": (
        "counter",
        "shard",
        "reshard migrations committed on a source shard: every "
        "exported key was imported at its new owner (import-before-"
        "drop) and the source's drop landed",
    ),
    "karpenter_sim_wire_faults_total": (
        "counter",
        "fault",
        "scripted wire faults injected by the shard-chaos scenario "
        "(sim/faults.py: drop, zero_frame, truncated_frame, "
        "garbled_payload, delay); each must cost the client one retry "
        "and zero wrong answers",
    ),
    # ---- diagnosis layer (docs/designs/observability.md, PR 7)
    "karpenter_reconcile_tick_duration_seconds": (
        "histogram",
        "(none)",
        "wall-clock duration of one full reconcile_once tick (every "
        "controller plus the diagnosis tail's own evaluation); the SLO "
        "engine's tick_duration_p99 signal reads its bucket-honest p99",
    ),
    "karpenter_pods_pending_age_seconds": (
        "gauge",
        "(none)",
        "age of the oldest pending pod not yet nominated onto a "
        "node/claim, on the injected clock, refreshed by the provisioner "
        "each reconcile (0 when nothing is waiting); the SLO engine's "
        "pending_pod_age_max signal — the reference's pending-pod-age "
        "alerting contract",
    ),
    "karpenter_slo_status": (
        "gauge",
        "rule",
        "1 while the rule is breached (fast AND slow burn windows over "
        "budget), 0 once the fast window recovers; transitions also emit "
        "SLOBreach/SLORecovered ledger events",
    ),
    "karpenter_slo_burn_rate": (
        "gauge",
        "rule, window",
        "time-weighted violating fraction over the rule's fast/slow "
        "window divided by its budget; >= 1 on both windows pages "
        "(zero-budget rules saturate at 1000 on any violation)",
    ),
    "karpenter_slo_breaches_total": (
        "counter",
        "rule",
        "SLOBreach transitions per rule over the process lifetime; the "
        "sim report's `slo` section carries the per-scenario counts",
    ),
    "karpenter_anomaly_detected_total": (
        "counter",
        "series, phase",
        "phase-latency samples that blew past their rolling "
        "median/MAD baseline (obs/detect.py); each detection also emits "
        "an AnomalyDetected ledger event carrying baseline vs observed "
        "and the magnitude",
    ),
    "karpenter_flight_dumps_total": (
        "counter",
        "trigger",
        "flight-recorder dumps written, per trigger (slo_breach / "
        "controller_crash / sigusr1 / http / manual); the dump itself is "
        "a JSONL ring of the last flight_ticks ticks' full context",
    ),
    # ---- pipelined reconcile (pipeline.py, docs/designs/
    # pipelined-reconcile.md)
    "karpenter_reconcile_overlap_seconds": (
        "histogram",
        "(none)",
        "per-tick host wall time that ran WHILE a speculatively "
        "dispatched consolidation search computed on device (dispatch at "
        "the previous tick's tail, advance under this tick's "
        "provisioning solve, join at the disruption slot); observed only "
        "when the speculation was adopted — the overlap the pipelined "
        "schedule actually realized, the difference between "
        "sum-of-phases and max-of-phases tick latency",
    ),
    "karpenter_pipeline_speculation_total": (
        "counter",
        "controller, outcome",
        "boundary-dispatched speculations by fate: 'adopted' (the "
        "authoritative pass's fingerprint matched — verdicts reused, "
        "overlap banked), 'stale' (cluster state moved between dispatch "
        "and join — every speculative verdict discarded, the pass "
        "recomputed synchronously), 'unused' (an earlier mechanism "
        "acted, consolidation never ran), 'refused' (the pass "
        "fingerprint declined to cover exotic inputs — no speculation "
        "possible; every tick refusing is a fingerprint bug, not a "
        "quiet cluster); adoption rate is the pipeline's hit rate on "
        "quiet ticks",
    ),
    "karpenter_pipeline_stage_errors_total": (
        "counter",
        "controller, stage",
        "speculative dispatch/advance stages that raised; crash-"
        "contained at the pipeline seam — the tick proceeds and the "
        "mutate stage recomputes synchronously, so a speculation bug "
        "can cost latency but never actions",
    ),
    "karpenter_launch_inflight": (
        "gauge",
        "(none)",
        "NodeClaim creates currently in flight in the provisioner's "
        "launch fan-out (bounded by launch_max_concurrency; the "
        "CreateFleet batcher coalesces them underneath); nonzero between "
        "flush start and the last outcome — a stuck CreateFleet is "
        "visible here while it is stuck",
    ),
    # ---- device observatory (obs/device.py, docs/designs/observability.md)
    "karpenter_device_compiles_total": (
        "counter",
        "fn",
        "XLA compilations per jit entry point (pack_kernel / "
        "pack_kernel_buffered / removal_verdict_kernel / "
        "population_verdict_kernel / resident_delta / mesh_pack / "
        "pallas_pack), detected as jit-cache growth at the counted "
        "dispatch seam; a warm steady cluster should see this flat — "
        "movement after the first ticks is a recompile storm in the "
        "making",
    ),
    "karpenter_device_compile_seconds": (
        "histogram",
        "fn",
        "wall time of one XLA compilation (the jit call's duration when "
        "the cache grew — trace+compile dominates; execution stays "
        "async); watched by the anomaly detector and baselined by "
        "doctor like a solver phase",
    ),
    "karpenter_device_warm_recompiles_total": (
        "counter",
        "fn",
        "compilations of a jit entry point that already had dispatches "
        "in an EARLIER reconcile tick — a fresh padded bucket, an axis "
        "change, a donation falling through; each also emits a "
        "DeviceRecompile ledger event (outside the simulator) and is "
        "the doctor's recompile-storm signal",
    ),
    "karpenter_device_dispatches_total": (
        "counter",
        "fn",
        "device dispatches per jit entry point through the counted seam "
        "(obs/device.py) — the denominator that turns transfer bytes "
        "and compile counts into per-dispatch attributions",
    ),
    "karpenter_device_transfer_bytes_total": (
        "counter",
        "site",
        "host->device bytes crossing the counted seam, per site: jit "
        "argument uploads attribute to their entry point (a numpy "
        "argument IS a transfer; device-resident args count zero), "
        "explicit uploads to their put site (pack_constants / "
        "mesh_constants / pallas_constants / resident_seed / "
        "removal_base / population_tensors); lint rule 9 fences raw "
        "device_put call sites so this family stays complete",
    ),
    "karpenter_device_resident_bytes": (
        "gauge",
        "consumer",
        "live device-buffer footprint of the resident cluster tensors "
        "(ops/resident.py), per consumer ('solve' = the pending-batch "
        "state, 'removal' = the consolidation base universe); reported "
        "after every seed/evict so it is the CURRENT truth, not a "
        "high-water mark — a monotonically growing value is a leak",
    ),
    "karpenter_device_resident_updates_total": (
        "counter",
        "kind",
        "resident-tensor updates by kind: 'donated' (warm scatter delta "
        "reusing donated buffers — allocates nothing), 'seed' (fresh "
        "full-tensor upload), 'noop' (refresh hit with no tensor "
        "change); warm steady state should be donated/noop-dominated",
    ),
    # ---- multi-tenant solver service (docs/designs/solver-service.md);
    # every family carries `tenant` (lint rule 12) and is served from the
    # solver process's OWN registry on ITS telemetry endpoint
    "karpenter_service_requests_total": (
        "counter",
        "tenant, method",
        "solver-service RPCs dispatched (ping / info / pack), per tenant "
        "— the fleet's per-cluster demand in one family",
    ),
    "karpenter_service_solves_total": (
        "counter",
        "tenant, path",
        "completed pack solves per tenant, split by execution path: "
        "'solo' (idle-group fall-through straight into the single-problem "
        "kernel) vs 'batched' (rode a coalesced fleet dispatch); a "
        "healthy busy mesh is batched-dominated, a quiet one solo-only",
    ),
    "karpenter_service_solve_wait_seconds": (
        "histogram",
        "tenant",
        "arrival-to-answer latency of one pack RPC including queue wait, "
        "per tenant — the fairness ground truth: doctor's tenant-"
        "starvation rule flags a tenant whose p99 runs far above the "
        "fleet median from this family's flight deltas",
    ),
    "karpenter_service_refusals_total": (
        "counter",
        "tenant, reason",
        "solves refused under backpressure with an explicit retry-after "
        "hint ('inflight-cap' = that tenant over its concurrent-solve "
        "cap, 'saturated' = the whole mesh's queue bound hit) — refusals "
        "are the DESIGNED overload behavior, never silent queuing",
    ),
    "karpenter_service_inflight": (
        "gauge",
        "tenant",
        "solves currently admitted (queued or on-device) per tenant; "
        "pinned at the inflight cap means that tenant is being shed",
    ),
    "karpenter_service_resident_bytes": (
        "gauge",
        "tenant",
        "device bytes pinned by this tenant's warm solve tensors in the "
        "budgeted cross-tenant resident pool (ops/resident.py); the sum "
        "across tenants stays under service_resident_budget_mb",
    ),
    "karpenter_service_resident_evictions_total": (
        "counter",
        "tenant",
        "times this tenant's WHOLE resident set was dropped as the "
        "coldest entry to fit another tenant under the device-bytes "
        "budget; a hot tenant evicting repeatedly means the budget is "
        "too small for the working set",
    ),
}
