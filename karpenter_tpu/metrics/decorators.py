"""CloudProvider metrics decorator (reference cmd/controller/main.go:46
`metrics.Decorate(cloudProvider)`): wraps every facade method with a
duration histogram and an error counter so the API surface is observable
without touching the facade itself.

Metric names mirror the reference's cloudprovider metrics
(website v0.31 concepts/metrics.md):
- karpenter_cloudprovider_duration_seconds{method, provider}
- karpenter_cloudprovider_errors_total{method, provider, error}
"""

from __future__ import annotations

import functools
from typing import Callable

from karpenter_tpu.metrics.registry import REGISTRY, Registry

_WRAPPED = (
    "create",
    "delete",
    "get",
    "list",
    "get_instance_types",
    "is_drifted",
)


class MetricsCloudProvider:
    """Duration/error recording proxy around a CloudProvider.

    The six facade methods are wrapped ONCE at construction (hot paths
    call them per claim per tick); everything else forwards to the inner
    provider untouched."""

    def __init__(self, inner, registry: Registry = REGISTRY):
        self._inner = inner
        self._registry = registry
        provider = inner.name()
        for method in _WRAPPED:
            setattr(
                self, method, self._wrap(method, getattr(inner, method), provider)
            )

    def name(self) -> str:
        return self._inner.name()

    def __getattr__(self, attr: str):
        return getattr(self._inner, attr)

    def _wrap(self, method: str, fn: Callable, provider: str) -> Callable:
        registry = self._registry
        labels = {"method": method, "provider": provider}
        err_labels = dict(labels)

        @functools.wraps(fn)
        def timed(*args, **kwargs):
            with registry.time(
                "karpenter_cloudprovider_duration_seconds", labels
            ):
                try:
                    return fn(*args, **kwargs)
                except Exception as exc:
                    registry.inc(
                        "karpenter_cloudprovider_errors_total",
                        {**err_labels, "error": type(exc).__name__},
                    )
                    raise

        return timed
