"""Error taxonomy (reference pkg/errors/errors.go + karpenter-core's
cloudprovider error wrappers, cloudprovider.go:101, instance.go:121)."""

from __future__ import annotations

from karpenter_tpu.cloud.fake.backend import (
    CloudAPIError,
    InsufficientCapacityError,
    LaunchTemplateNotFoundError,
)


class NodeClaimNotFoundError(Exception):
    """The machine backing a NodeClaim no longer exists in the cloud."""

    def __init__(self, provider_id: str):
        super().__init__(f"nodeclaim not found: {provider_id}")
        self.provider_id = provider_id


class NoImageResolvedError(Exception):
    """Image resolution produced no launchable template for the node
    class — bad selector terms or every candidate deprecated (the
    reference's amifamily resolver fails the launch with "no amis exist
    given constraints", resolver.go:118-127)."""

    def __init__(self, node_class: str):
        super().__init__(f"no image resolved for node class {node_class!r}")
        self.node_class = node_class


class InsufficientCapacityAggregateError(Exception):
    """Every launch candidate was capacity-constrained (the core treats
    this as retryable-later; the ICE cache keeps the failed pools masked,
    reference cloudprovider.go:101)."""

    def __init__(self, pools):
        super().__init__(f"insufficient capacity in all {len(pools)} pools")
        self.pools = list(pools)


def is_not_found(err: Exception) -> bool:
    return isinstance(err, NodeClaimNotFoundError) or (
        isinstance(err, CloudAPIError)
        and err.code in ("InvalidInstanceID.NotFound", "NotFound")
    )


def is_insufficient_capacity(err: Exception) -> bool:
    return isinstance(
        err, (InsufficientCapacityError, InsufficientCapacityAggregateError)
    )
