"""Remote cluster-store client: a KubeStore mirror over the wire.

`RemoteKubeStore` is a drop-in `KubeStore` (the Operator takes it
unchanged): reads serve from a local mirror, every mutation verb applies
locally AND forwards to the shared `StoreServer`
(service/store_server.py), and a background watch stream applies other
replicas' writes into the mirror — so a standby replica's caches stay
warm and a failover leader starts from the durable state, exactly like
the reference's informer-fed controllers over the kube-apiserver.

Consistency model:

- **Verbs** (put/delete/bind/evict/record_event) run the same
  deterministic KubeStore logic locally, then forward; the server is
  authoritative and assigns each object a resourceVersion.  Local object
  IDENTITY is preserved — controllers that hold a reference to an object
  they just put keep mutating the live mirror object.
- **In-place mutations** (controllers stamp conditions/labels directly,
  e.g. lifecycle.py) are picked up by shadow-diffing: before every Lease
  operation — i.e. at least once per reconcile tick and per renewal —
  `_flush_dirty` pushes every mirror object whose canonical encoding
  drifted from the server's last-known bytes.  A leader crash loses at
  most the unflushed tail of its last tick, the same as crashing before
  those writes.
- **Conflicts**: pushes carry the base resourceVersion; a stale write
  (a deposed leader's straggler) gets ``conflict`` back and the client
  adopts the server's object instead of clobbering.
- **Leases** are never written generically: acquire/renew/release are
  dedicated CAS RPCs, atomic server-side.  A store outage during a lease
  call returns False — a leader that cannot prove its lease abdicates
  (safety over liveness).
- **Failures**: transient socket errors retry with bounded backoff;
  request timeouts raise `StoreUnavailableError` (retryable) instead of
  hanging.  The watch thread reconnects and resyncs from a fresh
  snapshot, so a store restart mid-watch heals itself.
"""

from __future__ import annotations

import logging
import socket
import threading
from typing import Dict, Optional, Tuple

from karpenter_tpu.obs.context import current_trace_id
from karpenter_tpu.service.codec import decode, encode, recv_frame, send_frame
from karpenter_tpu.state.kube import KubeStore
from karpenter_tpu.state.wire import STORE_KINDS, canonical, from_wire, to_wire
from karpenter_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

RETRIES = 3
BACKOFF_S = 0.05  # doubles per attempt


class StoreUnavailableError(ConnectionError):
    """The shared store could not be reached (after retries) or timed
    out.  Retryable: the caller may re-issue the request."""


class RemoteKubeStore(KubeStore):
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8082,
        identity: str = "",
        connect_timeout: float = 5.0,
        request_timeout: float = 10.0,
        start_watch: bool = True,
        clock: Optional[Clock] = None,
    ):
        super().__init__()
        self.host = host
        self.port = port
        self.identity = identity or f"client-{id(self):x}"
        # injectable pacing clock: retry backoff and wait_synced polling
        # sleep on it, so under a FakeClock (the simulator's determinism
        # contract — no raw time.sleep outside utils/clock.py) the waits
        # become simulated time.  Socket TIMEOUTS stay wall-clock: they
        # bound real network reads, which no simulated clock can compress.
        # Caveat of the same contract: pairing a FakeClock with a REAL
        # remote server collapses the backoff to zero wall time, giving
        # the server no recovery window — a FakeClock belongs with
        # simulated peers; real deployments keep the default Clock.
        self.clock = clock or Clock()
        self.connect_timeout = connect_timeout
        self.request_timeout = request_timeout
        self._sock: Optional[socket.socket] = None
        self._rpc_lock = threading.Lock()  # one in-flight RPC per conn
        self._mirror_lock = threading.RLock()  # mirror + rv bookkeeping
        self._lease_mutex = threading.Lock()  # lease ops end-to-end
        self._rvs: Dict[Tuple[str, str], int] = {}
        self._shadow: Dict[Tuple[str, str], str] = {}
        self._lease_rvs: Dict[str, int] = {}
        self._event_rv = 0
        self.synced_rv = 0
        self._stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        self._watch_sock: Optional[socket.socket] = None
        if start_watch:
            self.start_watch()

    # ------------------------------------------------------------- transport
    def _connect(self) -> socket.socket:
        if self._sock is None:
            try:
                self._sock = socket.create_connection(
                    (self.host, self.port), timeout=self.connect_timeout
                )
                self._sock.settimeout(self.request_timeout)
            except OSError as exc:
                raise StoreUnavailableError(
                    f"cluster store at {self.host}:{self.port}: {exc}"
                ) from exc
        return self._sock

    def _close_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _rpc(self, header: dict) -> dict:
        """One request/response with bounded retry on transient errors.
        Mutations here are idempotent re-applied (puts/deletes/lease CAS);
        a retried record_event may at worst duplicate an event line."""
        header = dict(header, identity=self.identity)
        # trace-context propagation (obs/context.py): the tick's trace ID
        # rides the RPC header so the StoreServer records its handling
        # span under the CLIENT's timeline — one trace spans both
        # processes (docs/designs/observability.md)
        tid = current_trace_id()
        if tid:
            header["ctx"] = {"trace_id": tid}
        last: Optional[Exception] = None
        for attempt in range(RETRIES):
            with self._rpc_lock:
                try:
                    sock = self._connect()
                    send_frame(sock, encode(header, {}))
                    response, _ = decode(recv_frame(sock))
                    break
                except socket.timeout as exc:
                    # a timed-out request must surface as retryable, not
                    # hang or half-read the next response off the socket
                    self._close_sock()
                    raise StoreUnavailableError(
                        f"store request {header.get('method')} timed out "
                        f"after {self.request_timeout}s"
                    ) from exc
                except (ConnectionError, OSError) as exc:
                    self._close_sock()
                    last = exc
            if attempt < RETRIES - 1:  # no pointless sleep after the last try
                self.clock.sleep(BACKOFF_S * (2**attempt))
        else:
            raise StoreUnavailableError(
                f"cluster store at {self.host}:{self.port}: {last}"
            ) from last
        if response.get("status") == "error":
            raise RuntimeError(f"store error: {response.get('error')}")
        return response

    # ------------------------------------------------------------ mirroring
    def _record_applied(self, kind: str, key: str, obj, rv: int) -> None:
        if obj is None:
            self._rvs.pop((kind, key), None)
            self._shadow.pop((kind, key), None)
        else:
            self._rvs[(kind, key)] = rv
            self._shadow[(kind, key)] = canonical(obj)
        self.synced_rv = max(self.synced_rv, rv)

    def _locally_dirty(self, kind: str, key: str, obj) -> bool:
        """Whether the mirror object carries state the server has not
        acknowledged yet: its bytes differ from the last server-confirmed
        encoding (or it was never pushed at all — an in-flight create).
        Replication must never overwrite dirty local state; it reconciles
        through the flush -> conflict -> adopt path instead."""
        return self._shadow.get((kind, key)) != canonical(obj)

    def _absorb_events(self, events, remote: bool) -> None:
        """Apply server events to the mirror.

        Own RPC responses (`remote=False`): the local verb already ran —
        keep the local object (identity preserved for callers holding a
        reference) and record rv + the SERVER's bytes as the shadow, so a
        caller mutating the object right after the verb still diffs dirty
        against what the server actually holds.

        Watch events (`remote=True`): another replica wrote.  A clean
        local entry adopts the server object; a DIRTY one is left alone —
        this replica believes it is (or was) the writer, and the next
        flush's rv conflict decides who wins without ever silently
        clobbering either side."""
        with self._mirror_lock:
            for ev in events:
                kind = ev["kind"]
                if kind == "Event":
                    if ev["event_rv"] > self._event_rv:
                        self._event_rv = ev["event_rv"]
                        if remote:
                            self.events.append(from_wire(ev["event"]))
                    continue
                spec = STORE_KINDS.get(kind)
                if spec is None:
                    continue
                _cls, attr, _key_fn = spec
                key, rv = ev["key"], ev["rv"]
                store_dict = getattr(self, attr)
                if ev["verb"] == "delete":
                    local = store_dict.get(key)
                    if rv <= self._rvs.get((kind, key), 0):
                        # a stale echo must not delete a newer object
                        self.synced_rv = max(self.synced_rv, rv)
                        continue
                    if (
                        remote
                        and local is not None
                        and self._locally_dirty(kind, key, local)
                    ):
                        # same dirty protection as the put path: an
                        # in-flight local create/mutation is never
                        # silently dropped by a watch delete — the next
                        # flush's rv conflict resolves who wins
                        self.synced_rv = max(self.synced_rv, rv)
                        continue
                    store_dict.pop(key, None)
                    self._record_applied(kind, key, None, rv)
                    if remote and local is not None:
                        self._notify(kind, "delete", local)
                    continue
                if rv <= self._rvs.get((kind, key), 0):
                    self.synced_rv = max(self.synced_rv, rv)
                    continue
                local = store_dict.get(key)
                server_obj = from_wire(ev["obj"])  # decoded once, reused
                server_enc = canonical(server_obj)
                if not remote:
                    # own write: local object IS the source of this event
                    if local is None:  # deleted locally since; keep that
                        self.synced_rv = max(self.synced_rv, rv)
                        continue
                    self._rvs[(kind, key)] = rv
                    self._shadow[(kind, key)] = server_enc
                    self.synced_rv = max(self.synced_rv, rv)
                    continue
                if local is not None and self._locally_dirty(kind, key, local):
                    self.synced_rv = max(self.synced_rv, rv)
                    continue
                if local is not None and canonical(local) == server_enc:
                    self._record_applied(kind, key, local, rv)
                    continue
                store_dict[key] = server_obj
                self._record_applied(kind, key, server_obj, rv)
                self._notify(kind, "put", server_obj)

    def _forward(self, header: dict) -> dict:
        response = self._rpc(header)
        if response.get("status") == "conflict":
            kind = header["kind"]
            key = header.get("key")
            if key is None:  # put headers carry the object, not the key
                key = STORE_KINDS[kind][2](from_wire(header["obj"]))
            # Whose write won?  If the server's bytes equal what WE tried
            # to push, the "conflict" is our own racing flush (the verb's
            # forward and the renewal thread's flush both shipping the
            # same object): keep the LOCAL object so callers holding a
            # reference keep mutating live state, and just record rv +
            # server bytes.  Only a genuinely foreign write adopts the
            # server's clone.
            server_wire = response.get("obj")
            pushed_wire = header.get("obj")
            if (
                server_wire is not None
                and pushed_wire is not None
                and canonical(from_wire(server_wire))
                == canonical(from_wire(pushed_wire))
            ):
                with self._mirror_lock:
                    local = getattr(self, STORE_KINDS[kind][1]).get(key)
                    if local is not None:
                        self._rvs[(kind, key)] = response["rv"]
                        self._shadow[(kind, key)] = canonical(
                            from_wire(server_wire)
                        )
                        return response
            log.warning(
                "store write conflict on %s/%s (rv %s); adopting server state",
                kind, key, response.get("rv"),
            )
            self._adopt(kind, key, server_wire, response["rv"])
            return response
        self._absorb_events(response.get("events", ()), remote=False)
        return response

    def _adopt(self, kind: str, key: str, obj_wire, rv: int) -> None:
        _cls, attr, _key_fn = STORE_KINDS[kind]
        with self._mirror_lock:
            store_dict = getattr(self, attr)
            if obj_wire is None:
                store_dict.pop(key, None)
                self._record_applied(kind, key, None, rv)
                self.synced_rv = max(self.synced_rv, rv)
            else:
                obj = from_wire(obj_wire)
                store_dict[key] = obj
                self._record_applied(kind, key, obj, rv)

    # -------------------------------------------------------------- flushing
    def _flush_dirty(self) -> None:
        """Push every mirror object whose canonical bytes drifted from the
        server's last-known encoding (in-place mutations by controllers).
        Runs before every lease operation — at least once per tick.

        Cost note: this is an O(mirror) encode per lease operation — the
        full sweep is deliberate, because in-place mutations by design
        leave no hook to mark keys dirty; encoding is the only general
        detector.  The scan runs concurrently with the reconcile thread's
        unlocked in-place mutations, so a single object's encode can
        observe a torn state or raise (dict mutated during iteration):
        such objects are simply skipped this round — they are still dirty
        next round, and the background renewal retries within
        RETRY_PERIOD."""
        with self._mirror_lock:
            dirty = []
            for kind, (_cls, attr, key_fn) in STORE_KINDS.items():
                if kind == "Lease":
                    continue  # leases only move through the CAS RPCs
                for key, obj in list(getattr(self, attr).items()):
                    try:
                        enc = canonical(obj)
                    except RuntimeError:  # torn concurrent mutation
                        continue
                    if self._shadow.get((kind, key)) != enc:
                        dirty.append((kind, key, obj))
        for kind, key, obj in dirty:
            try:
                wire_obj = to_wire(obj)
            except RuntimeError:  # torn since the scan; next round
                continue
            try:
                self._forward(
                    {
                        "method": "put",
                        "kind": kind,
                        "obj": wire_obj,
                        "base_rv": self._rvs.get((kind, key), 0),
                    }
                )
            except StoreUnavailableError:
                raise  # the lease op turns this into abdication
            except Exception:
                # e.g. server-side validation rejecting one object must
                # not abort the rest of the flush or kill a renewal
                log.exception("flush of %s/%s failed; skipping", kind, key)

    # ------------------------------------------------------ overridden verbs
    def _put_and_forward(self, kind: str, obj, local_put) -> object:
        with self._mirror_lock:
            result = local_put(obj)
            base = self._rvs.get((kind, STORE_KINDS[kind][2](obj)), 0)
        self._forward(
            {"method": "put", "kind": kind, "obj": to_wire(obj), "base_rv": base}
        )
        return result

    def put_pod(self, pod):
        return self._put_and_forward("Pod", pod, super().put_pod)

    def put_node(self, node):
        return self._put_and_forward("Node", node, super().put_node)

    def put_node_claim(self, claim):
        return self._put_and_forward("NodeClaim", claim, super().put_node_claim)

    def put_node_pool(self, pool):
        return self._put_and_forward("NodePool", pool, super().put_node_pool)

    def put_node_class(self, nc):
        return self._put_and_forward("NodeClass", nc, super().put_node_class)

    def put_storage_class(self, sc):
        return self._put_and_forward(
            "StorageClass", sc, super().put_storage_class
        )

    def put_pvc(self, pvc):
        return self._put_and_forward(
            "PersistentVolumeClaim", pvc, super().put_pvc
        )

    def put_pdb(self, pdb):
        return self._put_and_forward("PodDisruptionBudget", pdb, super().put_pdb)

    def _delete_and_forward(self, kind: str, key: str, local_delete) -> None:
        with self._mirror_lock:
            base = self._rvs.get((kind, key), 0)
            local_delete(key)
        # base_rv fences a deposed leader's straggler deletes exactly like
        # stale puts: the server rejects if someone wrote the object since
        self._forward(
            {"method": "delete", "kind": kind, "key": key, "base_rv": base}
        )

    def delete_pod(self, key: str) -> None:
        self._delete_and_forward("Pod", key, super().delete_pod)

    def delete_node(self, name: str) -> None:
        self._delete_and_forward("Node", name, super().delete_node)

    def delete_node_claim(self, name: str) -> None:
        self._delete_and_forward("NodeClaim", name, super().delete_node_claim)

    def bind_pod(self, key: str, node_name: str) -> None:
        with self._mirror_lock:
            base = self._rvs.get(("Pod", key), 0)
            super().bind_pod(key, node_name)
        self._forward(
            {
                "method": "bind_pod",
                "kind": "Pod",
                "key": key,
                "node_name": node_name,
                "base_rv": base,
            }
        )

    def evict_pod(self, key: str) -> None:
        with self._mirror_lock:
            base = self._rvs.get(("Pod", key), 0)
            super().evict_pod(key)
        self._forward(
            {"method": "evict_pod", "kind": "Pod", "key": key, "base_rv": base}
        )

    def record_event(self, kind, reason, obj_name, message=""):
        super().record_event(kind, reason, obj_name, message)
        try:
            response = self._rpc(
                {
                    "method": "record_event",
                    "kind": kind,
                    "reason": reason,
                    "obj_name": obj_name,
                    "message": message,
                }
            )
        except StoreUnavailableError as exc:
            # events are advisory; a store blip must not fail a reconcile
            log.warning("event %s/%s not recorded remotely: %s", kind, reason, exc)
            return
        self._event_rv = max(self._event_rv, response.get("event_rv", 0))

    # ---------------------------------------------------------------- leases
    # _lease_mutex serializes each lease operation END-TO-END (header
    # construction through _lease_rvs update): without it the background
    # renewal thread can read its base_rv, lose the CPU to the tick's
    # acquire (which bumps the server's lease_seq), and then land a
    # stale-base renewal — a spurious conflict that abdicates a healthy
    # leader mid-tick.

    def try_acquire_lease(self, name, holder, now, duration_s) -> bool:
        with self._lease_mutex:
            try:
                self._flush_dirty()
                response = self._rpc(
                    {
                        "method": "lease_acquire",
                        "name": name,
                        "holder": holder,
                        "now": now,
                        "duration_s": duration_s,
                    }
                )
            except StoreUnavailableError as exc:
                log.warning("lease acquire unavailable (%s); abdicating", exc)
                return False
            self._lease_rvs[name] = response.get("rv", 0)
            # a fresh acquire's broadcast event is not echoed back to the
            # originator, so credit exactly THAT event's rv here or
            # wait_synced stalls on our own acquires.  (Never the server's
            # global rv: that would claim sync for other replicas' events
            # still queued on our watch socket.)
            self.synced_rv = max(
                self.synced_rv, response.get("lease_event_rv", 0)
            )
            if response.get("lease") is not None:
                with self._mirror_lock:
                    lease = from_wire(response["lease"])
                    self.leases[name] = lease
                    # record rv/shadow too: an installed-but-untracked
                    # Lease reads as permanently dirty, which would make
                    # _absorb_events skip every later foreign Lease event
                    # and freeze a stale holder into this mirror forever
                    self._record_applied(
                        "Lease",
                        name,
                        lease,
                        max(
                            self._rvs.get(("Lease", name), 0),
                            response.get("lease_event_rv", 0),
                        ),
                    )
            return bool(response["acquired"])

    def renew_lease(self, name, holder, now) -> bool:
        with self._lease_mutex:
            try:
                self._flush_dirty()
                response = self._rpc(
                    {
                        "method": "lease_renew",
                        "name": name,
                        "holder": holder,
                        "now": now,
                        "base_rv": self._lease_rvs.get(name),
                    }
                )
            except StoreUnavailableError as exc:
                log.warning("lease renew unavailable (%s); abdicating", exc)
                return False
            self._lease_rvs[name] = response.get("rv", 0)
            self.synced_rv = max(
                self.synced_rv, response.get("lease_event_rv", 0)
            )
            return bool(response["renewed"])

    def release_lease(self, name, holder) -> None:
        with self._lease_mutex:
            try:
                self._flush_dirty()
                response = self._rpc(
                    {"method": "lease_release", "name": name, "holder": holder}
                )
                self._lease_rvs[name] = response.get("rv", 0)
                self.synced_rv = max(
                    self.synced_rv, response.get("lease_event_rv", 0)
                )
            except StoreUnavailableError as exc:  # best-effort: expiry fences
                log.warning("lease release unavailable (%s)", exc)
            with self._mirror_lock:
                lease = self.leases.get(name)
                if lease is not None and lease.holder == holder:
                    lease.holder = ""
                    lease.renewed_at = 0.0
                    # refresh the shadow so the mirror entry stays clean
                    # for later foreign Lease events (see try_acquire)
                    self._record_applied(
                        "Lease",
                        name,
                        lease,
                        self._rvs.get(("Lease", name), 0),
                    )

    # ----------------------------------------------------------------- watch
    def start_watch(self) -> None:
        if self._watch_thread is not None:
            return
        self._watch_thread = threading.Thread(
            target=self._watch_loop,
            daemon=True,
            name=f"store-watch-{self.identity}",
        )
        self._watch_thread.start()

    def _watch_loop(self) -> None:
        import struct

        backoff = BACKOFF_S
        while not self._stop.is_set():
            sock = None
            try:
                sock = socket.create_connection(
                    (self.host, self.port), timeout=self.connect_timeout
                )
                send_frame(
                    sock,
                    encode({"method": "watch", "identity": self.identity}, {}),
                )
                header, _ = decode(recv_frame(sock))
                self._apply_snapshot(header["snapshot"])
                backoff = BACKOFF_S
                # BLOCKING reads: a short recv timeout could fire
                # mid-frame and desync the stream (the consumed prefix is
                # lost and the next read parses payload bytes as a length
                # header).  close() interrupts the blocking recv by
                # closing this socket instead.
                sock.settimeout(None)
                self._watch_sock = sock
                while not self._stop.is_set():
                    frame, _ = decode(recv_frame(sock))
                    self._absorb_events(frame.get("events", ()), remote=True)
            except (ConnectionError, OSError, ValueError, struct.error):
                if self._stop.wait(backoff):
                    break
                backoff = min(backoff * 2, 1.0)
            finally:
                self._watch_sock = None
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass

    def _apply_snapshot(self, snap: dict) -> None:
        """Full-state resync: adopt the server's objects, drop mirror
        entries the server no longer has (store restart / reconnect).
        Locally DIRTY entries are kept as-is — in-flight creates and
        unflushed in-place mutations reconcile through the next flush,
        never by a racing snapshot clobbering them (lost-update hazard)."""
        with self._mirror_lock:
            for kind, (_cls, attr, _key_fn) in STORE_KINDS.items():
                entries = snap["kinds"].get(kind, {})
                store_dict = getattr(self, attr)
                for key in list(store_dict):
                    # drop only keys the server has acknowledged before
                    # (recorded rv): an absent rv means an in-flight local
                    # create the server simply hasn't seen yet
                    if key not in entries and (kind, key) in self._rvs:
                        old = store_dict.pop(key)
                        self._record_applied(kind, key, None, 0)
                        self._notify(kind, "delete", old)
                for key, entry in entries.items():
                    obj_wire, rv = entry["obj"], entry["rv"]
                    local = store_dict.get(key)
                    if local is not None and (
                        rv <= self._rvs.get((kind, key), 0)
                        or self._locally_dirty(kind, key, local)
                    ):
                        self.synced_rv = max(self.synced_rv, rv)
                        continue
                    server_obj = from_wire(obj_wire)  # decoded once, reused
                    if local is not None and canonical(local) == canonical(
                        server_obj
                    ):
                        self._record_applied(kind, key, local, rv)
                        continue
                    store_dict[key] = server_obj
                    self._record_applied(kind, key, server_obj, rv)
                    self._notify(kind, "put", server_obj)
            self.events = [from_wire(e) for e in snap.get("events", [])]
            self._event_rv = snap.get("event_rv", self._event_rv)
            self.synced_rv = max(self.synced_rv, snap.get("rv", 0))

    def wait_synced(self, min_rv: Optional[int] = None, timeout: float = 5.0) -> bool:
        """Block until the mirror has applied every server mutation up to
        ``min_rv`` (default: the server's current rv).  Test/handoff
        helper: a standby asserts its mirror is warm before acting."""
        if min_rv is None:
            min_rv = self._rpc({"method": "stat"})["rv"]
        deadline = self.clock.now() + timeout
        while self.clock.now() < deadline:
            if self.synced_rv >= min_rv:
                return True
            self.clock.sleep(0.005)
        return self.synced_rv >= min_rv

    def close(self) -> None:
        self._stop.set()
        self._close_sock()
        watch_sock = self._watch_sock
        if watch_sock is not None:  # interrupt the blocking watch recv
            try:
                watch_sock.close()
            except OSError:
                pass
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=2.0)
            self._watch_thread = None
