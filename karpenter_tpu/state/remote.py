"""Remote cluster-store client: a KubeStore mirror over the wire.

`RemoteKubeStore` is a drop-in `KubeStore` (the Operator takes it
unchanged): reads serve from a local mirror, every mutation verb applies
locally AND forwards to the shared `StoreServer`
(service/store_server.py), and a background watch stream applies other
replicas' writes into the mirror — so a standby replica's caches stay
warm and a failover leader starts from the durable state, exactly like
the reference's informer-fed controllers over the kube-apiserver.

Consistency model:

- **Verbs** (put/delete/bind/evict/record_event) run the same
  deterministic KubeStore logic locally, then forward; the server is
  authoritative and assigns each object a resourceVersion.  Local object
  IDENTITY is preserved — controllers that hold a reference to an object
  they just put keep mutating the live mirror object.
- **In-place mutations** (controllers stamp conditions/labels directly,
  e.g. lifecycle.py) are picked up by shadow-diffing: before every Lease
  operation — i.e. at least once per reconcile tick and per renewal —
  `_flush_dirty` pushes every mirror object whose canonical encoding
  drifted from the server's last-known bytes.  A leader crash loses at
  most the unflushed tail of its last tick, the same as crashing before
  those writes.
- **Conflicts**: pushes carry the base resourceVersion; a stale write
  (a deposed leader's straggler) gets ``conflict`` back and the client
  adopts the server's object instead of clobbering.
- **Leases** are never written generically: acquire/renew/release are
  dedicated CAS RPCs, atomic server-side.  A store outage during a lease
  call returns False — a leader that cannot prove its lease abdicates
  (safety over liveness).
- **Failures**: transient socket errors retry with bounded backoff;
  request timeouts raise `StoreUnavailableError` (retryable) instead of
  hanging.  The watch thread reconnects and resyncs from a fresh
  snapshot, so a store restart mid-watch heals itself.
- **Sharding**: ``shards=[(host, port), ...]`` spreads the key space
  over N store primaries (service/shardrouter.py owns the hash).  Each
  shard gets its own `StoreChannel` — RPC socket, negotiated codec, and
  an independent watch stream with its own ``(epoch, seq)`` cursor and
  ``synced_rv`` (rv/seq/event_rv spaces are PER SHARD; only per-shard
  comparisons are meaningful).  Writes fan out to the owner shard;
  leases always route to shard 0; the merged watch streams feed one
  mirror, each key touched only by its owner's stream.  A topology
  change (``apply_topology``) tears down every channel and resyncs
  under the servers' migration epoch fence — per-key rvs migrate WITH
  their keys, so dirty-flush fencing survives the move.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.metrics.registry import Registry
from karpenter_tpu.obs.context import current_trace_id
from karpenter_tpu.analysis.sanitizer import (
    make_lock,
    make_rlock,
    note_access,
    note_blocking,
)
from karpenter_tpu.service.codec import (
    CODEC_BIN,
    CODEC_JSON,
    decode_payload,
    encode_payload,
    recv_frame,
    send_frame,
)
from karpenter_tpu.service.shardrouter import LEASE_SHARD, ShardRouter
from karpenter_tpu.service.watchclient import WatchChannelClient
from karpenter_tpu.state.binwire import SCHEMA_FP
from karpenter_tpu.state.kube import KubeStore
from karpenter_tpu.state.wire import (
    STORE_KINDS,
    canonical,
    from_wire,
    materialize,
    to_wire,
)
from karpenter_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

RETRIES = 3
BACKOFF_S = 0.05  # doubles per attempt
EVENTS_CAP = 4096  # mirror-side cluster-event ledger bound (default)


class StoreUnavailableError(ConnectionError):
    """The shared store could not be reached (after retries) or timed
    out.  Retryable: the caller may re-issue the request."""


class StoreChannel:
    """One shard's client-side state: the RPC socket (one in-flight
    request per connection — the framing protocol's invariant, held by
    ``_lock`` across send+recv), the negotiated codec, and this shard's
    independent watch cursor.

    rv/seq/event_rv are PER-SHARD spaces: ``synced_rv`` and
    ``event_rv`` here are this shard's high-water marks, never compared
    against another channel's.  The single-shard deployment is the
    degenerate case — one channel owning every key — which is exactly
    the pre-sharding client."""

    def __init__(self, host: str, port: int, index: int):
        self.host = host
        self.port = port
        self.index = index
        self._lock = make_lock("StoreChannel._lock")
        self.sock: Optional[socket.socket] = None
        self.sock_codec = CODEC_JSON  # negotiated per RPC connection
        self.watch_seq = 0
        self.watch_epoch = ""
        self.synced_rv = 0
        self.event_rv = 0
        # whether this channel has EVER completed a state transfer —
        # the first-sync test for resync accounting.  Inferring it from
        # zeroed cursors is wrong: an epoch change zeroes them too, and
        # the forced snapshot that follows is a genuine resync that
        # must be counted
        self.ever_synced = False
        self.stop = threading.Event()
        self.watch_thread: Optional[threading.Thread] = None
        self.watch_sock: Optional[socket.socket] = None

    def close_sock(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            finally:
                self.sock = None

    def shutdown(self) -> None:
        """Stop this channel's watch loop and sever both sockets.  The
        live watch socket gets a protocol-level shutdown(SHUT_RDWR)
        BEFORE close: close() alone frees the fd but does NOT wake a
        recv already blocked in another thread — the watch thread would
        sit out its whole join timeout on every teardown."""
        self.stop.set()
        self.close_sock()
        watch_sock = self.watch_sock
        if watch_sock is not None:
            try:
                watch_sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass  # already disconnected
            try:
                watch_sock.close()
            except OSError:
                pass
        if self.watch_thread is not None:
            self.watch_thread.join(timeout=2.0)
            self.watch_thread = None


class RemoteKubeStore(KubeStore):
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8082,
        identity: str = "",
        connect_timeout: float = 5.0,
        request_timeout: float = 10.0,
        start_watch: bool = True,
        clock: Optional[Clock] = None,
        codec: str = "auto",
        registry: Optional[Registry] = None,
        events_cap: int = EVENTS_CAP,
        shards: Optional[Sequence[Tuple[str, int]]] = None,
        watch_pace=None,
    ):
        super().__init__()
        self.host = host
        self.port = port
        self.identity = identity or f"client-{id(self):x}"
        # payload-codec preference: "auto" negotiates the compact binary
        # codec per connection (`hello` on the RPC socket, `codecs` on
        # the watch request) and falls back to tagged JSON against a
        # server that doesn't speak it; "json" never negotiates.
        self.codec = codec
        # store-plane telemetry (karpenter_store_rpc_seconds,
        # karpenter_store_bytes_*, StoreResync ledger events) lands in
        # the caller's registry — pass the operator's so the flight
        # recorder and doctor see the client half of the store plane.  A
        # bare default registry drops ledger events by design.
        self.registry = registry or Registry()
        # mirror-side cluster-event ledger bound (Settings.store_events_cap)
        self.events_cap = events_cap
        # injectable pacing clock: retry backoff and wait_synced polling
        # sleep on it, so under a FakeClock (the simulator's determinism
        # contract — no raw time.sleep outside utils/clock.py) the waits
        # become simulated time.  Socket TIMEOUTS stay wall-clock: they
        # bound real network reads, which no simulated clock can compress.
        # Caveat of the same contract: pairing a FakeClock with a REAL
        # remote server collapses the backoff to zero wall time, giving
        # the server no recovery window — a FakeClock belongs with
        # simulated peers; real deployments keep the default Clock.
        self.clock = clock or Clock()
        self.connect_timeout = connect_timeout
        self.request_timeout = request_timeout
        self._mirror_lock = make_rlock("RemoteKubeStore._mirror_lock")  # mirror + rv bookkeeping
        self._lease_mutex = make_lock("RemoteKubeStore._lease_mutex")  # lease ops end-to-end
        self._rvs: Dict[Tuple[str, str], int] = {}
        self._shadow: Dict[Tuple[str, str], str] = {}
        self._lease_rvs: Dict[str, int] = {}
        # one channel per shard; the single-address constructor is the
        # degenerate one-shard topology (the pre-sharding client,
        # byte-for-byte in behavior)
        self._channels: List[StoreChannel] = [
            StoreChannel(h, p, i)
            for i, (h, p) in enumerate(shards or [(host, port)])
        ]
        self._router = ShardRouter(len(self._channels))
        self.watch_resyncs: Dict[str, int] = {}
        self._stop = threading.Event()
        # reconnect-backoff pacing seam (service/watchclient.py): the
        # fleet simulator injects a deterministic pacer; None keeps
        # production's wall-clock exponential backoff
        self._watch_pace = watch_pace
        self._watch_enabled = False
        if start_watch:
            self.start_watch()

    # ----------------------------------------------- single-shard compat view
    # The one-shard deployment's tests and tools observe the client
    # through these names; they read channel 0 (the only channel).
    # Read-only on purpose: all writes go through the owning channel.
    @property
    def _sock_codec(self) -> str:
        return self._channels[0].sock_codec

    @property
    def _watch_seq(self) -> int:
        # last seq contiguously applied from the WATCH stream (snapshot
        # or event frames) — the delta-resync cursor.  NOT synced_rv:
        # that also counts rvs from our own RPC responses, whose
        # neighboring foreign events may still be in flight on the
        # watch socket; replaying from synced_rv could skip them.
        return self._channels[0].watch_seq

    @property
    def _watch_epoch(self) -> str:
        # the epoch that seq belongs to: seq spaces are
        # per-VersionedStore, and the server refuses to treat a cursor
        # from another epoch as covered (a fresh store's seqs could
        # have overtaken a stale cursor — a bare number proves nothing)
        return self._channels[0].watch_epoch

    @property
    def _watch_sock(self):
        return self._channels[0].watch_sock

    @property
    def synced_rv(self) -> int:
        """The mirror's sync high-water mark.  Per-shard rv spaces are
        independent, so the cross-shard aggregate is only meaningful as
        a monotone progress indicator; `wait_synced` compares per shard."""
        return max(c.synced_rv for c in self._channels)

    # ------------------------------------------------------------- transport
    def _owner_for(self, header: dict) -> int:
        """Which shard serves this request: leases pin to LEASE_SHARD,
        cluster events ride the shard owning the object they describe,
        keyed verbs hash by (kind, key)."""
        method = header.get("method")
        if method in ("lease_acquire", "lease_renew", "lease_release"):
            return LEASE_SHARD if self._router.n > 1 else 0
        if method == "record_event":
            return self._router.owner("Event", str(header.get("obj_name", "")))
        kind = header.get("kind")
        if kind:
            key = header.get("key")
            if key is None and header.get("obj") is not None:
                key = STORE_KINDS[kind][2](materialize(header["obj"]))
            return self._router.owner(kind, str(key))
        return 0

    def _connect(self, chan: StoreChannel) -> socket.socket:
        if chan.sock is None:
            try:
                chan.sock = socket.create_connection(
                    (chan.host, chan.port), timeout=self.connect_timeout
                )
                chan.sock.settimeout(self.request_timeout)
            except OSError as exc:
                raise StoreUnavailableError(
                    f"cluster store at {chan.host}:{chan.port}: {exc}"
                ) from exc
            chan.sock_codec = CODEC_JSON
            if self.codec == "auto":
                chan.sock_codec = self._hello(chan.sock)
        return chan.sock

    def _hello(self, sock: socket.socket) -> str:
        """Negotiate the payload codec for this connection.  The hello
        itself rides JSON; a server that doesn't know the method (the
        pre-fleet-scale protocol) answers with an error, which simply
        means: keep speaking JSON."""
        self._tx(
            sock,
            encode_payload(
                {
                    "method": "hello",
                    "codecs": [CODEC_BIN, CODEC_JSON],
                    "schema_fp": SCHEMA_FP,
                    "identity": self.identity,
                },
                CODEC_JSON,
            ),
            CODEC_JSON,
        )
        response = decode_payload(self._rx(sock, CODEC_JSON), CODEC_JSON)
        if (
            response.get("status") == "ok"
            and response.get("codec") == CODEC_BIN
            and response.get("schema_fp") == SCHEMA_FP
        ):
            return CODEC_BIN
        return CODEC_JSON

    # byte accounting wraps the raw frame I/O so every store family in
    # karpenter_store_bytes_{sent,received}_total{codec} counts the wire
    # reality (payload + the 8-byte length prefix)
    def _tx(self, sock: socket.socket, payload: bytes, codec: str) -> None:
        self.registry.inc(
            "karpenter_store_bytes_sent_total",
            {"codec": codec},
            by=len(payload) + 8,
        )
        send_frame(sock, payload)

    def _rx(self, sock: socket.socket, codec: str) -> bytes:
        payload = recv_frame(sock)
        self.registry.inc(
            "karpenter_store_bytes_received_total",
            {"codec": codec},
            by=len(payload) + 8,
        )
        return payload

    def _close_sock(self) -> None:
        for chan in self._channels:
            chan.close_sock()

    def _rpc(self, header: dict, shard: Optional[int] = None) -> dict:
        """One request/response with bounded retry on transient errors,
        routed to the owner shard (``shard`` overrides for control
        traffic like per-shard ``stat``).  Mutations here are idempotent
        re-applied (puts/deletes/lease CAS); a retried record_event may
        at worst duplicate an event line."""
        chan = self._channels[
            self._owner_for(header) if shard is None else shard
        ]
        header = dict(header, identity=self.identity)
        # runtime blocking witness: a store round trip issued while some
        # OTHER lock is held (the lease mutex is the one sanctioned
        # case) is the convoy the static lock-blocking rule predicts —
        # sanitized runs observe it here.  No-op in production.
        note_blocking("_rpc")
        # trace-context propagation (obs/context.py): the tick's trace ID
        # rides the RPC header so the StoreServer records its handling
        # span under the CLIENT's timeline — one trace spans both
        # processes (docs/designs/observability.md)
        tid = current_trace_id()
        if tid:
            header["ctx"] = {"trace_id": tid}
        last: Optional[Exception] = None
        t0 = time.perf_counter()
        for attempt in range(RETRIES):
            with chan._lock:
                try:
                    sock = self._connect(chan)
                    codec = chan.sock_codec
                    self._tx(
                        sock,
                        encode_payload(self._prep(header, codec), codec),
                        codec,
                    )
                    response = decode_payload(self._rx(sock, codec), codec)
                    break
                except socket.timeout as exc:
                    # a timed-out request must surface as retryable, not
                    # hang or half-read the next response off the socket
                    chan.close_sock()
                    raise StoreUnavailableError(
                        f"store request {header.get('method')} timed out "
                        f"after {self.request_timeout}s"
                    ) from exc
                except (ConnectionError, OSError, ValueError) as exc:
                    # ValueError: a malformed/truncated response frame
                    # (e.g. a fault injector tearing bytes) poisons the
                    # connection — reconnect, same as a transport drop
                    chan.close_sock()
                    last = exc
            if attempt < RETRIES - 1:  # no pointless sleep after the last try
                self.clock.sleep(BACKOFF_S * (2**attempt))
        else:
            raise StoreUnavailableError(
                f"cluster store at {chan.host}:{chan.port}: {last}"
            ) from last
        self.registry.observe(
            "karpenter_store_rpc_seconds",
            time.perf_counter() - t0,
            {"method": str(header.get("method", "?"))},
        )
        if response.get("status") == "error":
            raise RuntimeError(f"store error: {response.get('error')}")
        return response

    @staticmethod
    def _prep(header: dict, codec: str) -> dict:
        """Verb headers carry the live OBJECT in ``obj`` (the binary
        codec ships it natively — no tree build at all); the JSON path
        converts to the tagged tree here, at encode time."""
        obj = header.get("obj")
        if (
            obj is not None
            and codec == CODEC_JSON
            and not isinstance(obj, dict)
        ):
            header = dict(header, obj=to_wire(obj))
        return header

    # ------------------------------------------------------------ mirroring
    def _record_applied(
        self, chan: StoreChannel, kind: str, key: str, obj, rv: int
    ) -> None:
        if obj is None:
            self._rvs.pop((kind, key), None)
            self._shadow.pop((kind, key), None)
        else:
            self._rvs[(kind, key)] = rv
            self._shadow[(kind, key)] = canonical(obj)
        chan.synced_rv = max(chan.synced_rv, rv)

    def _locally_dirty(self, kind: str, key: str, obj) -> bool:
        """Whether the mirror object carries state the server has not
        acknowledged yet: its bytes differ from the last server-confirmed
        encoding (or it was never pushed at all — an in-flight create).
        Replication must never overwrite dirty local state; it reconciles
        through the flush -> conflict -> adopt path instead."""
        return self._shadow.get((kind, key)) != canonical(obj)

    def _absorb_events(self, chan: StoreChannel, events, remote: bool) -> None:
        """Apply server events to the mirror.  ``chan`` is the shard
        the events arrived from: its rv/event_rv spaces are the only
        ones these events may be compared against or credited to.

        Own RPC responses (`remote=False`): the local verb already ran —
        keep the local object (identity preserved for callers holding a
        reference) and record rv + the SERVER's bytes as the shadow, so a
        caller mutating the object right after the verb still diffs dirty
        against what the server actually holds.

        Watch events (`remote=True`): another replica wrote.  A clean
        local entry adopts the server object; a DIRTY one is left alone —
        this replica believes it is (or was) the writer, and the next
        flush's rv conflict decides who wins without ever silently
        clobbering either side."""
        with self._mirror_lock:
            for ev in events:
                kind = ev["kind"]
                if kind == "Event":
                    if ev["event_rv"] > chan.event_rv:
                        chan.event_rv = ev["event_rv"]
                        if remote:
                            self.events.append(materialize(ev["event"]))
                            if len(self.events) > self.events_cap:
                                del self.events[
                                    : len(self.events) - self.events_cap
                                ]
                    continue
                spec = STORE_KINDS.get(kind)
                if spec is None:
                    continue
                _cls, attr, _key_fn = spec
                key, rv = ev["key"], ev["rv"]
                store_dict = getattr(self, attr)
                if ev["verb"] == "delete":
                    local = store_dict.get(key)
                    if rv <= self._rvs.get((kind, key), 0):
                        # a stale echo must not delete a newer object
                        chan.synced_rv = max(chan.synced_rv, rv)
                        continue
                    if (
                        remote
                        and local is not None
                        and self._locally_dirty(kind, key, local)
                    ):
                        # same dirty protection as the put path: an
                        # in-flight local create/mutation is never
                        # silently dropped by a watch delete — the next
                        # flush's rv conflict resolves who wins
                        chan.synced_rv = max(chan.synced_rv, rv)
                        continue
                    store_dict.pop(key, None)
                    self._record_applied(chan, kind, key, None, rv)
                    if remote and local is not None:
                        self._notify(kind, "delete", local)
                    continue
                if rv <= self._rvs.get((kind, key), 0):
                    chan.synced_rv = max(chan.synced_rv, rv)
                    continue
                local = store_dict.get(key)
                server_obj = materialize(ev["obj"])  # decoded once, reused
                server_enc = canonical(server_obj)
                if not remote:
                    # own write: local object IS the source of this event
                    if local is None:  # deleted locally since; keep that
                        chan.synced_rv = max(chan.synced_rv, rv)
                        continue
                    self._rvs[(kind, key)] = rv
                    self._shadow[(kind, key)] = server_enc
                    chan.synced_rv = max(chan.synced_rv, rv)
                    continue
                if local is not None and self._locally_dirty(kind, key, local):
                    chan.synced_rv = max(chan.synced_rv, rv)
                    continue
                if local is not None and canonical(local) == server_enc:
                    self._record_applied(chan, kind, key, local, rv)
                    continue
                store_dict[key] = server_obj
                self._record_applied(chan, kind, key, server_obj, rv)
                self._notify(kind, "put", server_obj)

    def _forward(self, header: dict) -> dict:
        shard = self._owner_for(header)
        chan = self._channels[shard]
        response = self._rpc(header, shard=shard)
        if response.get("status") == "conflict":
            kind = header["kind"]
            key = header.get("key")
            if key is None:  # put headers carry the object, not the key
                key = STORE_KINDS[kind][2](materialize(header["obj"]))
            # Whose write won?  If the server's bytes equal what WE tried
            # to push, the "conflict" is our own racing flush (the verb's
            # forward and the renewal thread's flush both shipping the
            # same object): keep the LOCAL object so callers holding a
            # reference keep mutating live state, and just record rv +
            # server bytes.  Only a genuinely foreign write adopts the
            # server's clone.
            server_wire = response.get("obj")
            pushed_wire = header.get("obj")
            if (
                server_wire is not None
                and pushed_wire is not None
                and canonical(materialize(server_wire))
                == canonical(materialize(pushed_wire))
            ):
                with self._mirror_lock:
                    local = getattr(self, STORE_KINDS[kind][1]).get(key)
                    if local is not None:
                        self._rvs[(kind, key)] = response["rv"]
                        self._shadow[(kind, key)] = canonical(
                            materialize(server_wire)
                        )
                        return response
            log.warning(
                "store write conflict on %s/%s (rv %s); adopting server state",
                kind, key, response.get("rv"),
            )
            self._adopt(chan, kind, key, server_wire, response["rv"])
            return response
        self._absorb_events(chan, response.get("events", ()), remote=False)
        return response

    def _adopt(
        self, chan: StoreChannel, kind: str, key: str, obj_wire, rv: int
    ) -> None:
        _cls, attr, _key_fn = STORE_KINDS[kind]
        with self._mirror_lock:
            # lockset witness: the mirror is written from the watch
            # thread AND from controller-thread RPC responses — the
            # mirror lock must be their common lockset
            note_access("RemoteKubeStore.mirror")
            store_dict = getattr(self, attr)
            if obj_wire is None:
                store_dict.pop(key, None)
                self._record_applied(chan, kind, key, None, rv)
                chan.synced_rv = max(chan.synced_rv, rv)
            else:
                obj = materialize(obj_wire)
                store_dict[key] = obj
                self._record_applied(chan, kind, key, obj, rv)

    # -------------------------------------------------------------- flushing
    def _flush_dirty(self) -> None:
        """Push every mirror object whose canonical bytes drifted from the
        server's last-known encoding (in-place mutations by controllers).
        Runs before every lease operation — at least once per tick.

        Cost note: this is an O(mirror) encode per lease operation — the
        full sweep is deliberate, because in-place mutations by design
        leave no hook to mark keys dirty; encoding is the only general
        detector.  The scan runs concurrently with the reconcile thread's
        unlocked in-place mutations, so a single object's encode can
        observe a torn state or raise (dict mutated during iteration):
        such objects are simply skipped this round — they are still dirty
        next round, and the background renewal retries within
        RETRY_PERIOD."""
        with self._mirror_lock:
            dirty = []
            for kind, (_cls, attr, key_fn) in STORE_KINDS.items():
                if kind == "Lease":
                    continue  # leases only move through the CAS RPCs
                for key, obj in list(getattr(self, attr).items()):
                    try:
                        enc = canonical(obj)
                    except RuntimeError:  # torn concurrent mutation
                        continue
                    if self._shadow.get((kind, key)) != enc:
                        dirty.append((kind, key, obj))
        for kind, key, obj in dirty:
            try:
                wire_obj = to_wire(obj)
            except RuntimeError:  # torn since the scan; next round
                continue
            try:
                self._forward(
                    {
                        "method": "put",
                        "kind": kind,
                        "obj": wire_obj,
                        "base_rv": self._rvs.get((kind, key), 0),
                    }
                )
            except StoreUnavailableError:
                raise  # the lease op turns this into abdication
            except Exception:
                # e.g. server-side validation rejecting one object must
                # not abort the rest of the flush or kill a renewal
                log.exception("flush of %s/%s failed; skipping", kind, key)

    # ------------------------------------------------------ overridden verbs
    def _put_and_forward(self, kind: str, obj, local_put) -> object:
        with self._mirror_lock:
            result = local_put(obj)
            base = self._rvs.get((kind, STORE_KINDS[kind][2](obj)), 0)
        # the live object rides the header; `_prep` tree-ifies it only
        # when the connection negotiated down to JSON
        self._forward(
            {"method": "put", "kind": kind, "obj": obj, "base_rv": base}
        )
        return result

    def put_pod(self, pod):
        return self._put_and_forward("Pod", pod, super().put_pod)

    def put_node(self, node):
        return self._put_and_forward("Node", node, super().put_node)

    def put_node_claim(self, claim):
        return self._put_and_forward("NodeClaim", claim, super().put_node_claim)

    def put_node_pool(self, pool):
        return self._put_and_forward("NodePool", pool, super().put_node_pool)

    def put_node_class(self, nc):
        return self._put_and_forward("NodeClass", nc, super().put_node_class)

    def put_storage_class(self, sc):
        return self._put_and_forward(
            "StorageClass", sc, super().put_storage_class
        )

    def put_pvc(self, pvc):
        return self._put_and_forward(
            "PersistentVolumeClaim", pvc, super().put_pvc
        )

    def put_pdb(self, pdb):
        return self._put_and_forward("PodDisruptionBudget", pdb, super().put_pdb)

    def _delete_and_forward(self, kind: str, key: str, local_delete) -> None:
        with self._mirror_lock:
            base = self._rvs.get((kind, key), 0)
            local_delete(key)
        # base_rv fences a deposed leader's straggler deletes exactly like
        # stale puts: the server rejects if someone wrote the object since
        self._forward(
            {"method": "delete", "kind": kind, "key": key, "base_rv": base}
        )

    def delete_pod(self, key: str) -> None:
        self._delete_and_forward("Pod", key, super().delete_pod)

    def delete_node(self, name: str) -> None:
        self._delete_and_forward("Node", name, super().delete_node)

    def delete_node_claim(self, name: str) -> None:
        self._delete_and_forward("NodeClaim", name, super().delete_node_claim)

    def bind_pod(self, key: str, node_name: str) -> None:
        with self._mirror_lock:
            base = self._rvs.get(("Pod", key), 0)
            super().bind_pod(key, node_name)
        self._forward(
            {
                "method": "bind_pod",
                "kind": "Pod",
                "key": key,
                "node_name": node_name,
                "base_rv": base,
            }
        )

    def evict_pod(self, key: str) -> None:
        with self._mirror_lock:
            base = self._rvs.get(("Pod", key), 0)
            super().evict_pod(key)
        self._forward(
            {"method": "evict_pod", "kind": "Pod", "key": key, "base_rv": base}
        )

    def record_event(self, kind, reason, obj_name, message=""):
        super().record_event(kind, reason, obj_name, message)
        with self._mirror_lock:
            # the cap applies to OWN events too, not just watch-absorbed
            # foreign ones (the server's echo of this event is skipped by
            # the event_rv check, so this is the only trim site for it)
            if len(self.events) > self.events_cap:
                del self.events[: len(self.events) - self.events_cap]
        header = {
            "method": "record_event",
            "kind": kind,
            "reason": reason,
            "obj_name": obj_name,
            "message": message,
        }
        chan = self._channels[self._owner_for(header)]
        try:
            response = self._rpc(header, shard=chan.index)
        except StoreUnavailableError as exc:
            # events are advisory; a store blip must not fail a reconcile
            log.warning("event %s/%s not recorded remotely: %s", kind, reason, exc)
            return
        chan.event_rv = max(chan.event_rv, response.get("event_rv", 0))

    # ---------------------------------------------------------------- leases
    # _lease_mutex serializes each lease operation END-TO-END (header
    # construction through _lease_rvs update): without it the background
    # renewal thread can read its base_rv, lose the CPU to the tick's
    # acquire (which bumps the server's lease_seq), and then land a
    # stale-base renewal — a spurious conflict that abdicates a healthy
    # leader mid-tick.

    @property
    def _lease_chan(self) -> StoreChannel:
        """Leases pin to LEASE_SHARD under every topology — the
        leadership CAS space lives on exactly one shard."""
        return self._channels[LEASE_SHARD if self._router.n > 1 else 0]

    def try_acquire_lease(self, name, holder, now, duration_s) -> bool:
        with self._lease_mutex:
            chan = self._lease_chan
            try:
                self._flush_dirty()
                response = self._rpc(
                    {
                        "method": "lease_acquire",
                        "name": name,
                        "holder": holder,
                        "now": now,
                        "duration_s": duration_s,
                    }
                )
            except StoreUnavailableError as exc:
                log.warning("lease acquire unavailable (%s); abdicating", exc)
                return False
            self._lease_rvs[name] = response.get("rv", 0)
            # a fresh acquire's broadcast event is not echoed back to the
            # originator, so credit exactly THAT event's rv here or
            # wait_synced stalls on our own acquires.  (Never the server's
            # global rv: that would claim sync for other replicas' events
            # still queued on our watch socket.)
            chan.synced_rv = max(
                chan.synced_rv, response.get("lease_event_rv", 0)
            )
            if response.get("lease") is not None:
                with self._mirror_lock:
                    lease = from_wire(response["lease"])
                    self.leases[name] = lease
                    # record rv/shadow too: an installed-but-untracked
                    # Lease reads as permanently dirty, which would make
                    # _absorb_events skip every later foreign Lease event
                    # and freeze a stale holder into this mirror forever
                    self._record_applied(
                        chan,
                        "Lease",
                        name,
                        lease,
                        max(
                            self._rvs.get(("Lease", name), 0),
                            response.get("lease_event_rv", 0),
                        ),
                    )
            return bool(response["acquired"])

    def renew_lease(self, name, holder, now) -> bool:
        with self._lease_mutex:
            try:
                self._flush_dirty()
                response = self._rpc(
                    {
                        "method": "lease_renew",
                        "name": name,
                        "holder": holder,
                        "now": now,
                        "base_rv": self._lease_rvs.get(name),
                    }
                )
            except StoreUnavailableError as exc:
                log.warning("lease renew unavailable (%s); abdicating", exc)
                return False
            self._lease_rvs[name] = response.get("rv", 0)
            chan = self._lease_chan
            chan.synced_rv = max(
                chan.synced_rv, response.get("lease_event_rv", 0)
            )
            return bool(response["renewed"])

    def release_lease(self, name, holder) -> None:
        with self._lease_mutex:
            chan = self._lease_chan
            try:
                self._flush_dirty()
                response = self._rpc(
                    {"method": "lease_release", "name": name, "holder": holder}
                )
                self._lease_rvs[name] = response.get("rv", 0)
                chan.synced_rv = max(
                    chan.synced_rv, response.get("lease_event_rv", 0)
                )
            except StoreUnavailableError as exc:  # best-effort: expiry fences
                log.warning("lease release unavailable (%s)", exc)
            with self._mirror_lock:
                lease = self.leases.get(name)
                if lease is not None and lease.holder == holder:
                    lease.holder = ""
                    lease.renewed_at = 0.0
                    # refresh the shadow so the mirror entry stays clean
                    # for later foreign Lease events (see try_acquire)
                    self._record_applied(
                        chan,
                        "Lease",
                        name,
                        lease,
                        self._rvs.get(("Lease", name), 0),
                    )

    # ----------------------------------------------------------------- watch
    def start_watch(self) -> None:
        self._watch_enabled = True
        for chan in self._channels:
            if chan.watch_thread is not None:
                continue
            chan.watch_thread = threading.Thread(
                target=self._watch_loop,
                args=(chan,),
                daemon=True,
                name=f"store-watch-{self.identity}-s{chan.index}",
            )
            chan.watch_thread.start()

    def _watch_loop(self, chan: StoreChannel) -> None:
        # the dial/handshake/backoff/resync choreography is the SHARED
        # watch-client primitive (service/watchclient.py — one
        # definition with the read-replica follower); this mirror
        # contributes the handshake contents, the frame handler, and
        # the byte-counting tx/rx.  One loop per shard channel: each
        # stream carries only its shard's keys and advances only its
        # shard's (epoch, seq) cursor.
        def dial():
            sock = socket.create_connection(
                (chan.host, chan.port), timeout=self.connect_timeout
            )
            sock.settimeout(self.request_timeout)
            return sock

        def hello() -> dict:
            # delta resync: present the last seq this mirror applied
            # from this shard's watch stream; the server replays just
            # the gap when its replay log still covers it, and falls
            # back to a full snapshot when compaction has passed us by
            return {
                "method": "watch",
                "identity": self.identity,
                "codecs": (
                    [CODEC_BIN, CODEC_JSON]
                    if self.codec == "auto"
                    else [CODEC_JSON]
                ),
                "schema_fp": SCHEMA_FP,
                "since_seq": chan.watch_seq,
                "epoch": chan.watch_epoch,
            }

        def set_live(sock) -> None:
            chan.watch_sock = sock

        WatchChannelClient(
            dial=dial,
            hello=hello,
            tx=lambda sock, payload: self._tx(sock, payload, CODEC_JSON),
            rx=self._rx,
            on_epoch=lambda epoch: self._note_epoch(chan, epoch),
            on_legacy_snapshot=lambda snap: self._apply_snapshot(chan, snap),
            on_frame=lambda frame, initial: self._handle_watch_frame(
                chan, frame, initial=initial
            ),
            stop=chan.stop,
            on_live=set_live,
            backoff_s=BACKOFF_S,
            pace=self._watch_pace,
        ).run()

    def _handle_watch_frame(
        self, chan: StoreChannel, frame: dict, initial: bool = False
    ) -> None:
        """One pushed watch frame: ordinary events, or a resync the
        server forced (reconnect gap, or this client fell so far behind
        that its bounded queue overflowed and was coalesced)."""
        ftype = frame.get("type")
        if ftype == "events":
            self._absorb_events(chan, frame.get("events", ()), remote=True)
            # frames arrive in seq order on one stream; assignment (not
            # max) lets a post-restart server's fresh, lower seq epoch
            # take over (see _apply_snapshot)
            chan.watch_seq = frame.get("seq", chan.watch_seq)
            return
        if ftype != "resync":
            return
        # a mid-stream resync may announce a NEW epoch (a read replica
        # that had to full-resync from a restarted primary rotates its
        # own) — the reset must land before the payload applies
        if "epoch" in frame:
            self._note_epoch(chan, str(frame.get("epoch") or ""))
        mode = frame.get("mode", "snapshot")
        first_sync = initial and not chan.ever_synced
        chan.ever_synced = True
        if not first_sync:
            # a genuine resync (not the very first state transfer):
            # count it and put it on the decision ledger — a mirror that
            # keeps resyncing is either too slow or repeatedly cut off
            self.watch_resyncs[mode] = self.watch_resyncs.get(mode, 0) + 1
            self.registry.inc(
                "karpenter_store_resync_total", {"kind": mode}
            )
            self.registry.event(
                "StoreResync", mode=mode, identity=self.identity
            )
        if mode == "snapshot":
            self._apply_snapshot(chan, frame["snapshot"])
        else:
            self._absorb_events(chan, frame.get("events", ()), remote=True)
        chan.watch_seq = frame.get("seq", chan.watch_seq)

    def _note_epoch(self, chan: StoreChannel, epoch: str) -> None:
        """Adopt the server's epoch id, resetting every old-space cursor
        the moment a CHANGE is detected — before any payload applies.
        Doing it at detection time (not at snapshot-apply time) matters:
        if the connection drops between the ack and the sync frame, the
        next reconnect must still present a new-epoch-consistent cursor
        (seq 0), never a new epoch label over an old-space seq that the
        busy new server's log might falsely 'cover'."""
        with self._mirror_lock:
            if epoch == chan.watch_epoch:
                return
            if chan.watch_epoch:
                # genuine epoch change: old-space cursors are meaningless
                chan.watch_seq = 0
                chan.synced_rv = 0
                # per-key rvs drop to 0 for CLEAN keys — 0 keeps the
                # snapshot deletion sweep working (the key is still
                # provably server-acked) while never vetoing adoption of
                # new-space rvs.  Dirty keys keep their entries and heal
                # through flush -> fence conflict -> adopt.  Only THIS
                # shard's keys: other shards' rv spaces didn't rotate.
                for (kind, key) in list(self._rvs):
                    if self._router.owner(kind, key) != chan.index:
                        continue
                    _cls, attr, _key_fn = STORE_KINDS[kind]
                    obj = getattr(self, attr).get(key)
                    if obj is None or not self._locally_dirty(
                        kind, key, obj
                    ):
                        self._rvs[(kind, key)] = 0
            chan.watch_epoch = epoch

    def _apply_snapshot(self, chan: StoreChannel, snap: dict) -> None:
        """Full-state resync for ONE shard: adopt the server's objects,
        drop mirror entries this shard owns that the server no longer
        has (store restart / reconnect).  The deletion sweep is
        ownership-restricted — shard i's snapshot says nothing about
        keys other shards hold.  Locally DIRTY entries are kept as-is —
        in-flight creates and unflushed in-place mutations reconcile
        through the next flush, never by a racing snapshot clobbering
        them (lost-update hazard)."""
        with self._mirror_lock:
            for kind, (_cls, attr, _key_fn) in STORE_KINDS.items():
                entries = snap["kinds"].get(kind, {})
                store_dict = getattr(self, attr)
                for key in list(store_dict):
                    # drop only keys the server has acknowledged before
                    # (recorded rv): an absent rv means an in-flight local
                    # create the server simply hasn't seen yet
                    if (
                        key not in entries
                        and (kind, key) in self._rvs
                        and self._router.owner(kind, key) == chan.index
                    ):
                        old = store_dict.pop(key)
                        self._record_applied(chan, kind, key, None, 0)
                        self._notify(kind, "delete", old)
                for key, entry in entries.items():
                    obj_wire, rv = entry["obj"], entry["rv"]
                    local = store_dict.get(key)
                    if local is not None and (
                        rv <= self._rvs.get((kind, key), 0)
                        or self._locally_dirty(kind, key, local)
                    ):
                        chan.synced_rv = max(chan.synced_rv, rv)
                        continue
                    server_obj = materialize(obj_wire)  # decoded once
                    if local is not None and canonical(local) == canonical(
                        server_obj
                    ):
                        self._record_applied(chan, kind, key, local, rv)
                        continue
                    store_dict[key] = server_obj
                    self._record_applied(chan, kind, key, server_obj, rv)
                    self._notify(kind, "put", server_obj)
            snap_events = snap.get("events", [])
            snap_event_rv = snap.get("event_rv", chan.event_rv)
            if self._router.n <= 1:
                # single shard: the server ledger IS the ledger — adopt
                # it wholesale.  The cap is an INVARIANT, not a
                # steady-state tendency: a snapshot from a server with a
                # larger ledger adopts only the newest events_cap entries
                self.events = [
                    materialize(e)
                    for e in snap_events[-self.events_cap :]
                ]
                chan.event_rv = snap_event_rv
            else:
                # merged ledger: this shard contributes only the events
                # the mirror hasn't credited from it yet (its event_rv
                # delta) — replacing would wipe the other shards' events
                fresh = snap_event_rv - chan.event_rv
                if fresh > 0:
                    for e in snap_events[-fresh:]:
                        self.events.append(materialize(e))
                    chan.event_rv = snap_event_rv
                    if len(self.events) > self.events_cap:
                        del self.events[
                            : len(self.events) - self.events_cap
                        ]
            # synced_rv MAXES: it also credits rvs from our own RPC
            # responses, which the origin-skipping watch stream never
            # echoes — assignment could regress below a racing own write
            # and stall wait_synced forever.  Epoch changes already
            # zeroed it in _note_epoch, so maxing never resurrects an
            # old space.  watch_seq assigns: only the watch stream
            # advances it, and in-epoch a snapshot's seq is >= anything
            # it delivered.
            chan.synced_rv = max(chan.synced_rv, snap.get("rv", 0))
            chan.watch_seq = snap.get("seq", 0)
            chan.ever_synced = True  # legacy path counts as a transfer too

    def wait_synced(self, min_rv: Optional[int] = None, timeout: float = 5.0) -> bool:
        """Block until the mirror has applied every server mutation up to
        ``min_rv`` (default: every shard's current rv).  Test/handoff
        helper: a standby asserts its mirror is warm before acting.

        With an explicit ``min_rv`` the aggregate high-water mark is
        compared (single-shard semantics — the caller got the target
        from one shard's response); with the default, each shard is
        statted and waited on in ITS OWN rv space."""
        if min_rv is None:
            targets = [
                (chan, self._rpc({"method": "stat"}, shard=chan.index)["rv"])
                for chan in self._channels
            ]
            synced = lambda: all(c.synced_rv >= t for c, t in targets)
        else:
            synced = lambda: self.synced_rv >= min_rv
        deadline = self.clock.now() + timeout
        while self.clock.now() < deadline:
            if synced():
                return True
            self.clock.sleep(0.005)
        return synced()

    # ------------------------------------------------------------- topology
    def apply_topology(self, addresses: Sequence[Tuple[str, int]]) -> None:
        """Re-point this client at a new shard topology (after a
        coordinator-driven reshard).  Tears down every channel (watch
        loops included), swaps the router atomically under the mirror
        lock, and resyncs from scratch cursors.  Per-key rvs are KEPT:
        they migrated with their keys server-side, so dirty-flush
        fencing still lines up at the new owners; the fresh channels'
        empty watch_epoch means the first epoch adoption does not zero
        them (see ``_note_epoch``)."""
        for chan in self._channels:
            chan.shutdown()
        with self._mirror_lock:
            self._channels = [
                StoreChannel(h, p, i) for i, (h, p) in enumerate(addresses)
            ]
            self._router = ShardRouter(len(self._channels))
        if self._watch_enabled and not self._stop.is_set():
            self.start_watch()

    def close(self) -> None:
        self._stop.set()
        for chan in self._channels:
            chan.shutdown()
