"""Wire codec for cluster-store objects: typed JSON, no pickling.

The shared-store subsystem (service/store_server.py + state/remote.py)
ships every KubeStore object over the same length-prefixed socket frames
the solver sidecar uses (service/codec.py).  Like the solver protocol,
the store protocol must never execute peer-controlled payloads, so
objects travel as tagged JSON trees, not pickles: each node is either a
JSON native or a one-key tag —

    {"!dc": "ClassName", "f": {field: value, ...}}   dataclass
    {"!res": {axis: float}}                          Resources (canonical units)
    {"!req": {...normalized Requirement fields...}}  Requirement
    {"!reqs": [...]}                                 Requirements conjunction
    {"!t": [...]}                                    tuple
    {"!fs": [...]}                                   frozenset (sorted)
    {"!m": {...}}                                    plain mapping

Only classes in the registry decode — an unknown tag is an error, never
an attribute lookup on arbitrary names.  ``canonical`` (sort_keys dumps)
is the byte form used for resourceVersion shadow-diffing on the client:
two semantically equal objects encode to equal bytes.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Tuple

from karpenter_tpu.api.objects import (
    BlockDeviceMapping,
    Disruption,
    NodeClaim,
    NodeClass,
    NodePool,
    Overhead,
    PersistentVolumeClaim,
    Pod,
    PodAffinityTerm,
    SelectorTerm,
    StorageClass,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from karpenter_tpu.api.requirements import Requirement, Requirements
from karpenter_tpu.api.resources import Resources
from karpenter_tpu.state.kube import Node, PodDisruptionBudget
from karpenter_tpu.utils.leader import Lease

# the CLOSED set of classes the store protocol itself ships.  The binary
# codec (state/binwire.py) derives its class-id table and schema
# fingerprint from exactly this tuple, so it must stay static: classes
# added later via register_dataclass extend the tagged-JSON codec only
# (the simulator's trace lines), never the negotiated binary protocol.
STORE_WIRE_CLASSES = (
    BlockDeviceMapping,
    Disruption,
    Lease,
    Node,
    NodeClaim,
    NodeClass,
    NodePool,
    Overhead,
    PersistentVolumeClaim,
    Pod,
    PodAffinityTerm,
    PodDisruptionBudget,
    SelectorTerm,
    StorageClass,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)

_DATACLASSES = {cls.__name__: cls for cls in STORE_WIRE_CLASSES}

def register_dataclass(cls: type) -> type:
    """Extend the wire codec with an additional dataclass.

    The store protocol itself only ever ships the closed set above, but
    the codec is reused by other subsystems — the cluster simulator's
    trace (sim/trace.py) encodes fake-cloud objects (MachineShape,
    FakeImage, ...) through the same tagged-JSON rules.  Registration is
    idempotent; a NAME collision with a different class is an error, so
    no registered kind can ever be silently re-bound."""
    existing = _DATACLASSES.get(cls.__name__)
    if existing is not None and existing is not cls:
        raise ValueError(
            f"wire dataclass name collision: {cls.__name__!r} already "
            f"registered to {existing.__module__}.{existing.__qualname__}"
        )
    _DATACLASSES[cls.__name__] = cls
    return cls


# kind name -> (class, KubeStore dict attribute, key function)
STORE_KINDS: Dict[str, Tuple[type, str, Any]] = {
    "Pod": (Pod, "pods", lambda o: o.key()),
    "Node": (Node, "nodes", lambda o: o.name),
    "NodeClaim": (NodeClaim, "node_claims", lambda o: o.name),
    "NodePool": (NodePool, "node_pools", lambda o: o.name),
    "NodeClass": (NodeClass, "node_classes", lambda o: o.name),
    "PodDisruptionBudget": (PodDisruptionBudget, "pdbs", lambda o: o.name),
    "StorageClass": (StorageClass, "storage_classes", lambda o: o.name),
    "PersistentVolumeClaim": (
        PersistentVolumeClaim,
        "pvcs",
        lambda o: o.key(),
    ),
    "Lease": (Lease, "leases", lambda o: o.name),
}


def to_wire(value: Any) -> Any:
    """Object tree -> tagged-JSON tree (see module docstring)."""
    if isinstance(value, Resources):
        return {"!res": value.to_dict()}
    if isinstance(value, Requirements):
        return {"!reqs": [to_wire(r) for r in value]}
    if isinstance(value, Requirement):
        return {
            "!req": {
                "key": value.key,
                "complement": value.complement,
                "values": sorted(value.values),
                "gt": value.greater_than,
                "lt": value.less_than,
                "min_values": value.min_values,
                "absent_ok": value.absent_ok,
            }
        }
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return {
            "!dc": type(value).__name__,
            "f": {
                f.name: to_wire(getattr(value, f.name))
                for f in dataclasses.fields(value)
            },
        }
    if isinstance(value, tuple):
        return {"!t": [to_wire(v) for v in value]}
    if isinstance(value, frozenset):
        return {"!fs": sorted(to_wire(v) for v in value)}
    if isinstance(value, dict):
        return {"!m": {str(k): to_wire(v) for k, v in value.items()}}
    if isinstance(value, list):
        return [to_wire(v) for v in value]
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    raise TypeError(f"unencodable store value: {type(value).__name__}")


def from_wire(data: Any) -> Any:
    """Tagged-JSON tree -> object tree.  Unknown tags/classes error."""
    if isinstance(data, dict):
        if "!res" in data:
            return Resources._from_raw(
                {k: float(v) for k, v in data["!res"].items()}
            )
        if "!reqs" in data:
            return Requirements(from_wire(r) for r in data["!reqs"])
        if "!req" in data:
            r = data["!req"]
            return Requirement._raw(
                r["key"],
                r["complement"],
                frozenset(r["values"]),
                r["gt"],
                r["lt"],
                r["min_values"],
                r["absent_ok"],
            )
        if "!dc" in data:
            cls = _DATACLASSES.get(data["!dc"])
            if cls is None:
                raise ValueError(f"unknown wire dataclass: {data['!dc']!r}")
            return cls(**{k: from_wire(v) for k, v in data["f"].items()})
        if "!t" in data:
            return tuple(from_wire(v) for v in data["!t"])
        if "!fs" in data:
            return frozenset(from_wire(v) for v in data["!fs"])
        if "!m" in data:
            return {k: from_wire(v) for k, v in data["!m"].items()}
        raise ValueError(f"untagged wire mapping: {sorted(data)[:3]}")
    if isinstance(data, list):
        return [from_wire(v) for v in data]
    return data


def materialize(value: Any) -> Any:
    """Wire tree OR already-decoded value -> decoded value.

    The negotiated binary codec (state/binwire.py) ships store objects
    natively, so an event's ``obj`` may arrive as a live dataclass (or a
    tuple, for cluster-event appends) instead of a tagged tree; the
    tagged-JSON path always ships trees.  Both halves of the store plane
    normalize through this one seam."""
    return from_wire(value) if isinstance(value, (dict, list)) else value


def canonical(obj: Any) -> str:
    """Deterministic byte form of an object (shadow-diffing + equality)."""
    return json.dumps(to_wire(obj), sort_keys=True, separators=(",", ":"))
