"""In-memory cluster state: the scheduling snapshot.

Analogue of karpenter-core's `state.Cluster` (instantiated at reference
cmd/controller/main.go:49-55): a cache over nodes + bound pods that the
provisioner and deprovisioner consult.  Where the reference incrementally
maintains it from informer events, we rebuild the snapshot from the
KubeStore on demand (cheap at our scale) plus track in-flight NodeClaims
that have no Node yet — those still reserve capacity against scheduling.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.api import NodeClaim, Pod, Resources, Taint
from karpenter_tpu.api import labels as L
from karpenter_tpu.state.kube import KubeStore, Node

# how long a nomination holds before the pod returns to the provisionable
# pool (reference karpenter-core state.Cluster nomination window)
NOMINATION_TTL = 20.0


@dataclass
class StateNode:
    """A node (or not-yet-registered claim) with its live usage."""

    name: str
    provider_id: str
    labels: Dict[str, str]
    taints: List[Taint]
    allocatable: Resources
    capacity: Resources = field(default_factory=Resources)
    pods: List[Pod] = field(default_factory=list)
    used: Resources = field(default_factory=Resources)
    node: Optional[Node] = None
    claim: Optional[NodeClaim] = None
    nominated: bool = False  # has in-flight pod reservations

    @property
    def registered(self) -> bool:
        return self.node is not None

    @property
    def initialized(self) -> bool:
        return self.claim is not None and self.claim.initialized or (
            self.claim is None and self.node is not None and self.node.ready
        )

    @property
    def pool_name(self) -> str:
        return self.labels.get(L.LABEL_NODEPOOL, "")

    @property
    def capacity_type(self) -> str:
        return self.labels.get(L.LABEL_CAPACITY_TYPE, L.CAPACITY_TYPE_ON_DEMAND)

    @property
    def zone(self) -> str:
        return self.labels.get(L.LABEL_ZONE, "")

    @property
    def instance_type_name(self) -> str:
        return self.labels.get(L.LABEL_INSTANCE_TYPE, "")

    def available(self) -> Resources:
        return (self.allocatable - self.used).clamp_nonnegative()

    def marked_for_deletion(self) -> bool:
        return (self.node is not None and self.node.deleted_at is not None) or (
            self.claim is not None and self.claim.deleted_at is not None
        )


class Cluster:
    """Snapshot builder + nomination ledger.

    Nominations (pods the provisioner has decided to place on an in-flight
    node) prevent double-provisioning between the launch and the kube
    scheduler binding the pod — the reference tracks these the same way in
    state.Cluster's podNominations.
    """

    def __init__(self, kube: KubeStore, clock=None):
        self.kube = kube
        self.clock = clock
        # pod key -> (node/claim name, nomination timestamp)
        self._nominations: Dict[str, Tuple[str, float]] = {}

    def _now(self) -> float:
        return self.clock.now() if self.clock is not None else 0.0

    def nominate(self, pod_key: str, node_name: str) -> None:
        self._nominations[pod_key] = (node_name, self._now())

    def clear_nomination(self, pod_key: str) -> None:
        self._nominations.pop(pod_key, None)

    def _live(self, pod_key: str) -> Optional[str]:
        entry = self._nominations.get(pod_key)
        if entry is None:
            return None
        node_name, ts = entry
        # nominations EXPIRE: if the scheduler hasn't bound the pod within
        # the window (taint added after nomination, kubelet wedged), the
        # pod must return to the provisionable pool and the node must stop
        # being charged for it — otherwise both deadlock forever (the
        # reference's state.Cluster nomination window is ~20s)
        if self.clock is not None and self._now() - ts > NOMINATION_TTL:
            self._nominations.pop(pod_key, None)
            return None
        return node_name

    def nominated_node(self, pod_key: str) -> Optional[str]:
        return self._live(pod_key)

    def nominations(self) -> List[tuple]:
        """Snapshot of live (pod key, target node/claim name) entries —
        the read API for consumers like the consistency checker."""
        return [
            (k, node)
            for k in list(self._nominations)
            if (node := self._live(k)) is not None
        ]

    def snapshot(self) -> List[StateNode]:
        nodes: Dict[str, StateNode] = {}
        claims_by_provider = {
            c.provider_id: c for c in self.kube.node_claims.values() if c.provider_id
        }
        for n in self.kube.nodes.values():
            claim = claims_by_provider.get(n.provider_id)
            nodes[n.name] = StateNode(
                name=n.name,
                provider_id=n.provider_id,
                labels=dict(n.labels),
                taints=list(n.taints),
                allocatable=n.allocatable,
                capacity=n.capacity,
                node=n,
                claim=claim,
            )
        # in-flight claims (launched, not yet registered as Nodes)
        registered_provider_ids = {n.provider_id for n in self.kube.nodes.values()}
        for c in self.kube.node_claims.values():
            if c.provider_id and c.provider_id in registered_provider_ids:
                continue
            nodes[c.name] = StateNode(
                name=c.name,
                provider_id=c.provider_id,
                labels=dict(c.labels),
                taints=list(c.taints),
                allocatable=c.allocatable,
                capacity=c.capacity,
                claim=c,
            )
        # charge bound pods
        for p in self.kube.pods.values():
            if p.node_name and p.node_name in nodes:
                sn = nodes[p.node_name]
                sn.pods.append(p)
                sn.used = sn.used + p.requests
        # charge nominated (in-flight) pods
        for pod_key in list(self._nominations):
            node_name = self._live(pod_key)  # drops expired entries
            if node_name is None:
                continue
            pod = self.kube.pods.get(pod_key)
            sn = nodes.get(node_name)
            if pod is None or pod.node_name or sn is None:
                # nomination resolved or stale; drop it
                self._nominations.pop(pod_key, None)
                continue
            sn.pods.append(pod)
            sn.used = sn.used + pod.requests
            sn.nominated = True
        return list(nodes.values())

    def pool_usage(self, pool_name: str) -> Resources:
        """Total capacity consumed by a pool (for NodePool.limits
        enforcement; reference designs/limits.md).  Uses node capacity
        uniformly regardless of how the node joined (claim or adoption)."""
        out = Resources()
        for sn in self.snapshot():
            if sn.pool_name == pool_name and not sn.marked_for_deletion():
                out = out + (sn.capacity if sn.capacity else sn.allocatable)
        return out
