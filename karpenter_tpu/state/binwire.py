"""Compact binary wire codec (``bin1``) for the cluster-store protocol.

The tagged-JSON codec (state/wire.py) is the store's lingua franca: safe,
self-describing, and slow at fleet scale — every Pod crossing the wire
pays a Python tree build plus JSON string scanning, and a watch event
fanned out to N subscribers pays the encode N times.  ``bin1`` is the
negotiated fast path (service/store_server.py `hello`): the same value
domain, encoded directly from the live objects into length-prefixed
binary with no intermediate tree.

Frame-relevant properties:

- **Length-prefixed, versioned**: every payload starts with the magic
  byte + codec version (service/codec.py `encode_payload`); every
  variable-size value carries a varint length.  An endpoint that doesn't
  recognize the version negotiates down to tagged JSON.
- **Closed schema**: only `STORE_WIRE_CLASSES` encode — the class-id
  table is positional over that static tuple, and `SCHEMA_FP` hashes the
  class list *and every field list in declaration order*.  Peers
  exchange the fingerprint at `hello`/`watch` time; any mismatch (a
  build whose dataclasses drifted) falls back to JSON instead of
  decoding garbage.  Like ``from_wire``, unknown ids are an error, never
  an attribute lookup — and no payload is ever executed.
- **Default elision**: dataclasses encode as (class-id, n, (field-idx,
  value)*) with fields still holding their declared default omitted —
  the decoder rebuilds via ``cls(**present)`` so elided fields re-take
  their defaults.  A Pod is mostly defaults; elision is where the wire
  shrinks ~5x under tagged JSON.
- **Splicing**: `Raw` wraps pre-encoded value bytes so a frame can embed
  an already-rendered event batch without re-encoding — the server
  renders each watch event once and every subscriber frame reuses the
  bytes (the fan-out win the JSON protocol structurally cannot have).

Equality contract: for every value the tagged-JSON codec accepts,
``decode_value(encode_value(v))`` is ``canonical``-equal to ``v`` (the
round-trip fuzz in tests/test_store_scale.py pins this against the JSON
codec on the same objects).
"""

from __future__ import annotations

import dataclasses
import hashlib
import struct
from typing import Any, List, Tuple

from karpenter_tpu.api.requirements import Requirement, Requirements
from karpenter_tpu.api.resources import Resources
from karpenter_tpu.state.wire import STORE_WIRE_CLASSES

BIN_CODEC = "bin1"
BIN_VERSION = 1

# value tags (one byte each)
_T_NONE, _T_FALSE, _T_TRUE = 0, 1, 2
_T_INT, _T_FLOAT, _T_STR = 3, 4, 5
_T_LIST, _T_TUPLE, _T_FSET, _T_DICT = 6, 7, 8, 9
_T_RES, _T_REQ, _T_REQS, _T_DC = 10, 11, 12, 13

_pack_d = struct.Struct(">d").pack
_unpack_d = struct.Struct(">d").unpack_from


class Raw:
    """Pre-encoded value bytes, spliced verbatim into an enclosing
    encode.  The bytes MUST be one complete ``encode_value`` output —
    the codec cannot re-validate them (that is the point: zero-cost
    reuse of an already-rendered event)."""

    __slots__ = ("data",)

    def __init__(self, data: bytes):
        self.data = data


def _skip_spec(f: dataclasses.Field):
    """(kind, arg) describing when a field's value may be elided, or
    None when it never may.  Elision must be exact: the decoder fills
    the declared default back in, so a value is skippable only when it
    is indistinguishable from that default (type included — a 0 on a
    None-default field must still ship)."""
    if f.default is not dataclasses.MISSING:
        d = f.default
        if d is None:
            return ("none", None)
        if isinstance(d, bool):
            return ("is", d)
        if isinstance(d, (int, float, str)):
            # floats additionally compare by repr: -0.0 == 0.0 but the
            # canonical JSON forms differ, and elision must never change
            # the canonical bytes
            return ("eq", d)
        if isinstance(d, tuple) and not d:
            return ("empty", tuple)
        return None
    if f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
        sample = f.default_factory()  # type: ignore[misc]
        if type(sample) in (list, dict, tuple, set, frozenset) and not sample:
            return ("empty", type(sample))
        return None
    return None


def _field_fp(f: dataclasses.Field) -> str:
    """The fingerprint-relevant identity of one field: its name AND its
    default.  Defaults matter because elision round-trips through them —
    a peer whose default drifted would silently fill the WRONG value
    back in for an elided field, so drifted defaults must break the
    fingerprint and negotiate down to JSON."""
    if f.default is not dataclasses.MISSING:
        return f"{f.name}={f.default!r}"
    if f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
        return f"{f.name}~{f.default_factory()!r}"  # type: ignore[misc]
    return f.name


def _build_tables(wire_classes=STORE_WIRE_CLASSES):
    classes: List[Tuple[type, List[str], list]] = []
    ids = {}
    fp = hashlib.sha256()
    fp.update(f"bin{BIN_VERSION};".encode())
    for cid, cls in enumerate(wire_classes):
        fields = dataclasses.fields(cls)
        names = [f.name for f in fields]
        skips = [_skip_spec(f) for f in fields]
        classes.append((cls, names, skips))
        ids[cls] = cid
        fp.update(
            f"{cls.__name__}:{','.join(_field_fp(f) for f in fields)};".encode()
        )
    return classes, ids, fp.hexdigest()[:16]


_CLASSES, _CLASS_IDS, SCHEMA_FP = _build_tables()


def _sorted_det(values):
    """Deterministic ordering for unordered containers, so equal sets
    encode to equal bytes regardless of PYTHONHASHSEED."""
    try:
        return sorted(values)
    except TypeError:
        return sorted(values, key=repr)


def _enc_len(v: int, out: bytearray) -> None:
    while v > 127:
        out.append((v & 127) | 128)
        v >>= 7
    out.append(v)


def _enc(value: Any, out: bytearray) -> None:
    t = type(value)
    if value is None:
        out.append(_T_NONE)
    elif t is bool:
        out.append(_T_TRUE if value else _T_FALSE)
    elif t is int:
        out.append(_T_INT)
        # zigzag, arbitrary-precision safe: negatives map to odd codes
        _enc_len((-value << 1) - 1 if value < 0 else value << 1, out)
    elif t is float:
        out.append(_T_FLOAT)
        out += _pack_d(value)
    elif t is str:
        out.append(_T_STR)
        b = value.encode()
        _enc_len(len(b), out)
        out += b
    elif t is list:
        out.append(_T_LIST)
        _enc_len(len(value), out)
        for v in value:
            _enc(v, out)
    elif t is tuple:
        out.append(_T_TUPLE)
        _enc_len(len(value), out)
        for v in value:
            _enc(v, out)
    elif t is frozenset or t is set:
        out.append(_T_FSET)
        _enc_len(len(value), out)
        for v in _sorted_det(value):
            _enc(v, out)
    elif t is dict:
        out.append(_T_DICT)
        _enc_len(len(value), out)
        for k, v in value.items():
            kb = str(k).encode()  # str keys, matching to_wire
            _enc_len(len(kb), out)
            out += kb
            _enc(v, out)
    elif t is Resources:
        out.append(_T_RES)
        d = value.to_dict()
        _enc_len(len(d), out)
        for k, v in d.items():
            kb = k.encode()
            _enc_len(len(kb), out)
            out += kb
            out += _pack_d(float(v))
    elif t is Requirements:
        out.append(_T_REQS)
        items = list(value)
        _enc_len(len(items), out)
        for r in items:
            _enc(r, out)
    elif t is Requirement:
        out.append(_T_REQ)
        _enc(value.key, out)
        out.append(1 if value.complement else 0)
        vals = _sorted_det(value.values)
        _enc_len(len(vals), out)
        for v in vals:
            _enc(v, out)
        _enc(value.greater_than, out)
        _enc(value.less_than, out)
        _enc(value.min_values, out)
        out.append(1 if value.absent_ok else 0)
    elif t is Raw:
        out += value.data
    else:
        cid = _CLASS_IDS.get(t)
        if cid is None:
            raise TypeError(f"unencodable bin1 value: {t.__name__}")
        _, names, skips = _CLASSES[cid]
        present = []
        for idx, name in enumerate(names):
            v = getattr(value, name)
            spec = skips[idx]
            if spec is not None:
                kind, arg = spec
                if kind == "none":
                    if v is None:
                        continue
                elif kind == "is":
                    if v is arg:
                        continue
                elif kind == "eq":
                    if (
                        type(v) is type(arg)
                        and v == arg
                        and (type(v) is not float or repr(v) == repr(arg))
                    ):
                        continue
                else:  # empty container of the default's type
                    if type(v) is arg and not v:
                        continue
            present.append((idx, v))
        out.append(_T_DC)
        _enc_len(cid, out)
        _enc_len(len(present), out)
        for idx, v in present:
            _enc_len(idx, out)
            _enc(v, out)


def encode_value(value: Any) -> bytes:
    out = bytearray()
    _enc(value, out)
    return bytes(out)


def _dec_len(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    v = 0
    while True:
        b = buf[pos]
        pos += 1
        v |= (b & 127) << shift
        if b < 128:
            return v, pos
        shift += 7


def _dec(buf: bytes, pos: int) -> Tuple[Any, int]:
    tag = buf[pos]
    pos += 1
    if tag == _T_NONE:
        return None, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_INT:
        v, pos = _dec_len(buf, pos)
        return (-((v + 1) >> 1) if v & 1 else v >> 1), pos
    if tag == _T_FLOAT:
        return _unpack_d(buf, pos)[0], pos + 8
    if tag == _T_STR:
        n, pos = _dec_len(buf, pos)
        return buf[pos : pos + n].decode(), pos + n
    if tag == _T_LIST:
        n, pos = _dec_len(buf, pos)
        out = []
        for _ in range(n):
            v, pos = _dec(buf, pos)
            out.append(v)
        return out, pos
    if tag == _T_TUPLE:
        n, pos = _dec_len(buf, pos)
        out = []
        for _ in range(n):
            v, pos = _dec(buf, pos)
            out.append(v)
        return tuple(out), pos
    if tag == _T_FSET:
        n, pos = _dec_len(buf, pos)
        out = []
        for _ in range(n):
            v, pos = _dec(buf, pos)
            out.append(v)
        return frozenset(out), pos
    if tag == _T_DICT:
        n, pos = _dec_len(buf, pos)
        d = {}
        for _ in range(n):
            kn, pos = _dec_len(buf, pos)
            k = buf[pos : pos + kn].decode()
            pos += kn
            v, pos = _dec(buf, pos)
            d[k] = v
        return d, pos
    if tag == _T_RES:
        n, pos = _dec_len(buf, pos)
        d = {}
        for _ in range(n):
            kn, pos = _dec_len(buf, pos)
            k = buf[pos : pos + kn].decode()
            pos += kn
            d[k] = _unpack_d(buf, pos)[0]
            pos += 8
        return Resources._from_raw(d), pos
    if tag == _T_REQS:
        n, pos = _dec_len(buf, pos)
        out = []
        for _ in range(n):
            v, pos = _dec(buf, pos)
            out.append(v)
        return Requirements(out), pos
    if tag == _T_REQ:
        key, pos = _dec(buf, pos)
        comp = buf[pos] == 1
        pos += 1
        n, pos = _dec_len(buf, pos)
        vals = []
        for _ in range(n):
            v, pos = _dec(buf, pos)
            vals.append(v)
        gt, pos = _dec(buf, pos)
        lt, pos = _dec(buf, pos)
        mv, pos = _dec(buf, pos)
        ao = buf[pos] == 1
        pos += 1
        return Requirement._raw(
            key, comp, frozenset(vals), gt, lt, mv, ao
        ), pos
    if tag == _T_DC:
        cid, pos = _dec_len(buf, pos)
        if cid >= len(_CLASSES):
            raise ValueError(f"unknown bin1 class id: {cid}")
        cls, names, _ = _CLASSES[cid]
        n, pos = _dec_len(buf, pos)
        kw = {}
        for _ in range(n):
            idx, pos = _dec_len(buf, pos)
            v, pos = _dec(buf, pos)
            kw[names[idx]] = v
        return cls(**kw), pos
    raise ValueError(f"unknown bin1 tag: {tag}")


def decode_value(buf: bytes, pos: int = 0) -> Any:
    value, end = _dec(buf, pos)
    if end != len(buf):
        raise ValueError(
            f"trailing bin1 bytes: decoded to {end} of {len(buf)}"
        )
    return value
