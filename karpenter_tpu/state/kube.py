"""In-memory kube-apiserver analogue.

The reference keeps all durable state in the kube-apiserver (CRDs:
NodePool/Provisioner, NodeClaim/Machine, EC2NodeClass) — SURVEY.md section 5
"checkpoint/resume: none needed".  We mirror that: this store is the single
source of durable truth; caches elsewhere are reconstructable from it.  Its
test role matches controller-runtime envtest in the reference suites
(pkg/cloudprovider/suite_test.go:64-78).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from karpenter_tpu.analysis.sanitizer import make_lock, note_access
from karpenter_tpu.api import (
    NodeClaim,
    NodeClass,
    NodePool,
    PersistentVolumeClaim,
    Pod,
    Resources,
    StorageClass,
    Taint,
)
from karpenter_tpu.api import labels as L


@dataclass
class PodDisruptionBudget:
    """v1.PodDisruptionBudget projection: the termination controller's
    evictions respect these (reference: core termination controller is
    PDB-aware, designs/termination.md)."""

    name: str
    label_selector: Dict[str, str] = field(default_factory=dict)
    min_available: Optional[int] = None
    max_unavailable: Optional[int] = None
    namespace: str = "default"

    def selects(self, pod: Pod) -> bool:
        if pod.namespace != self.namespace:
            return False
        return all(pod.labels.get(k) == v for k, v in self.label_selector.items())

    def disruptions_allowed(self, all_matching: List[Pod]) -> int:
        """How many matching pods may be evicted right now, given the FULL
        matching set (any phase).  Pods already unavailable — evicted and
        not yet rescheduled — consume the budget, exactly like the PDB
        status accounting in Kubernetes."""
        matching = [p for p in all_matching if self.selects(p)]
        healthy = sum(1 for p in matching if p.phase == "Running")
        unavailable = len(matching) - healthy
        if self.max_unavailable is not None:
            return max(0, self.max_unavailable - unavailable)
        if self.min_available is not None:
            return max(0, healthy - self.min_available)
        return healthy


@dataclass
class Node:
    """A registered cluster node (the v1.Node analogue)."""

    name: str
    provider_id: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    capacity: Resources = field(default_factory=Resources)
    allocatable: Resources = field(default_factory=Resources)
    ready: bool = False
    created_at: float = 0.0
    deleted_at: Optional[float] = None
    cordoned: bool = False


class KubeStore:
    """Typed object store with the handful of list/get/delete verbs the
    controllers need, plus simple event hooks for cache invalidation."""

    def __init__(self):
        self.pods: Dict[str, Pod] = {}  # key: ns/name
        self.nodes: Dict[str, Node] = {}
        self.node_claims: Dict[str, NodeClaim] = {}
        self.node_pools: Dict[str, NodePool] = {}
        self.node_classes: Dict[str, NodeClass] = {}
        self.pdbs: Dict[str, PodDisruptionBudget] = {}
        self.storage_classes: Dict[str, StorageClass] = {}
        self.pvcs: Dict[str, PersistentVolumeClaim] = {}  # key: ns/name
        self.events: List[tuple] = []  # (kind, reason, obj_name, message)
        self._watchers: List[Callable[[str, str, object], None]] = []
        self._seq = itertools.count(1)
        # coordination.k8s.io/v1 Leases (utils/leader.py): the only store
        # surface touched concurrently by competing replicas, so its
        # compare-and-swap runs under a lock
        self.leases: Dict[str, "Lease"] = {}
        self._lease_lock = make_lock("KubeStore._lease_lock")

    # -- watch hooks ---------------------------------------------------------
    def watch(self, fn: Callable[[str, str, object], None]) -> None:
        """fn(kind, verb, obj) on every mutation."""
        self._watchers.append(fn)

    def _notify(self, kind: str, verb: str, obj) -> None:
        for fn in self._watchers:
            fn(kind, verb, obj)

    # -- pods ----------------------------------------------------------------
    def put_pod(self, pod: Pod) -> Pod:
        self.pods[pod.key()] = pod
        self._notify("Pod", "put", pod)
        return pod

    def delete_pod(self, key: str) -> None:
        pod = self.pods.pop(key, None)
        if pod is not None:
            self._notify("Pod", "delete", pod)

    def pending_pods(self) -> List[Pod]:
        return [
            p for p in self.pods.values() if p.phase == "Pending" and not p.node_name
        ]

    def pods_on_node(self, node_name: str) -> List[Pod]:
        return [p for p in self.pods.values() if p.node_name == node_name]

    def bind_pod(self, key: str, node_name: str) -> None:
        pod = self.pods[key]
        pod.node_name = node_name
        pod.phase = "Running"
        # the first consumer anchors WaitForFirstConsumer volumes: the
        # volume provisions in the bound node's zone, pinning every later
        # consumer of the claim there (scheduling.md:387-411)
        if pod.volume_claims:
            node = self.nodes.get(node_name)
            zone = node.labels.get(L.LABEL_ZONE, "") if node else ""
            if zone:
                for cname in pod.volume_claims:
                    pvc = self.pvcs.get(f"{pod.namespace}/{cname}")
                    if pvc is not None and not pvc.bound_zone:
                        pvc.bound_zone = zone
                        self._notify("PersistentVolumeClaim", "bind", pvc)
        self._notify("Pod", "bind", pod)

    def evict_pod(self, key: str) -> None:
        """Eviction semantics: a controller-owned pod re-pends (its
        controller recreates it); a bare pod is deleted — the Eviction API
        analogue the termination controller drains with."""
        pod = self.pods.get(key)
        if pod is None:
            return
        if pod.has_controller:
            pod.node_name = ""
            pod.phase = "Pending"
            self._notify("Pod", "evict", pod)
        else:
            self.delete_pod(key)

    # -- nodes ---------------------------------------------------------------
    def put_node(self, node: Node) -> Node:
        self.nodes[node.name] = node
        self._notify("Node", "put", node)
        return node

    def delete_node(self, name: str) -> None:
        node = self.nodes.pop(name, None)
        if node is not None:
            for p in self.pods_on_node(name):
                # pods on a deleted node go back to pending (controller-owned
                # pods are recreated by their controller in a real cluster);
                # each re-pend notifies so store replication (state/remote.py)
                # ships the cascade, not just the node deletion
                p.node_name = ""
                p.phase = "Pending"
                self._notify("Pod", "put", p)
            self._notify("Node", "delete", node)

    def node_by_provider_id(self, provider_id: str) -> Optional[Node]:
        for n in self.nodes.values():
            if n.provider_id == provider_id:
                return n
        return None

    # -- node claims ---------------------------------------------------------
    def put_node_claim(self, claim: NodeClaim) -> NodeClaim:
        self.node_claims[claim.name] = claim
        self._notify("NodeClaim", "put", claim)
        return claim

    def delete_node_claim(self, name: str) -> None:
        claim = self.node_claims.pop(name, None)
        if claim is not None:
            self._notify("NodeClaim", "delete", claim)

    def claim_by_provider_id(self, provider_id: str) -> Optional[NodeClaim]:
        for c in self.node_claims.values():
            if c.provider_id == provider_id:
                return c
        return None

    # -- pools / classes -----------------------------------------------------
    def put_node_pool(self, pool: NodePool) -> NodePool:
        """Admission: validation runs before the write (the webhook
        analogue, api/validation.py)."""
        from karpenter_tpu.api.validation import validate_node_pool

        validate_node_pool(pool)
        self.node_pools[pool.name] = pool
        self._notify("NodePool", "put", pool)
        return pool

    def put_node_class(self, nc: NodeClass) -> NodeClass:
        from karpenter_tpu.api.validation import validate_node_class

        validate_node_class(nc)
        self.node_classes[nc.name] = nc
        self._notify("NodeClass", "put", nc)
        return nc

    def get_node_class(self, name: str) -> Optional[NodeClass]:
        return self.node_classes.get(name)

    def put_storage_class(self, sc: StorageClass) -> StorageClass:
        from karpenter_tpu.api.validation import validate_storage_class

        validate_storage_class(sc)
        self.storage_classes[sc.name] = sc
        self._notify("StorageClass", "put", sc)
        return sc

    def put_pvc(self, pvc: PersistentVolumeClaim) -> PersistentVolumeClaim:
        # Immediate-mode claims provision as soon as they exist — the fake
        # PV controller picks the storage class's first allowed zone
        sc = self.storage_classes.get(pvc.storage_class)
        if (
            not pvc.bound_zone
            and sc is not None
            and sc.binding_mode == "Immediate"
            and sc.zones
        ):
            pvc.bound_zone = sc.zones[0]
        self.pvcs[pvc.key()] = pvc
        self._notify("PersistentVolumeClaim", "put", pvc)
        return pvc

    def put_pdb(self, pdb: PodDisruptionBudget) -> PodDisruptionBudget:
        self.pdbs[pdb.name] = pdb
        self._notify("PodDisruptionBudget", "put", pdb)
        return pdb

    def daemonset_pods(self) -> List[Pod]:
        """Template daemonset pods (one per daemonset) used for per-node
        overhead during scheduling."""
        seen = {}
        for p in self.pods.values():
            if p.is_daemonset:
                seen.setdefault(p.constraint_signature(), p)
        return list(seen.values())

    # -- leases --------------------------------------------------------------
    def try_acquire_lease(
        self, name: str, holder: str, now: float, duration_s: float
    ) -> bool:
        """Atomic acquire-or-renew (the coordination/v1 Lease update the
        reference's controller-runtime election performs): succeeds when
        the lease is free, expired, or already held by ``holder``.
        Watcher callbacks fire AFTER the lock is released — the lock is
        non-reentrant and a competing replica's election must not stall
        on a slow watcher."""
        from karpenter_tpu.utils.leader import Lease

        acquired = None
        with self._lease_lock:
            note_access("KubeStore.leases")  # lockset witness
            lease = self.leases.get(name)
            if (
                lease is not None
                and lease.holder
                and lease.holder != holder
                and now - lease.renewed_at <= lease.duration_s
            ):
                return False  # held by a live other replica
            if lease is None or lease.holder != holder:
                lease = Lease(
                    name=name,
                    holder=holder,
                    acquired_at=now,
                    duration_s=duration_s,
                )
                self.leases[name] = lease
                acquired = lease
            lease.renewed_at = now
            lease.duration_s = duration_s
        if acquired is not None:
            self._notify("Lease", "acquire", acquired)
        return True

    def renew_lease(self, name: str, holder: str, now: float) -> bool:
        """Renew-ONLY: succeeds only while ``holder`` still holds the
        lease.  The background renewal thread uses this so it can never
        re-acquire a lease the graceful shutdown path just released."""
        with self._lease_lock:
            lease = self.leases.get(name)
            if lease is None or lease.holder != holder:
                return False
            lease.renewed_at = now
            return True

    def release_lease(self, name: str, holder: str) -> None:
        """Graceful give-up: only the current holder may free the lease."""
        released = None
        with self._lease_lock:
            lease = self.leases.get(name)
            if lease is not None and lease.holder == holder:
                lease.holder = ""
                lease.renewed_at = 0.0
                released = lease
        if released is not None:
            self._notify("Lease", "release", released)

    # -- events --------------------------------------------------------------
    def record_event(self, kind: str, reason: str, obj_name: str, message: str = ""):
        self.events.append((kind, reason, obj_name, message))
