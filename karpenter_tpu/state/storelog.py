"""Disk-backed replay log for the store plane (docs/designs/
store-scale.md, "Durability").

PR 12's `VersionedStore` keeps its replay log in memory: a restarted
store process comes back as a NEW epoch with an empty log, so every
reconnecting client is forced onto a full snapshot — a fleet-wide
snapshot storm exactly when the plane is weakest.  `DurableReplayLog`
cursors that log to disk: every commit batch appends one length-prefixed
``bin1`` record, and a periodic checkpoint rewrites the segment as
(snapshot + tail), so a restarted `StoreServer` re-adopts its previous
epoch/seq space and serves *delta* resyncs from the recovered tail.

Record format (one segment file, records concatenated):

    [8B big-endian payload length][bin1 payload]

where the payload is the standard versioned ``encode_payload`` framing
(magic + codec version + one encoded value) of either:

- ``{"type": "checkpoint", "epoch", "seq", "rv", "event_rv",
  "lease_seq", "snapshot"}`` — a full-state snapshot; always the
  segment's FIRST record (checkpointing atomically replaces the file).
- ``{"type": "batch", "seq", "epoch", "events": [Raw...]}`` — one
  commit batch, events in the store's rendered bin form (the same bytes
  the watch fan-out ships).

Torn-tail rule: a crash mid-append leaves at most one truncated record
at the tail.  Recovery DROPS any record whose length prefix is
incomplete, whose declared length overruns the file, or whose payload
fails to decode — it is never decoded wrong, and nothing after a torn
record is trusted (a later record boundary found by luck inside garbage
is still garbage).  The durable prefix is exactly what fsync policy
guaranteed.

fsync policy (the chart's ``store.logFsync`` knob): ``"always"`` syncs
after every append (a crash loses nothing acknowledged), ``"off"``
leaves flushing to the OS (a crash may lose the unsynced tail — which
recovery then treats as torn).  The fsync call itself is an injectable
seam (``fsync_fn``) so the fleet-chaos harness can script an fsync
FAILURE deterministically: on the first OSError the log marks itself
failed, stops appending, and counts
``karpenter_store_log_failures_total`` — the in-memory store keeps
serving (availability) while restart durability degrades to the last
synced prefix, which is exactly what a real disk failure means.
"""

from __future__ import annotations

import logging
import os
import struct
from typing import Callable, Dict, List, Optional, Tuple

from karpenter_tpu.analysis.sanitizer import make_lock, note_blocking
from karpenter_tpu.service.codec import (
    CODEC_BIN,
    decode_payload,
    encode_payload,
)

log = logging.getLogger(__name__)

# rewrite the segment as (checkpoint + empty tail) after this many batch
# records: bounds both recovery time and segment growth.  Deliberately
# larger than the in-memory replay bound — the disk tail is what makes a
# RESTARTED store serve deltas, so it should cover at least as much
# history as the live log does.
CHECKPOINT_EVERY_BATCHES = 1024

FSYNC_ALWAYS = "always"
FSYNC_OFF = "off"


def read_segment(path: str) -> Tuple[List[dict], int]:
    """Scan one segment file, applying the torn-tail rule.  Returns
    ``(records, torn)`` where ``torn`` counts the dropped tail records
    (0 or 1 in practice — everything after the first tear is dropped as
    one unit).  Malformed bytes surface as a DROP, never as an
    ``IndexError`` or a wrongly-decoded record."""
    try:
        blob = open(path, "rb").read()
    except FileNotFoundError:
        return [], 0
    records: List[dict] = []
    pos = 0
    while pos < len(blob):
        if pos + 8 > len(blob):
            return records, 1  # torn length prefix
        (size,) = struct.unpack(">Q", blob[pos : pos + 8])
        if pos + 8 + size > len(blob):
            return records, 1  # declared length overruns the file
        try:
            rec = decode_payload(blob[pos + 8 : pos + 8 + size], CODEC_BIN)
        except ValueError:
            return records, 1  # undecodable payload: torn mid-record
        if not isinstance(rec, dict) or "type" not in rec:
            return records, 1
        records.append(rec)
        pos += 8 + size
    return records, 0


class DurableReplayLog:
    """One store shard's crash-durable replay segment.

    The owning ``VersionedStore`` calls ``append_batch`` under its own
    lock at every commit and ``write_checkpoint`` at epoch rotations;
    auto-checkpointing (every ``checkpoint_every`` batches) is driven by
    the store too, so the snapshot renders under the store lock where
    live objects are safe to encode.  The log's own lock only orders the
    file writes against ``close`` (appends are already serialized by the
    store lock; a second writer process is out of scope — one segment,
    one store)."""

    def __init__(
        self,
        path: str,
        fsync: str = FSYNC_ALWAYS,
        fsync_fn: Optional[Callable[[int], None]] = None,
        checkpoint_every: int = CHECKPOINT_EVERY_BATCHES,
        registry=None,
    ):
        self.path = path
        self.fsync = fsync
        # the injectable fsync seam: the chaos harness swaps in a
        # failing callable; production keeps os.fsync
        self.fsync_fn = fsync_fn or os.fsync
        self.checkpoint_every = max(1, checkpoint_every)
        self.registry = registry  # re-bound by the owning store/server
        self._lock = make_lock("DurableReplayLog._lock")
        self._fh = None
        self.failed = False
        self.batches_since_checkpoint = 0
        self.torn_records = 0

    # ------------------------------------------------------------- recovery
    def recover(self) -> Tuple[Optional[dict], List[dict]]:
        """Read the segment back: ``(checkpoint, batches)``.  The LAST
        checkpoint record wins (there is at most one per segment — the
        checkpointer atomically replaces the file — but a segment
        hand-edited or produced by an older build must not confuse
        recovery); batch records before it are superseded, batch records
        after it in ITS epoch with ascending seq are the durable tail."""
        records, torn = read_segment(self.path)
        self.torn_records = torn
        if torn:
            self._count("karpenter_store_log_torn_records_total", torn)
        checkpoint: Optional[dict] = None
        batches: List[dict] = []
        for rec in records:
            if rec["type"] == "checkpoint":
                checkpoint = rec
                batches = []
            elif rec["type"] == "batch":
                if checkpoint is not None and (
                    rec.get("epoch") != checkpoint.get("epoch")
                    or rec.get("seq", 0) <= checkpoint.get("seq", 0)
                ):
                    continue  # another epoch's stray tail: superseded
                if batches and rec.get("seq", 0) != batches[-1]["seq"] + 1:
                    # a seq gap means the segment is internally
                    # inconsistent — trust only the contiguous prefix
                    break
                batches.append(rec)
        return checkpoint, batches

    # ------------------------------------------------------------- appending
    def _open(self):
        if self._fh is None:
            self._fh = open(self.path, "ab")
        return self._fh

    def _write_record(self, fh, record: dict) -> int:
        payload = encode_payload(record, CODEC_BIN)
        fh.write(struct.pack(">Q", len(payload)) + payload)
        return len(payload) + 8

    def append_batch(self, seq: int, epoch: str, events) -> None:
        """Append one commit batch.  Called under the store lock (the
        rendered ``events`` are immutable ``Raw`` bytes, so only the
        file write itself happens here).  A failed log never raises into
        the commit path: the store stays available; durability degrades
        to the synced prefix and the failure is counted."""
        if self.failed:
            return
        note_blocking("storelog_append")
        with self._lock:
            try:
                fh = self._open()
                n = self._write_record(
                    fh, {"type": "batch", "seq": seq, "epoch": epoch,
                         "events": list(events)}
                )
                fh.flush()
                if self.fsync == FSYNC_ALWAYS:
                    self.fsync_fn(fh.fileno())
            except OSError as exc:
                self._fail(exc)
                return
            self.batches_since_checkpoint += 1
            self._count("karpenter_store_log_bytes_total", n)
            self._count("karpenter_store_log_records_total", 1)

    def checkpoint_due(self) -> bool:
        return (
            not self.failed
            and self.batches_since_checkpoint >= self.checkpoint_every
        )

    def write_checkpoint(
        self,
        epoch: str,
        seq: int,
        rv: int,
        event_rv: int,
        lease_seq: Dict[str, int],
        snapshot: dict,
    ) -> None:
        """Atomically replace the segment with one checkpoint record:
        write a temp file, fsync it, ``os.replace`` over the segment.
        A crash at ANY point leaves either the old segment or the new
        one — never a half-checkpoint (the rename is the commit)."""
        if self.failed:
            return
        note_blocking("storelog_checkpoint")
        with self._lock:
            tmp = self.path + ".tmp"
            try:
                if self._fh is not None:
                    self._fh.close()
                    self._fh = None
                with open(tmp, "wb") as fh:
                    self._write_record(
                        fh,
                        {
                            "type": "checkpoint",
                            "epoch": epoch,
                            "seq": seq,
                            "rv": rv,
                            "event_rv": event_rv,
                            "lease_seq": dict(lease_seq),
                            "snapshot": snapshot,
                        },
                    )
                    fh.flush()
                    if self.fsync != FSYNC_OFF:
                        self.fsync_fn(fh.fileno())
                os.replace(tmp, self.path)
            except OSError as exc:
                self._fail(exc)
                return
            self.batches_since_checkpoint = 0
            self._count("karpenter_store_log_checkpoints_total", 1)

    # ------------------------------------------------------------- plumbing
    def _fail(self, exc: BaseException) -> None:
        # first failure wins; the log goes inert (appends no-op) so a
        # dead disk degrades durability, never availability
        log.error("durable replay log %s failed: %s", self.path, exc)
        self.failed = True
        self._count("karpenter_store_log_failures_total", 1)
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None

    def _count(self, metric: str, by: int) -> None:
        if self.registry is not None:
            self.registry.inc(metric, by=by)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
