"""Solver sidecar tests: codec roundtrip, server dispatch, remote solve
parity with the local kernel."""

import numpy as np
import pytest

from karpenter_tpu.api import Pod, Resources
from karpenter_tpu.ops.packer import run_pack
from karpenter_tpu.ops.tensorize import compile_problem
from karpenter_tpu.scheduling import TensorScheduler
from karpenter_tpu.service import RemoteSolver, SolverServer, SolverUnavailableError
from karpenter_tpu.service.codec import decode, encode
from karpenter_tpu.testing import Environment


@pytest.fixture(scope="module")
def server():
    srv = SolverServer(port=0).start_background()
    yield srv
    srv.stop()


@pytest.fixture()
def client(server):
    c = RemoteSolver(*server.address)
    yield c
    c.close()


class TestCodec:
    def test_roundtrip(self):
        meta = {"method": "pack", "k_slots": 64}
        arrays = {
            "a": np.arange(12, dtype=np.float32).reshape(3, 4),
            "b": np.array([True, False, True]),
            "c": np.int32(7).reshape(()),
        }
        header, out = decode(encode(meta, arrays))
        assert header["method"] == "pack" and header["k_slots"] == 64
        np.testing.assert_array_equal(out["a"], arrays["a"])
        np.testing.assert_array_equal(out["b"], arrays["b"])
        assert out["c"].shape == () and out["c"] == 7


class TestServer:
    def test_ping_info(self, client):
        assert client.ping()
        info = client.info()
        assert info["device_count"] >= 1

    def test_unknown_method_errors(self, server):
        import socket

        from karpenter_tpu.service.codec import recv_frame, send_frame

        with socket.create_connection(server.address) as sock:
            send_frame(sock, encode({"method": "nope"}, {}))
            header, _ = decode(recv_frame(sock))
        assert header["status"] == "error"

    def test_unavailable_raises(self):
        c = RemoteSolver("127.0.0.1", 1)  # nothing listens there
        with pytest.raises(SolverUnavailableError):
            c.ping()


class TestRemoteSolve:
    def test_remote_pack_matches_local(self, client):
        env = Environment()
        pool = env.default_node_pool()
        env.default_node_class()
        types = env.instance_types.list(pool, env.kube.get_node_class("default"))
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(64)]
        prob = compile_problem(pods, [pool], {pool.name: types})
        local = run_pack(prob)
        remote = client.pack_problem(prob)
        np.testing.assert_array_equal(np.asarray(local.take), remote.take)
        np.testing.assert_array_equal(np.asarray(local.node_cfg), remote.node_cfg)
        np.testing.assert_array_equal(np.asarray(local.leftover), remote.leftover)

    def test_concurrent_clients_each_get_their_own_answer(self, server):
        """The sidecar's stated contract: one server, many controllers,
        requests parallelize across its thread pool — each concurrent
        client must receive ITS problem's answer, bit-exact with a local
        solve, never a cross-wired response."""
        import threading

        env = Environment()
        pool = env.default_node_pool()
        env.default_node_class()
        types = env.instance_types.list(pool, env.kube.get_node_class("default"))
        # distinct problems: different pod counts -> different placements
        probs = {
            n: compile_problem(
                [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(n)],
                [pool], {pool.name: types},
            )
            for n in (8, 16, 24, 32, 40, 48)
        }
        expected = {
            n: np.asarray(run_pack(p).node_pods) for n, p in probs.items()
        }
        errors = []

        def worker(n):
            try:
                c = RemoteSolver(*server.address)
                try:
                    for _ in range(5):
                        out = c.pack_problem(probs[n])
                        np.testing.assert_array_equal(
                            np.asarray(out.node_pods), expected[n]
                        )
                finally:
                    c.close()
            except Exception as exc:  # pragma: no cover - failure path
                errors.append((n, exc))

        threads = [threading.Thread(target=worker, args=(n,)) for n in probs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors

    def test_scheduler_with_remote_backend(self, client):
        env = Environment()
        pool = env.default_node_pool()
        env.default_node_class()
        types = env.instance_types.list(pool, env.kube.get_node_class("default"))
        pods = [Pod(requests=Resources(cpu=1, memory="2Gi")) for _ in range(100)]
        local_result = TensorScheduler([pool], {pool.name: types}).solve(pods)
        remote_ts = TensorScheduler(
            [pool], {pool.name: types}, pack_fn=client.pack_problem
        )
        remote_result = remote_ts.solve(pods)
        assert remote_ts.last_path == "tensor"
        assert remote_result.node_count() == local_result.node_count()
        assert sum(len(n.pods) for n in remote_result.new_nodes) == 100
