"""Sharded fleet chaos proof (ISSUE PR 17 tentpole acceptance): 3 real
Operators against 4 durable, key-partitioned store shards through
seeded churn while the storm kills shards mid-write, injects wire-level
faults, fails an fsync, and splits 4 shards into 5 under the migration
epoch fence — with zero double-launches, every restarted shard serving
a disk-backed DELTA resync (never a snapshot, replay bytes < 10% of
the snapshot), and byte-identical run/run and run/replay traces.

The live run below is the tier-1 budget's ONE sharded fleet execution;
the run/run and replay byte-identity proofs re-run the whole storm and
are marked ``slow`` (they triple the wall time for a determinism
property the unsharded fleet suite already guards on every run).
"""

import json
import logging

import pytest

from karpenter_tpu.sim.fleet import (
    FLEET_SCENARIOS,
    read_fleet_tape,
    replay_fleet,
    run_fleet,
)

TICKS = 36


@pytest.fixture(scope="module")
def shard_run():
    logging.disable(logging.WARNING)  # straggler-fence conflicts are loud
    try:
        runner, report = run_fleet("store-fleet-shard-chaos", 0, TICKS)
    finally:
        logging.disable(logging.NOTSET)
    return runner, report


class TestShardChaos:
    def test_zero_double_launches_and_clean_invariants(self, shard_run):
        _runner, report = shard_run
        assert report["double_launches"] == 0
        assert report["invariants"]["violations"] == []
        assert report["launches"] > 0
        assert report["operators"] == 3

    def test_shard_kills_recovered_with_delta_resyncs(self, shard_run):
        _runner, report = shard_run
        shards = report["shards"]
        # the split grew the fleet 4 -> 5
        assert shards["n"] == 5
        assert shards["kills"] >= 1
        # every restarted shard re-adopted its epoch FROM DISK and
        # served the reconnecting mirrors a delta, never a snapshot
        assert shards["epoch_preserved"] is True
        assert shards["delta_resyncs"] >= 1
        assert shards["snapshot_fallbacks"] == 0
        # the acceptance ratio: replay bytes < 10% of snapshot bytes
        assert 0.0 < shards["delta_ratio_max"] < 0.1

    def test_split_migrated_keys_under_the_fence(self, shard_run):
        _runner, report = shard_run
        shards = report["shards"]
        assert shards["split_moved_keys"] > 0
        # migration completed: doctor's stuck-migration rule watches
        # begun > committed; a clean run commits everything it begins
        assert shards["merged_reader_synced"] is True

    def test_wire_faults_and_fsync_failures_were_injected(self, shard_run):
        _runner, report = shard_run
        shards = report["shards"]
        # the deterministic injector actually fired (a chaos proof with
        # no chaos proves nothing) and every fault healed — invariants
        # above are clean
        assert sum(shards["wire_faults"].values()) >= 1
        assert shards["fsync_failures"] >= 1

    def test_trace_structure_names_the_chaos(self, shard_run):
        runner, _report = shard_run
        lines = [
            json.loads(line) for line in runner.trace.text().splitlines()
        ]
        kinds = {l["t"] for l in lines}
        assert {"meta", "tick", "ev", "dig", "fleet", "report"} <= kinds
        evs = [l for l in lines if l["t"] == "ev"]
        ev_kinds = {l["kind"] for l in evs}
        # every chaos decision was resolved onto the tape (no rng in
        # replay): kills name their shard, faults name their kind
        assert {"shard_kill", "shard_split", "wire_fault", "fsync_fail"} <= (
            ev_kinds
        )
        for l in evs:
            if l["kind"] == "shard_kill":
                assert isinstance(l["data"]["shard"], int)
            if l["kind"] == "wire_fault":
                assert l["data"]["fault"]

    def test_scenario_registered(self):
        assert "store-fleet-shard-chaos" in FLEET_SCENARIOS

    @pytest.mark.slow
    def test_run_run_byte_identical(self, shard_run):
        runner, report = shard_run
        logging.disable(logging.WARNING)
        try:
            runner2, report2 = run_fleet("store-fleet-shard-chaos", 0, TICKS)
        finally:
            logging.disable(logging.NOTSET)
        assert report2 == report
        assert runner2.trace.text() == runner.trace.text()

    @pytest.mark.slow
    def test_replay_byte_identical(self, shard_run, tmp_path):
        runner, report = shard_run
        path = tmp_path / "fleet-shards.jsonl"
        path.write_text(runner.trace.text())
        logging.disable(logging.WARNING)
        try:
            runner3, report3, recorded = replay_fleet(str(path))
        finally:
            logging.disable(logging.NOTSET)
        assert recorded == report
        assert report3 == report
        assert runner3.trace.text() == runner.trace.text()
        # the tape reader agrees on scenario identity
        meta = read_fleet_tape(str(path))[0]
        assert meta["scenario"] == "store-fleet-shard-chaos"
