"""Tensor-solver tests: kernel behavior + parity with the FFD oracle.

Mirrors the reference's test strategy (SURVEY.md §4): real scheduling logic
over the fake cloud, with the oracle (scheduling/scheduler.py) as the
semantics definition the kernel must match or beat.
"""

import random

import numpy as np
import pytest

from karpenter_tpu.api import Pod, Requirement, Resources, Taint, Toleration
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import PodAffinityTerm, TopologySpreadConstraint
from karpenter_tpu.api.requirements import Op
from karpenter_tpu.ops.tensorize import compile_problem
from karpenter_tpu.scheduling import Scheduler, TensorScheduler
from karpenter_tpu.testing import Environment


@pytest.fixture(scope="module")
def env():
    return Environment()


@pytest.fixture(scope="module")
def setup(env):
    pool = env.default_node_pool()
    nc = env.default_node_class()
    types = env.instance_types.list(pool, nc)
    return pool, types


def both(pool, types, pods, **kw):
    oracle = Scheduler([pool], {pool.name: types}, **kw).solve(pods)
    ts = TensorScheduler([pool], {pool.name: types}, **kw)
    tensor = ts.solve(pods)
    return oracle, tensor, ts


# ---------------------------------------------------------------------------
# compile_problem
# ---------------------------------------------------------------------------


class TestTensorize:
    def test_classes_group_identical_pods(self, setup):
        pool, types = setup
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(50)]
        prob = compile_problem(pods, [pool], {pool.name: types})
        assert len(prob.classes) == 1
        assert prob.cnt[0] == 50
        assert prob.supported

    def test_configs_cover_zones_and_capacity_types(self, setup):
        pool, types = setup
        pods = [Pod(requests=Resources(cpu=1))]
        prob = compile_problem(pods, [pool], {pool.name: types})
        zones = {c.zone for c in prob.configs}
        cts = {c.capacity_type for c in prob.configs}
        assert zones == {"zone-a", "zone-b", "zone-c"}
        assert cts == {L.CAPACITY_TYPE_ON_DEMAND, L.CAPACITY_TYPE_SPOT}

    def test_node_selector_masks_feasibility(self, setup):
        pool, types = setup
        pod = Pod(
            requests=Resources(cpu=1),
            node_selector={L.LABEL_ARCH: "arm64"},
        )
        prob = compile_problem([pod], [pool], {pool.name: types})
        for c_idx in np.nonzero(prob.feas[0])[0]:
            cfg = prob.configs[c_idx]
            req = cfg.instance_type.requirements.get(L.LABEL_ARCH)
            assert req.has("arm64")

    def test_unsupported_constraints_reported(self, setup):
        pool, types = setup
        # hostname-keyed required affinity (same-node co-location) is the
        # remaining oracle-only shape
        pod = Pod(
            requests=Resources(cpu=1),
            pod_affinity=[
                PodAffinityTerm(
                    topology_key=L.LABEL_HOSTNAME,
                    label_selector=(("app", "x"),),
                    anti=False,
                )
            ],
        )
        prob = compile_problem([pod], [pool], {pool.name: types})
        assert not prob.supported

    def test_zone_spread_splits_classes(self, setup):
        pool, types = setup
        sel = (("app", "s"),)
        pods = [
            Pod(
                labels={"app": "s"},
                requests=Resources(cpu=1),
                topology_spread=[
                    TopologySpreadConstraint(
                        max_skew=1, topology_key=L.LABEL_ZONE, label_selector=sel
                    )
                ],
            )
            for _ in range(10)
        ]
        prob = compile_problem(pods, [pool], {pool.name: types})
        zone_pins = sorted(cm.zone_pin for cm in prob.classes)
        assert zone_pins == ["zone-a", "zone-b", "zone-c"]
        counts = sorted(len(cm.pods) for cm in prob.classes)
        assert counts == [3, 3, 4]


# ---------------------------------------------------------------------------
# Solver vs oracle parity
# ---------------------------------------------------------------------------


class TestParity:
    def test_homogeneous_matches_oracle(self, setup):
        pool, types = setup
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(200)]
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        assert not tensor.unschedulable
        assert tensor.node_count() <= oracle.node_count()
        assert sum(len(n.pods) for n in tensor.new_nodes) == 200

    def test_heterogeneous_close_to_oracle(self, setup):
        pool, types = setup
        random.seed(7)
        pods = []
        for i in range(300):
            pods.append(
                Pod(
                    requests=Resources(
                        cpu=random.choice([0.25, 0.5, 1, 2]),
                        memory=random.choice(["256Mi", "1Gi", "4Gi"]),
                    )
                )
            )
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        assert not tensor.unschedulable
        # quality bar: within 15% of the oracle's node count
        assert tensor.node_count() <= max(oracle.node_count() * 1.15, 1)

    def test_hostname_anti_affinity_one_per_node(self, setup):
        pool, types = setup
        sel = (("app", "dense"),)
        pods = [
            Pod(
                labels={"app": "dense"},
                requests=Resources(cpu=0.25),
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_HOSTNAME, label_selector=sel, anti=True
                    )
                ],
            )
            for _ in range(40)
        ]
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        assert tensor.node_count() == oracle.node_count() == 40
        assert all(len(n.pods) == 1 for n in tensor.new_nodes)

    def test_zone_spread_balances(self, setup):
        pool, types = setup
        sel = (("app", "z"),)
        pods = [
            Pod(
                labels={"app": "z"},
                requests=Resources(cpu=1, memory="1Gi"),
                topology_spread=[
                    TopologySpreadConstraint(
                        max_skew=1, topology_key=L.LABEL_ZONE, label_selector=sel
                    )
                ],
            )
            for _ in range(90)
        ]
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        zone_counts = {}
        for n in tensor.new_nodes:
            zone = n.requirements.get(L.LABEL_ZONE).any_value()
            zone_counts[zone] = zone_counts.get(zone, 0) + len(n.pods)
        assert max(zone_counts.values()) - min(zone_counts.values()) <= 1

    def test_zone_spread_levels_existing_skew(self, setup):
        """Bound pods matched by the spread SELECTOR (even if they carry no
        constraint themselves) must seed the skew counts — new placements go
        to the under-filled zones."""
        pool, types = setup
        from karpenter_tpu.state.cluster import StateNode

        bound = [Pod(labels={"app": "z"}, node_name="node-a") for _ in range(4)]
        existing = StateNode(
            name="node-a",
            provider_id="i-a",
            labels={
                L.LABEL_ZONE: "zone-a",
                L.LABEL_ARCH: "amd64",
                L.LABEL_OS: "linux",
            },
            taints=[],
            allocatable=Resources(cpu=0.5, pods=110),  # no room for new pods
            pods=bound,
        )
        sel = (("app", "z"),)
        pods = [
            Pod(
                labels={"app": "z"},
                requests=Resources(cpu=1, memory="1Gi"),
                topology_spread=[
                    TopologySpreadConstraint(
                        max_skew=1, topology_key=L.LABEL_ZONE, label_selector=sel
                    )
                ],
            )
            for _ in range(5)
        ]
        ts = TensorScheduler([pool], {pool.name: types}, existing=[existing])
        r = ts.solve(pods)
        assert ts.last_path == "tensor"
        totals = {"zone-a": 4, "zone-b": 0, "zone-c": 0}
        for n in r.new_nodes:
            zone = n.requirements.get(L.LABEL_ZONE).any_value()
            totals[zone] += len(n.pods)
        # leveling optimum given the pre-existing 4-in-zone-a: 4/3/2 (the
        # oracle produces the same); the buggy blank-slate split gave 6/2/1
        assert totals == {"zone-a": 4, "zone-b": 3, "zone-c": 2}, totals

    def test_zone_spread_respects_pod_zone_requirements(self, setup):
        """A zone-spread pod restricted to two zones must only split across
        those zones (Kubernetes filters skew domains by nodeAffinity)."""
        pool, types = setup
        sel = (("app", "zz"),)
        pods = [
            Pod(
                labels={"app": "zz"},
                requests=Resources(cpu=1),
                node_selector={},
                required_affinity=[
                    Requirement(L.LABEL_ZONE, Op.IN, ["zone-a", "zone-b"])
                ],
                topology_spread=[
                    TopologySpreadConstraint(
                        max_skew=1, topology_key=L.LABEL_ZONE, label_selector=sel
                    )
                ],
            )
            for _ in range(8)
        ]
        ts = TensorScheduler([pool], {pool.name: types})
        r = ts.solve(pods)
        assert not r.unschedulable
        zones = {
            n.requirements.get(L.LABEL_ZONE).any_value() for n in r.new_nodes
        }
        assert zones <= {"zone-a", "zone-b"}

    def test_tolerations_against_tainted_pool(self, env, setup):
        _, types = setup
        tainted = env.default_node_pool(
            name="tainted", taints=[Taint(key="team", value="ml")]
        )
        pods_no_tol = [Pod(requests=Resources(cpu=1))]
        pods_tol = [
            Pod(
                requests=Resources(cpu=1),
                tolerations=[Toleration(key="team", value="ml")],
            )
        ]
        ts = TensorScheduler([tainted], {"tainted": types})
        r1 = ts.solve(pods_no_tol)
        assert len(r1.unschedulable) == 1
        r2 = ts.solve(pods_tol)
        assert r2.node_count() == 1

    def test_zone_pod_affinity_on_tensor_path(self, setup):
        """Zone-keyed required pod affinity compiles to a zone anchor and
        stays on the TPU path (round-1 VERDICT item #1)."""
        pool, types = setup
        sel = (("app", "a"),)
        pods = [
            Pod(
                labels={"app": "a"},
                requests=Resources(cpu=1),
                pod_affinity=[
                    PodAffinityTerm(topology_key=L.LABEL_ZONE, label_selector=sel)
                ],
            )
            for _ in range(6)
        ]
        ts = TensorScheduler([pool], {pool.name: types})
        r = ts.solve(pods)
        assert ts.last_path == "tensor"
        assert not r.unschedulable
        # all anchored in one zone
        zones = {
            n.requirements.get(L.LABEL_ZONE).any_value() for n in r.new_nodes
        }
        assert len(zones) == 1

    def test_requirement_gt_lt(self, setup):
        pool, types = setup
        pod = Pod(
            requests=Resources(cpu=1),
            required_affinity=[
                Requirement(L.LABEL_INSTANCE_CPU, Op.GT, ["8"]),
                Requirement(L.LABEL_INSTANCE_CPU, Op.LT, ["64"]),
            ],
        )
        ts = TensorScheduler([pool], {pool.name: types})
        r = ts.solve([pod])
        assert ts.last_path == "tensor"
        assert r.node_count() == 1
        it = r.new_nodes[0].feasible_types[0]
        assert 8 < it.capacity.cpu < 64

    def test_existing_nodes_used_first(self, env, setup):
        pool, types = setup
        from karpenter_tpu.state.cluster import StateNode

        existing = StateNode(
            name="node-1",
            provider_id="i-1",
            labels={
                L.LABEL_ARCH: "amd64",
                L.LABEL_OS: "linux",
                L.LABEL_ZONE: "zone-a",
                L.LABEL_NODEPOOL: pool.name,
            },
            taints=[],
            allocatable=Resources(cpu=8, memory="32Gi", pods=110),
        )
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(4)]
        ts = TensorScheduler([pool], {pool.name: types}, existing=[existing])
        r = ts.solve(pods)
        assert ts.last_path == "tensor"
        assert r.node_count() == 0
        assert len(r.existing_placements) == 4

    def test_unschedulable_when_nothing_fits(self, setup):
        pool, types = setup
        pod = Pod(requests=Resources(cpu=10000))
        ts = TensorScheduler([pool], {pool.name: types})
        r = ts.solve([pod])
        assert len(r.unschedulable) == 1

    def test_spot_preferred_when_flexible(self, setup):
        pool, types = setup
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(10)]
        _, tensor, _ = both(pool, types, pods)
        for n in tensor.new_nodes:
            ct = n.requirements.get(L.LABEL_CAPACITY_TYPE).any_value()
            assert ct == L.CAPACITY_TYPE_SPOT  # spot is cheaper in the fake

    def test_on_demand_when_pinned(self, setup):
        pool, types = setup
        pods = [
            Pod(
                requests=Resources(cpu=1),
                node_selector={L.LABEL_CAPACITY_TYPE: L.CAPACITY_TYPE_ON_DEMAND},
            )
        ]
        ts = TensorScheduler([pool], {pool.name: types})
        r = ts.solve(pods)
        ct = r.new_nodes[0].requirements.get(L.LABEL_CAPACITY_TYPE).any_value()
        assert ct == L.CAPACITY_TYPE_ON_DEMAND

    def test_weighted_pools_respected(self, env, setup):
        _, types = setup
        heavy = env.default_node_pool(name="heavy", weight=10)
        light = env.default_node_pool(name="light", weight=1)
        pods = [Pod(requests=Resources(cpu=1)) for _ in range(5)]
        ts = TensorScheduler([light, heavy], {"heavy": types, "light": types})
        r = ts.solve(pods)
        for n in r.new_nodes:
            assert n.pool.name == "heavy"


# ---------------------------------------------------------------------------
# Coupled constraints on the tensor path (round-2: VERDICT item #1)
# ---------------------------------------------------------------------------


class TestCoupledConstraints:
    def _zone_of(self, node):
        return node.requirements.get(L.LABEL_ZONE).any_value()

    def test_cross_class_zone_affinity_anchors_together(self, setup):
        """Class A requires zone co-location with class B (different sig):
        the whole component pins to one zone, on the tensor path."""
        pool, types = setup
        b_pods = [
            Pod(labels={"app": "b"}, requests=Resources(cpu=2, memory="4Gi"))
            for _ in range(4)
        ]
        a_pods = [
            Pod(
                labels={"app": "a"},
                node_selector={L.LABEL_ARCH: "amd64"},  # distinct signature
                requests=Resources(cpu=1, memory="2Gi"),
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_ZONE, label_selector=(("app", "b"),)
                    )
                ],
            )
            for _ in range(4)
        ]
        ts = TensorScheduler([pool], {pool.name: types})
        r = ts.solve(a_pods + b_pods)
        assert ts.last_path == "tensor"
        assert not r.unschedulable
        zones = {self._zone_of(n) for n in r.new_nodes}
        assert len(zones) == 1

    def test_zone_affinity_follows_existing_anchor(self, env, setup):
        """Existing matching pods anchor the domain; followers join it."""
        pool, types = setup
        from karpenter_tpu.state.cluster import StateNode

        anchor_pod = Pod(labels={"app": "z"}, requests=Resources(cpu=1))
        anchor_pod.node_name = "existing-b"
        sn = StateNode(
            name="existing-b",
            provider_id="i-exist",
            labels={
                L.LABEL_ZONE: "zone-b",
                L.LABEL_NODEPOOL: pool.name,
                L.LABEL_CAPACITY_TYPE: L.CAPACITY_TYPE_ON_DEMAND,
            },
            taints=[],
            allocatable=Resources(cpu=64, memory="256Gi", pods=110),
            pods=[anchor_pod],
            used=anchor_pod.requests,
        )
        pods = [
            Pod(
                labels={"app": "z"},
                requests=Resources(cpu=4, memory="8Gi"),
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_ZONE, label_selector=(("app", "z"),)
                    )
                ],
            )
            for _ in range(5)
        ]
        ts = TensorScheduler([pool], {pool.name: types}, existing=[sn])
        r = ts.solve(pods)
        assert ts.last_path == "tensor"
        assert not r.unschedulable
        for n in r.new_nodes:
            assert self._zone_of(n) == "zone-b"

    def test_zone_anti_affinity_distinct_zones(self, setup):
        """Self-selecting zone anti-affinity: one matching pod per zone."""
        pool, types = setup
        pods = [
            Pod(
                labels={"app": "s"},
                requests=Resources(cpu=1),
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_ZONE,
                        label_selector=(("app", "s"),),
                        anti=True,
                    )
                ],
            )
            for _ in range(3)
        ]
        ts = TensorScheduler([pool], {pool.name: types})
        r = ts.solve(pods)
        assert ts.last_path == "tensor"
        assert not r.unschedulable
        zones = [self._zone_of(n) for n in r.new_nodes]
        assert sorted(zones) == ["zone-a", "zone-b", "zone-c"]

    def test_zone_anti_affinity_overflow_unschedulable(self, setup):
        """More matching pods than zones: the excess is unschedulable with
        a specific reason (matches the oracle's outcome)."""
        pool, types = setup
        def mk():
            return Pod(
                labels={"app": "s"},
                requests=Resources(cpu=1),
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_ZONE,
                        label_selector=(("app", "s"),),
                        anti=True,
                    )
                ],
            )
        oracle, tensor, ts = both(setup[0], setup[1], [mk() for _ in range(5)])
        assert ts.last_path == "tensor"
        assert len(tensor.unschedulable) == 2
        assert len(oracle.unschedulable) == 2
        assert "zone anti-affinity" in next(iter(tensor.unschedulable.values()))

    def test_zone_anti_affinity_respects_existing(self, setup):
        """Zones already holding a matching pod are excluded."""
        pool, types = setup
        from karpenter_tpu.state.cluster import StateNode

        placed = Pod(labels={"app": "s"}, requests=Resources(cpu=1))
        placed.node_name = "existing-a"
        sn = StateNode(
            name="existing-a",
            provider_id="i-a",
            labels={
                L.LABEL_ZONE: "zone-a",
                L.LABEL_NODEPOOL: pool.name,
                L.LABEL_CAPACITY_TYPE: L.CAPACITY_TYPE_ON_DEMAND,
            },
            taints=[],
            allocatable=Resources(cpu=64, memory="256Gi", pods=110),
            pods=[placed],
            used=placed.requests,
        )
        pods = [
            Pod(
                labels={"app": "s"},
                requests=Resources(cpu=1),
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_ZONE,
                        label_selector=(("app", "s"),),
                        anti=True,
                    )
                ],
            )
            for _ in range(2)
        ]
        ts = TensorScheduler([pool], {pool.name: types}, existing=[sn])
        r = ts.solve(pods)
        assert ts.last_path == "tensor"
        assert not r.unschedulable
        zones = sorted(self._zone_of(n) for n in r.new_nodes)
        assert zones == ["zone-b", "zone-c"]


class TestHybridSolve:
    def test_one_exotic_pod_does_not_oracle_the_batch(self, setup):
        """A CROSS-CLASS hostname-affinity group (oracle-only: the
        selector reaches another class) rides along with a large plain
        batch: the plain pods solve on the tensor path (round-1 VERDICT
        weak #2 / fix #8)."""
        pool, types = setup
        plain = [
            Pod(requests=Resources(cpu=1, memory="2Gi")) for _ in range(200)
        ]
        anchor = Pod(labels={"team": "y"}, requests=Resources(cpu=1))
        exotic = [
            Pod(
                labels={"app": "h"},
                requests=Resources(cpu=1),
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_HOSTNAME,
                        label_selector=(("team", "y"),),
                    )
                ],
            )
            for _ in range(3)
        ]
        ts = TensorScheduler([pool], {pool.name: types})
        r = ts.solve(plain + [anchor] + exotic)
        assert ts.last_path == "hybrid"
        assert not r.unschedulable
        placed = sum(len(n.pods) for n in r.new_nodes) + len(
            r.existing_placements
        )
        assert placed == 204
        # hostname affinity satisfied: followers on the anchor's node
        exotic_nodes = {
            n.name
            for n in r.new_nodes
            for p in n.pods
            if p.labels.get("app") == "h" or p.labels.get("team") == "y"
        }
        assert len(exotic_nodes) == 1

    def test_self_coloc_group_compiles_to_tensor(self, setup):
        """Self-selecting hostname co-location now compiles (macro
        placement unit): pure tensor path, whole group on ONE node."""
        pool, types = setup
        plain = [Pod(requests=Resources(cpu=1, memory="2Gi")) for _ in range(50)]
        group = [
            Pod(
                labels={"app": "co"},
                requests=Resources(cpu=1, memory="1Gi"),
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_HOSTNAME,
                        label_selector=(("app", "co"),),
                    )
                ],
            )
            for _ in range(5)
        ]
        ts = TensorScheduler([pool], {pool.name: types})
        r = ts.solve(plain + group)
        assert ts.last_path == "tensor"
        assert not r.unschedulable
        coloc_nodes = {
            n.name for n in r.new_nodes for p in n.pods
            if p.labels.get("app") == "co"
        }
        assert len(coloc_nodes) == 1
        node = next(n for n in r.new_nodes if n.name in coloc_nodes)
        assert sum(1 for p in node.pods if p.labels.get("app") == "co") == 5

    def test_oversized_coloc_group_unschedulable(self, setup):
        """A group whose sum fits no single node is wholly unschedulable
        (real-scheduler bind semantics: the first bound member pins all
        others to its node)."""
        pool, types = setup
        biggest = max(t.capacity.cpu for t in types)
        n = int(biggest // 4) + 2  # 4-cpu members; sum exceeds every node
        group = [
            Pod(
                labels={"app": "huge"},
                requests=Resources(cpu=4, memory="1Gi"),
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_HOSTNAME,
                        label_selector=(("app", "huge"),),
                    )
                ],
            )
            for _ in range(n)
        ]
        ts = TensorScheduler([pool], {pool.name: types})
        r = ts.solve(group)
        assert len(r.unschedulable) == n

    def test_coloc_with_live_members_goes_oracle(self, setup):
        """Members already running on a live node force the oracle (the
        group must JOIN that node, which the macro can't express)."""
        from karpenter_tpu.ops.tensorize import partition_groups
        from karpenter_tpu.state.cluster import StateNode

        pool, types = setup
        member = Pod(labels={"app": "co"}, requests=Resources(cpu=1))
        live = StateNode(
            name="n1", provider_id="i-1", labels={}, taints=[],
            allocatable=Resources(cpu=8), capacity=Resources(cpu=8),
            pods=[member],
        )
        incoming = [
            Pod(
                labels={"app": "co"},
                requests=Resources(cpu=1),
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_HOSTNAME,
                        label_selector=(("app", "co"),),
                    )
                ],
            )
            for _ in range(2)
        ]
        _, unsupported, why = partition_groups(incoming, existing=[live])
        assert len(unsupported) == 2
        assert "live nodes" in why
        # without live members the same pods compile
        sup, unsupported2, _ = partition_groups(incoming)
        assert not unsupported2 and sup
        # the non-presplit compile gate sees live members too (direct
        # compile_problem callers get the same protection)
        from karpenter_tpu.ops.tensorize import compile_problem

        prob = compile_problem(
            incoming, [pool], {pool.name: types}, existing=[live]
        )
        assert "live nodes" in prob.unsupported_reason

    def test_hybrid_closure_pulls_coupled_classes(self, setup):
        """A spread constraint whose selector reaches an oracle-only class
        drags that class to the oracle half too (soundness of the split)."""
        pool, types = setup
        from karpenter_tpu.ops.tensorize import partition_pods

        exotic = Pod(
            labels={"team": "x"},
            requests=Resources(cpu=1),
            pod_affinity=[
                PodAffinityTerm(
                    topology_key=L.LABEL_HOSTNAME, label_selector=(("team", "x"),)
                )
            ],
        )
        spreader = Pod(
            labels={"team": "x", "app": "s"},
            requests=Resources(cpu=2),
            topology_spread=[
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=L.LABEL_ZONE,
                    label_selector=(("team", "x"),),
                )
            ],
        )
        plain = [Pod(requests=Resources(cpu=1)) for _ in range(5)]
        supported, unsupported, _ = partition_pods([exotic, spreader] + plain)
        assert len(unsupported) == 2  # exotic + coupled spreader
        assert len(supported) == 5

    def test_hybrid_parity_with_oracle(self, setup):
        """Mixed batch: hybrid node count stays <= the pure-oracle count."""
        pool, types = setup
        random.seed(7)
        pods = []
        for i in range(120):
            pods.append(Pod(requests=Resources(cpu=random.choice([1, 2, 4]))))
        # one-sided anti coupling keeps a small closure oracle-side: the
        # watchers' terms select the co pods, which carry no term
        for i in range(4):
            pods.append(
                Pod(
                    labels={"app": "co", "variant": str(i % 2)},
                    requests=Resources(cpu=2),
                )
            )
        for i in range(2):
            pods.append(
                Pod(
                    labels={"role": "watcher"},
                    requests=Resources(cpu=1),
                    pod_affinity=[
                        PodAffinityTerm(
                            topology_key=L.LABEL_HOSTNAME,
                            label_selector=(("app", "co"),),
                            anti=True,
                        )
                    ],
                )
            )
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "hybrid"
        assert not tensor.unschedulable
        # the tensor half right-sizes for the plain pods before the
        # oracle sees the anti-coupled classes: at most one extra node
        assert tensor.node_count() <= oracle.node_count() + 1


class TestCrossClassColocMerge:
    """Node-equivalent hostname co-location closures compile as ONE macro
    placement unit (ops/tensorize.py:_coloc_component_mergeable) instead of
    falling to the oracle."""

    def _group(self, g, n=5, cross=True, **pod_kw):
        pods = []
        term = PodAffinityTerm(
            topology_key=L.LABEL_HOSTNAME,
            label_selector=(("pair", f"host-{g}"),),
        )
        for i in range(n):
            labels = {"pair": f"host-{g}"}
            if cross:
                labels["variant"] = str(i % 2)
            pods.append(
                Pod(
                    labels=labels,
                    requests=Resources(cpu=1, memory="2Gi"),
                    pod_affinity=[term],
                    **pod_kw,
                )
            )
        return pods

    def test_cross_class_compiles_and_colocates(self, setup):
        pool, types = setup
        pods = [Pod(requests=Resources(cpu=1)) for _ in range(40)]
        for g in range(6):
            pods += self._group(g)
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        assert not tensor.unschedulable
        assert tensor.node_count() <= oracle.node_count()
        by_group = {}
        for vn in tensor.new_nodes:
            for p in vn.pods:
                if p.pod_affinity:
                    by_group.setdefault(p.labels["pair"], set()).add(vn.name)
        assert len(by_group) == 6
        assert all(len(nodes) == 1 for nodes in by_group.values())

    def test_one_sig_many_request_classes_merges(self, setup):
        """A single self-selecting signature spanning several request
        classes (previously 'across multiple resource classes' -> oracle)
        now merges into one unit."""
        pool, types = setup
        term = PodAffinityTerm(
            topology_key=L.LABEL_HOSTNAME, label_selector=(("app", "db"),)
        )
        pods = [
            Pod(
                labels={"app": "db"},
                requests=Resources(cpu=c),
                pod_affinity=[term],
            )
            for c in (1, 2, 4)
        ]
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        assert not tensor.unschedulable
        assert tensor.node_count() == 1

    def test_node_inequivalent_closure_compiles(self, setup):
        """Members differing in tolerations (node-INEQUIVALENT) compile as
        one macro unit whose feasibility row is the AND of the members' —
        the whole group must land on one node, so intersection is exact."""
        pool, types = setup
        pods = [Pod(requests=Resources(cpu=1)) for _ in range(10)]
        group = self._group(0)
        for i, p in enumerate(group):
            if i % 2:
                p.tolerations = [
                    Toleration(key="burst", value="yes", effect="NoSchedule")
                ]
        pods += group
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        assert not tensor.unschedulable
        nodes = {
            vn.name
            for vn in tensor.new_nodes
            for p in vn.pods
            if p.labels.get("pair") == "host-0"
        }
        assert len(nodes) == 1
        assert tensor.node_count() <= oracle.node_count() + 1

    def test_inequivalent_closure_selector_intersects(self, setup):
        """A member pinning the pool via node selector narrows the whole
        group: every member lands on the selected pool's node."""
        pool, types = setup
        group = self._group(0)
        group[1].node_selector = {L.LABEL_NODEPOOL: pool.name}
        oracle, tensor, ts = both(pool, types, group)
        assert ts.last_path == "tensor"
        assert not tensor.unschedulable
        assert tensor.node_count() == 1
        assert tensor.new_nodes[0].pool.name == pool.name

    def test_preference_differing_closure_compiles(self, setup):
        """Members differing in PREFERRED affinity merge too: each
        member's preferences fold into its OWN feasibility row, so the
        group compiles pinned where the satisfiable preference points."""
        pool, types = setup
        group = self._group(0)
        group[0].preferred_affinity = [
            Requirement(L.LABEL_ZONE, Op.IN, ["zone-a"])
        ]
        pods = [Pod(requests=Resources(cpu=1)) for _ in range(10)] + group
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        assert not tensor.unschedulable
        nodes = set()
        for vn in tensor.new_nodes:
            for p in vn.pods:
                if p.labels.get("pair") == "host-0":
                    nodes.add(vn.name)
                    # the carrier's preference is honored by the group
                    assert vn.zone_options() == {"zone-a"}
        assert len(nodes) == 1

    def test_preference_differing_closure_relaxes_as_a_unit(self, setup):
        """An IMPOSSIBLE preference on one member (the others carry
        none): preference lists DIFFER, so the compile ladder must not
        peel uniformly — the whole closure relaxes through the oracle,
        which peels per member, and the group still lands together."""
        pool, types = setup
        group = self._group(0)
        group[0].preferred_affinity = [
            Requirement(L.LABEL_ZONE, Op.IN, ["zone-nowhere"])
        ]
        pods = [Pod(requests=Resources(cpu=1)) for _ in range(10)] + group
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "hybrid"  # relaxed as a unit via the oracle
        assert not tensor.unschedulable
        nodes = {
            vn.name
            for vn in tensor.new_nodes
            for p in vn.pods
            if p.labels.get("pair") == "host-0"
        }
        assert len(nodes) == 1

    def test_mixed_satisfiability_prefs_closure_peels_per_member(self, setup):
        """Members with DIFFERING preference lists where one member's is
        impossible: the compile ladder must NOT peel uniformly (that
        would drop the satisfiable preference too) — the closure relaxes
        as a unit through the oracle, which peels only the impossible
        one and keeps the group pinned where the satisfiable preference
        points."""
        pool, types = setup
        group = self._group(0, n=4)
        group[0].preferred_affinity = [
            Requirement(L.LABEL_ZONE, Op.IN, ["zone-a"])  # satisfiable
        ]
        group[1].preferred_affinity = [
            Requirement(L.LABEL_ZONE, Op.IN, ["zone-nowhere"])  # not
        ]
        pods = [Pod(requests=Resources(cpu=1)) for _ in range(10)] + group
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "hybrid"  # relaxed as a unit via the oracle
        assert not tensor.unschedulable, tensor.unschedulable
        nodes = {
            id(vn): vn
            for vn in tensor.new_nodes
            for p in vn.pods
            if p.labels.get("pair") == "host-0"
        }
        assert len(nodes) == 1, {v.name for v in nodes.values()}
        # the group honors the SATISFIABLE member's preference
        (vn,) = nodes.values()
        assert vn.zone_options() == {"zone-a"}


    def test_conflicting_inequivalent_closure_unschedulable(self, setup):
        """Disjoint node selectors across members make the intersection
        empty: the whole group reports unschedulable (gang semantics, same
        as the oversized-group case)."""
        pool, types = setup
        group = self._group(0, n=4)
        group[0].node_selector = {L.LABEL_NODEPOOL: pool.name}
        group[1].node_selector = {L.LABEL_NODEPOOL: "nowhere"}
        ts = TensorScheduler([pool], {pool.name: types})
        res = ts.solve(group)
        assert ts.last_path == "tensor"
        assert len(res.unschedulable) == len(group)

    def test_closure_with_spread_member_stays_oracle(self, setup):
        """A closure member carrying a topology spread is not mergeable."""
        pool, types = setup
        group = self._group(0)
        group[0].topology_spread = [
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=L.LABEL_ZONE,
                label_selector=(("pair", "host-0"),),
            )
        ]
        pods = [Pod(requests=Resources(cpu=1)) for _ in range(10)] + group
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "hybrid"

    def test_closure_with_live_members_stays_oracle(self, setup, env):
        """Selector reaching a pod bound on a live node: the group must
        JOIN that node, which the macro can't express."""
        from karpenter_tpu.ops.tensorize import partition_groups
        from karpenter_tpu.state.cluster import StateNode

        pool, types = setup
        bound = Pod(labels={"pair": "host-0"}, requests=Resources(cpu=1))
        live = StateNode(
            name="live-1",
            provider_id="fake://live-1",
            labels={L.LABEL_ZONE: "zone-a"},
            taints=[],
            allocatable=Resources(cpu=8, memory="32Gi"),
            pods=[bound],
        )
        group = self._group(0)
        sup, unsup, why = partition_groups(group, existing=[live])
        assert len(unsup) == len(group)
        assert why  # whole closure stays oracle
        # the SELF-selecting single-class shape reports the live-member
        # reason directly
        solo = [p for p in self._group(0, cross=False)]
        sup2, unsup2, why2 = partition_groups(solo, existing=[live])
        assert len(unsup2) == len(solo)
        assert "live nodes" in why2

    def test_merged_closure_nonrep_extended_resource_capacitated(self, setup):
        """An extended resource requested only by a NON-rep member must get
        a capacity axis: no fake type carries it, so the merged group is
        unschedulable — not silently placed."""
        pool, types = setup
        term = PodAffinityTerm(
            topology_key=L.LABEL_HOSTNAME, label_selector=(("pair", "fpga"),)
        )
        a = Pod(
            labels={"pair": "fpga", "variant": "0"},
            requests=Resources(cpu=1),
            pod_affinity=[term],
        )
        b = Pod(
            labels={"pair": "fpga", "variant": "1"},
            requests=Resources({"cpu": 1, "example.com/fpga": 1}),
            pod_affinity=[term],
        )
        ts = TensorScheduler([pool], {pool.name: types})
        res = ts.solve([a, b])
        assert ts.last_path == "tensor"
        assert len(res.unschedulable) == 2
        assert not res.new_nodes

    def test_hybrid_memory_pod_joins_tensor_node(self, setup):
        """A continued (oracle-half) pod with a MEMORY request must join a
        tensor-decoded node that has room — the decode headroom hint is in
        raw units, not the compiled MiB scale."""
        pool, types = setup
        plain = [Pod(requests=Resources(cpu=1, memory="2Gi")) for _ in range(6)]
        # ONE-SIDED anti coupling: the watcher's term selects the mem
        # pods, which carry no term themselves — asymmetric, oracle-only
        watcher = Pod(
            labels={"role": "watch"},
            requests=Resources(cpu=0.25, memory="256Mi"),
            pod_affinity=[
                PodAffinityTerm(
                    topology_key=L.LABEL_HOSTNAME,
                    label_selector=(("pair", "mem"),),
                    anti=True,
                )
            ],
        )
        group = [watcher] + [
            Pod(
                labels={"pair": "mem", "variant": str(i % 2)},
                requests=Resources(cpu=0.25, memory="512Mi"),
            )
            for i in range(2)
        ]
        ts = TensorScheduler([pool], {pool.name: types})
        res = ts.solve(plain + group)
        assert ts.last_path == "hybrid"
        assert not res.unschedulable
        oracle = Scheduler([pool], {pool.name: types}).solve(plain + group)
        # the group fits beside the plain pods on the tensor node(s):
        # no extra node vs the pure-oracle pack
        assert res.node_count() <= oracle.node_count()

    def test_spread_group_spanning_request_classes_balances_sum(self, setup):
        """A service whose pods span several REQUEST classes must balance
        the GROUP total across zones, not each class independently — three
        per-class remainders stacking on zone-a would breach maxSkew."""
        pool, types = setup
        sel = (("svc", "multi"),)
        c = TopologySpreadConstraint(
            max_skew=1, topology_key=L.LABEL_ZONE, label_selector=sel
        )
        pods = []
        for n, cpu in ((8, 0.25), (6, 1), (14, 2)):  # 28 pods, 3 classes
            for _ in range(n):
                pods.append(
                    Pod(
                        labels={"svc": "multi"},
                        requests=Resources(cpu=cpu, memory="1Gi"),
                        topology_spread=[c],
                    )
                )
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        assert not tensor.unschedulable
        counts = {}
        for vn in tensor.new_nodes:
            zone = vn.requirements.get(L.LABEL_ZONE).any_value()
            counts[zone] = counts.get(zone, 0) + len(vn.pods)
        assert max(counts.values()) - min(counts.values()) <= 1, counts

    def test_cross_class_mutual_zone_spread_compiles(self, setup):
        """Pods of one service differing in a variant label (distinct
        signatures) mutually carrying the identical zone spread compile to
        the tensor path with the group total balanced."""
        pool, types = setup
        sel = (("svc", "web"),)
        c = TopologySpreadConstraint(
            max_skew=1, topology_key=L.LABEL_ZONE, label_selector=sel
        )
        pods = [
            Pod(
                labels={"svc": "web", "variant": str(i % 2)},
                requests=Resources(cpu=1, memory="2Gi"),
                topology_spread=[c],
            )
            for i in range(28)
        ]
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        assert not tensor.unschedulable
        counts = {}
        for vn in tensor.new_nodes:
            zone = vn.requirements.get(L.LABEL_ZONE).any_value()
            counts[zone] = counts.get(zone, 0) + len(vn.pods)
        assert max(counts.values()) - min(counts.values()) <= 1, counts

    def test_one_sided_spread_coupling_stays_oracle(self, setup):
        """A class counted by the group but not carrying the constraint
        (one-sided coupling) still needs the oracle's runtime counts."""
        from karpenter_tpu.ops.tensorize import partition_groups

        pool, types = setup
        sel = (("svc", "web2"),)
        c = TopologySpreadConstraint(
            max_skew=1, topology_key=L.LABEL_ZONE, label_selector=sel
        )
        carriers = [
            Pod(
                labels={"svc": "web2"},
                requests=Resources(cpu=1),
                topology_spread=[c],
            )
            for _ in range(6)
        ]
        counted_only = [
            Pod(labels={"svc": "web2", "variant": "x"}, requests=Resources(cpu=1))
            for _ in range(3)
        ]
        sup, unsup, why = partition_groups(carriers + counted_only)
        assert len(unsup) == 9
        assert "spread" in why

    def test_mutual_cross_class_anti_affinity_compiles(self, setup):
        """Variant classes mutually carrying the identical hostname
        anti-affinity selector compile to the tensor path and never share
        a node across the union."""
        pool, types = setup
        term = PodAffinityTerm(
            topology_key=L.LABEL_HOSTNAME,
            label_selector=(("app", "solo2"),),
            anti=True,
        )
        pods = [
            Pod(
                labels={"app": "solo2", "variant": str(i % 2)},
                requests=Resources(cpu=0.25),
                pod_affinity=[term],
            )
            for i in range(12)
        ]
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        assert not tensor.unschedulable
        assert tensor.node_count() == oracle.node_count() == 12
        assert all(len(n.pods) == 1 for n in tensor.new_nodes)

    def test_one_sided_anti_affinity_stays_oracle(self, setup):
        """A class counted by the selector but not carrying the term
        (asymmetric coupling) still needs the oracle."""
        from karpenter_tpu.ops.tensorize import partition_groups

        carriers = [
            Pod(
                labels={"app": "solo3"},
                requests=Resources(cpu=0.25),
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_HOSTNAME,
                        label_selector=(("app", "solo3"),),
                        anti=True,
                    )
                ],
            )
            for _ in range(3)
        ]
        counted = [Pod(labels={"app": "solo3", "v": "x"}, requests=Resources(cpu=1))]
        sup, unsup, why = partition_groups(carriers + counted)
        assert len(unsup) == 4
        assert "anti-affinity" in why

    def test_live_unconstrained_matching_pod_blocks_anti(self, setup):
        """A bound pod with matching labels blocks an anti-affinity class
        on its node even though the bound pod carries no constraint."""
        from karpenter_tpu.state.cluster import StateNode

        pool, types = setup
        bound = Pod(labels={"app": "solo4"}, requests=Resources(cpu=1))
        live = StateNode(
            name="live-anti",
            provider_id="fake://live-anti",
            labels={L.LABEL_ZONE: "zone-a"},
            taints=[],
            allocatable=Resources(cpu=64, memory="256Gi"),
            pods=[bound],
            used=Resources(cpu=1),
        )
        incoming = [
            Pod(
                labels={"app": "solo4"},
                requests=Resources(cpu=0.25),
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_HOSTNAME,
                        label_selector=(("app", "solo4"),),
                        anti=True,
                    )
                ],
            )
            for _ in range(2)
        ]
        ts = TensorScheduler([pool], {pool.name: types}, existing=[live])
        res = ts.solve(incoming)
        assert ts.last_path == "tensor"
        assert not res.unschedulable
        # neither incoming pod may land on the live node (it already holds
        # a matching pod); each opens its own node
        assert not res.existing_placements
        assert res.node_count() == 2


class TestPreferredAffinity:
    """Preferred node affinity: honored when feasible (treated as required
    while simulating), relaxed all-at-once when the pod would otherwise be
    unschedulable — karpenter-core's preference relaxation (reference
    website v0.31 concepts/scheduling.md)."""

    def test_preference_honored_on_tensor_path(self, setup):
        pool, types = setup
        pods = [
            Pod(
                requests=Resources(cpu=1, memory="2Gi"),
                preferred_affinity=[
                    Requirement(L.LABEL_ZONE, Op.IN, ["zone-b"])
                ],
            )
            for _ in range(20)
        ]
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        assert not tensor.unschedulable
        for vn in tensor.new_nodes:
            assert vn.requirements.get(L.LABEL_ZONE).has("zone-b")
        for vn in oracle.new_nodes:
            assert vn.requirements.get(L.LABEL_ZONE).has("zone-b")

    def test_unsatisfiable_preference_relaxes(self, setup):
        pool, types = setup
        pods = [Pod(requests=Resources(cpu=1, memory="2Gi")) for _ in range(10)]
        pods += [
            Pod(
                requests=Resources(cpu=1, memory="2Gi"),
                preferred_affinity=[
                    Requirement(L.LABEL_ZONE, Op.IN, ["zone-nowhere"])
                ],
            )
            for _ in range(5)
        ]
        oracle, tensor, ts = both(pool, types, pods)
        # the preference can't be met; pods schedule anyway — relaxed at
        # COMPILE time (globally-empty strict row -> preference peel on
        # the compiled rows), so the batch never leaves the tensor path
        assert not tensor.unschedulable
        assert not oracle.unschedulable
        assert ts.last_path == "tensor"
        assert ts.last_compile_relaxed == 5
        placed = sum(len(n.pods) for n in tensor.new_nodes)
        assert placed == 15

    def test_preferences_split_classes(self, setup):
        """Pods differing only in preferences are distinct classes."""
        pool, types = setup
        a = Pod(requests=Resources(cpu=1))
        b = Pod(
            requests=Resources(cpu=1),
            preferred_affinity=[Requirement(L.LABEL_ZONE, Op.IN, ["zone-b"])],
        )
        assert a.constraint_signature() != b.constraint_signature()
        prob = compile_problem([a, b], [pool], {pool.name: types})
        assert len(prob.classes) == 2

    def test_relaxed_pod_respects_spread_of_placed_siblings(self, setup):
        """A relaxing pod sharing a spread group with tensor-placed
        siblings must see their zone counts (the seed_topology replay)."""
        pool, types = setup
        sel = (("svc", "pref"),)
        c = TopologySpreadConstraint(
            max_skew=1, topology_key=L.LABEL_ZONE, label_selector=sel
        )
        plain = [
            Pod(
                labels={"svc": "pref"},
                requests=Resources(cpu=1, memory="2Gi"),
                topology_spread=[c],
            )
            for _ in range(8)
        ]
        pref = Pod(
            labels={"svc": "pref"},
            requests=Resources(cpu=1, memory="2Gi"),
            topology_spread=[c],
            preferred_affinity=[
                Requirement(L.LABEL_INSTANCE_CATEGORY, Op.IN, ["no-such"])
            ],
        )
        ts = TensorScheduler([pool], {pool.name: types})
        res = ts.solve(plain + [pref])
        assert not res.unschedulable
        counts = {}
        for vn in res.new_nodes:
            zone = vn.requirements.get(L.LABEL_ZONE).any_value()
            for p in vn.pods:
                counts[zone] = counts.get(zone, 0) + 1
        assert sum(counts.values()) == 9
        assert max(counts.values()) - min(counts.values()) <= 1, counts

    def test_compaction_never_trades_away_satisfiable_preference(self, setup):
        """The decode compaction pass must not move preference carriers off
        the node that honors their preference."""
        pool, types = setup
        pods = [Pod(requests=Resources(cpu=1, memory="2Gi")) for _ in range(12)]
        pods += [
            Pod(
                requests=Resources(cpu=0.25, memory="512Mi"),
                preferred_affinity=[
                    Requirement(L.LABEL_ZONE, Op.IN, ["zone-b"])
                ],
            )
            for _ in range(2)
        ]
        ts = TensorScheduler([pool], {pool.name: types})
        res = ts.solve(pods)
        assert not res.unschedulable
        for vn in res.new_nodes:
            for p in vn.pods:
                if p.preferred_affinity:
                    assert vn.requirements.get(L.LABEL_ZONE).has("zone-b")


class TestNodeAffinityOrTerms:
    """nodeSelectorTerms OR semantics (reference scheduling.md:230-259):
    karpenter goes through the terms in order and takes the first that
    works; the tensor path compiles term 0, the oracle walks the rest."""

    def test_first_term_wins_when_feasible(self, setup):
        pool, types = setup
        pods = [
            Pod(
                requests=Resources(cpu=1, memory="2Gi"),
                affinity_terms=[
                    (Requirement(L.LABEL_ZONE, Op.IN, ["zone-b"]),),
                    (Requirement(L.LABEL_ZONE, Op.IN, ["zone-c"]),),
                ],
            )
            for _ in range(10)
        ]
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        assert not tensor.unschedulable
        for vn in tensor.new_nodes:
            assert vn.requirements.get(L.LABEL_ZONE).has("zone-b")

    def test_falls_through_to_second_term(self, setup):
        pool, types = setup
        pods = [Pod(requests=Resources(cpu=1, memory="2Gi")) for _ in range(6)]
        pods += [
            Pod(
                requests=Resources(cpu=1, memory="2Gi"),
                affinity_terms=[
                    (Requirement(L.LABEL_ZONE, Op.IN, ["zone-nowhere"]),),
                    (Requirement(L.LABEL_ZONE, Op.IN, ["zone-c"]),),
                ],
            )
            for _ in range(4)
        ]
        oracle, tensor, ts = both(pool, types, pods)
        assert not tensor.unschedulable
        assert not oracle.unschedulable
        # the term walk ran at compile time (term 0 admits no config),
        # so the batch stays on the tensor path
        assert ts.last_path == "tensor"
        assert ts.last_compile_relaxed == 4
        for res in (tensor, oracle):
            for vn in res.new_nodes:
                for p in vn.pods:
                    if p.affinity_terms:
                        assert vn.requirements.get(L.LABEL_ZONE).has("zone-c")

    def test_all_terms_fail_unschedulable(self, setup):
        pool, types = setup
        pod = Pod(
            requests=Resources(cpu=1),
            affinity_terms=[
                (Requirement(L.LABEL_ZONE, Op.IN, ["zone-x"]),),
                (Requirement(L.LABEL_ZONE, Op.IN, ["zone-y"]),),
            ],
        )
        oracle, tensor, ts = both(pool, types, [pod])
        assert pod.key() in tensor.unschedulable
        assert pod.key() in oracle.unschedulable

    def test_terms_split_classes(self, setup):
        pool, types = setup
        a = Pod(requests=Resources(cpu=1))
        b = Pod(
            requests=Resources(cpu=1),
            affinity_terms=[(Requirement(L.LABEL_ZONE, Op.IN, ["zone-b"]),)],
        )
        assert a.constraint_signature() != b.constraint_signature()


class TestScheduleAnywaySpread:
    """ScheduleAnyway topology spread: honored as required until the pod
    proves unschedulable, then relaxed (karpenter's best-effort semantics,
    reference scheduling.md:319-331)."""

    def test_soft_spread_balances_when_feasible(self, setup):
        pool, types = setup
        sel = (("svc", "soft"),)
        c = TopologySpreadConstraint(
            max_skew=1,
            topology_key=L.LABEL_ZONE,
            when_unsatisfiable="ScheduleAnyway",
            label_selector=sel,
        )
        pods = [
            Pod(
                labels={"svc": "soft"},
                requests=Resources(cpu=1, memory="2Gi"),
                topology_spread=[c],
            )
            for _ in range(30)
        ]
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        assert not tensor.unschedulable
        for res in (tensor, oracle):
            counts = {}
            for vn in res.new_nodes:
                zone = vn.requirements.get(L.LABEL_ZONE).any_value()
                counts[zone] = counts.get(zone, 0) + len(vn.pods)
            assert max(counts.values()) - min(counts.values()) <= 1, counts

    def test_soft_spread_relaxes_instead_of_failing(self, setup):
        """Pods restricted to one zone with a soft spread still schedule
        (the spread would demand zones the selector forbids)."""
        pool, types = setup
        sel = (("svc", "soft2"),)
        c = TopologySpreadConstraint(
            max_skew=1,
            topology_key=L.LABEL_ZONE,
            when_unsatisfiable="ScheduleAnyway",
            label_selector=sel,
        )
        pods = [
            Pod(
                labels={"svc": "soft2"},
                requests=Resources(cpu=1, memory="2Gi"),
                node_selector={L.LABEL_ZONE: "zone-a"},
                topology_spread=[c],
            )
            for _ in range(9)
        ]
        oracle, tensor, ts = both(pool, types, pods)
        assert not tensor.unschedulable
        assert not oracle.unschedulable
        # everything in zone-a: the spread relaxed rather than blocking
        for res in (tensor, oracle):
            for vn in res.new_nodes:
                assert vn.requirements.get(L.LABEL_ZONE).has("zone-a")

    def test_hard_spread_still_blocks(self, setup):
        """The same shape with DoNotSchedule keeps its hard semantics."""
        pool, types = setup
        sel = (("svc", "hard2"),)
        c = TopologySpreadConstraint(
            max_skew=1, topology_key=L.LABEL_ZONE, label_selector=sel
        )
        pods = [
            Pod(
                labels={"svc": "hard2"},
                requests=Resources(cpu=1, memory="2Gi"),
                node_selector={L.LABEL_ZONE: "zone-a"},
                topology_spread=[c],
            )
            for _ in range(9)
        ]
        oracle, tensor, ts = both(pool, types, pods)
        # kube semantics: skew counts only zones the pods can use, so a
        # one-zone universe... the reference treats domains from the
        # PROVISIONER's requirements — pods restricted by nodeSelector to
        # one zone can all land there (skew over candidate domains = 1)
        # OR be held pending; either way both paths must AGREE
        assert bool(tensor.unschedulable) == bool(oracle.unschedulable)


class TestMatchExpressions:
    """Kube label-selector matchExpressions (In/NotIn/Exists) on pod
    affinity and topology spread (reference scheduling.md:360-373)."""

    def test_in_expression_coloc_compiles(self, setup):
        pool, types = setup
        term = PodAffinityTerm(
            topology_key=L.LABEL_HOSTNAME,
            match_expressions=(("tier", "In", ("db", "cache")),),
        )
        pods = [
            Pod(
                labels={"tier": ("db" if i % 2 else "cache")},
                requests=Resources(cpu=1, memory="2Gi"),
                pod_affinity=[term],
            )
            for i in range(4)
        ]
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        assert not tensor.unschedulable
        nodes = {vn.name for vn in tensor.new_nodes for p in vn.pods}
        assert len(nodes) == 1  # one co-located unit

    def test_notin_anti_affinity_oracle_exact(self, setup):
        """NotIn selects pods MISSING the label too — only the oracle's
        runtime sets can express that; routing must stay correct."""
        from karpenter_tpu.ops.tensorize import partition_groups

        pool, types = setup
        anti = PodAffinityTerm(
            topology_key=L.LABEL_HOSTNAME,
            match_expressions=(("safe", "NotIn", ("yes",)),),
            anti=True,
        )
        carrier = Pod(
            labels={"app": "x"}, requests=Resources(cpu=1), pod_affinity=[anti]
        )
        plain = [Pod(requests=Resources(cpu=1)) for _ in range(3)]
        sup, unsup, why = partition_groups([carrier] + plain)
        # the selector reaches the plain (unlabeled) class: everything
        # coupled goes oracle
        assert len(unsup) == 4
        oracle, tensor, ts = both(pool, types, [carrier] + plain)
        assert ts.last_path == "oracle"
        assert not tensor.unschedulable
        # the carrier must not share a node with anything it selects
        for vn in tensor.new_nodes:
            keys = {p.key() for p in vn.pods}
            if carrier.key() in keys:
                assert len(keys) == 1

    def test_exists_spread_balances(self, setup):
        pool, types = setup
        c = TopologySpreadConstraint(
            max_skew=1,
            topology_key=L.LABEL_ZONE,
            match_expressions=(("svc", "Exists", ()),),
        )
        pods = [
            Pod(
                labels={"svc": f"v{i % 3}"},
                requests=Resources(cpu=1, memory="2Gi"),
                topology_spread=[c],
            )
            for i in range(18)
        ]
        oracle, tensor, ts = both(pool, types, pods)
        assert not tensor.unschedulable
        counts = {}
        for vn in tensor.new_nodes:
            zone = vn.requirements.get(L.LABEL_ZONE).any_value()
            counts[zone] = counts.get(zone, 0) + len(vn.pods)
        assert max(counts.values()) - min(counts.values()) <= 1, counts

    def test_live_carrier_repels_incoming_matchers(self, setup):
        """Symmetric anti-affinity: a BOUND pod carrying an anti term
        repels incoming pods its selector matches, even though they carry
        no term themselves."""
        from karpenter_tpu.state.cluster import StateNode

        pool, types = setup
        carrier = Pod(
            labels={"lonely": "true"},
            requests=Resources(cpu=1),
            pod_affinity=[
                PodAffinityTerm(
                    topology_key=L.LABEL_HOSTNAME,
                    label_selector=(("team", "a"),),
                    anti=True,
                )
            ],
        )
        live = StateNode(
            name="live-sym",
            provider_id="fake://live-sym",
            labels={L.LABEL_ZONE: "zone-a"},
            taints=[],
            allocatable=Resources(cpu=64, memory="256Gi", pods=110),
            pods=[carrier],
            used=Resources(cpu=1),
        )
        incoming = [
            Pod(labels={"team": "a"}, requests=Resources(cpu=0.5, memory="1Gi"))
            for _ in range(2)
        ]
        ts = TensorScheduler([pool], {pool.name: types}, existing=[live])
        res = ts.solve(incoming)
        assert ts.last_path == "oracle"  # live carrier routes to the oracle
        assert not res.unschedulable
        # neither matching pod may join the carrier's node
        assert not res.existing_placements
        assert res.node_count() >= 1

    def test_compaction_respects_unlabeled_carrier(self, setup):
        """The decode compaction pass must not move a selector-matched pod
        onto an UNLABELED anti-affinity carrier's node."""
        pool, types = setup
        carrier = Pod(
            requests=Resources(cpu=8),
            pod_affinity=[
                PodAffinityTerm(
                    topology_key=L.LABEL_HOSTNAME,
                    label_selector=(("team", "a"),),
                    anti=True,
                )
            ],
        )
        matcher = Pod(labels={"team": "a"}, requests=Resources(cpu=0.5))
        fillers = [Pod(requests=Resources(cpu=8)) for _ in range(2)]
        ts = TensorScheduler([pool], {pool.name: types})
        res = ts.solve([carrier, matcher] + fillers)
        assert not res.unschedulable
        for vn in res.new_nodes:
            keys = {p.key() for p in vn.pods}
            if carrier.key() in keys:
                assert matcher.key() not in keys


class TestCustomTopologyKeySpread:
    """Spreads on arbitrary node-label keys compile when pool templates
    partition the domains (scheduling.md:319-331)."""

    def _setup(self, env):
        nc = env.default_node_class()
        ra = env.default_node_pool(name="rack-a", labels={"example.com/rack": "r1"})
        rb = env.default_node_pool(name="rack-b", labels={"example.com/rack": "r2"})
        pools = [ra, rb]
        inv = {p.name: env.instance_types.list(p, nc) for p in pools}
        return pools, inv

    def _pods(self, n=12, skew=1):
        c = TopologySpreadConstraint(
            max_skew=skew,
            topology_key="example.com/rack",
            label_selector=(("app", "w"),),
        )
        return [
            Pod(labels={"app": "w"}, requests=Resources(cpu=1, memory="2Gi"),
                topology_spread=[c])
            for _ in range(n)
        ]

    def test_compiles_and_balances(self, env):
        pools, inv = self._setup(env)
        pods = [Pod(requests=Resources(cpu=1, memory="2Gi")) for _ in range(20)]
        pods += self._pods(12)
        ts = TensorScheduler(pools, inv)
        res = ts.solve(pods)
        oracle = Scheduler(pools, inv).solve(pods)
        assert ts.last_path == "tensor"
        assert not res.unschedulable
        counts = {}
        for vn in res.new_nodes:
            rack = vn.requirements.get("example.com/rack")
            for p in vn.pods:
                if p.labels.get("app") == "w":
                    assert rack is not None
                    counts[rack.any_value()] = counts.get(rack.any_value(), 0) + 1
        assert set(counts) == {"r1", "r2"}
        assert max(counts.values()) - min(counts.values()) <= 1, counts
        assert res.node_count() <= oracle.node_count() + 1

    def test_multivalued_template_stays_oracle(self, env):
        from karpenter_tpu.api import Requirements as Reqs
        from karpenter_tpu.ops.tensorize import partition_groups

        nc = env.default_node_class()
        multi = env.default_node_pool(
            name="multi",
            requirements=Reqs(
                [Requirement("example.com/rack", Op.IN, ["r1", "r2"])]
            ),
        )
        pods = self._pods(4)
        sup, unsup, why = partition_groups(pods, pools=[multi])
        assert len(unsup) == 4
        assert "topology spread on key" in why

    def test_spread_spanning_request_classes_shares_accumulator(self, env):
        """Two request classes under one custom-key spread balance their
        SUM across racks, like the zone accumulator."""
        pools, inv = self._setup(env)
        c = TopologySpreadConstraint(
            max_skew=1,
            topology_key="example.com/rack",
            label_selector=(("app", "w"),),
        )
        pods = []
        for n, cpu in ((7, 1), (5, 2)):
            for _ in range(n):
                pods.append(
                    Pod(labels={"app": "w"},
                        requests=Resources(cpu=cpu, memory="2Gi"),
                        topology_spread=[c])
                )
        ts = TensorScheduler(pools, inv)
        res = ts.solve(pods)
        assert ts.last_path == "tensor"
        assert not res.unschedulable
        counts = {}
        for vn in res.new_nodes:
            rack = vn.requirements.get("example.com/rack").any_value()
            counts[rack] = counts.get(rack, 0) + len(vn.pods)
        assert max(counts.values()) - min(counts.values()) <= 1, counts

    def test_live_only_domain_is_a_valid_split_target(self, env):
        """A domain served only by a LIVE node (its pool is gone) is
        still a valid placement target: the split class's feasibility row
        holds just the existing-node columns, matching the oracle."""
        from karpenter_tpu.api import labels as L2
        from karpenter_tpu.state.cluster import StateNode

        nc = env.default_node_class()
        ra = env.default_node_pool(name="rack-a2", labels={"example.com/rack": "r1"})
        pools = [ra]
        inv = {ra.name: env.instance_types.list(ra, nc)}
        live = StateNode(
            name="live-r2",
            provider_id="fake://live-r2",
            labels={
                L2.LABEL_ZONE: "zone-a",
                "example.com/rack": "r2",
                L2.LABEL_NODEPOOL: "gone",
            },
            taints=[],
            allocatable=Resources(cpu=8, memory="32Gi", pods=110),
        )
        c = TopologySpreadConstraint(
            max_skew=1,
            topology_key="example.com/rack",
            label_selector=(("app", "w"),),
        )
        pods = [
            Pod(
                labels={"app": "w"},
                requests=Resources(cpu=1, memory="2Gi"),
                node_selector={"example.com/rack": "r2"},
                topology_spread=[c],
            )
            for _ in range(2)
        ]
        ts = TensorScheduler(pools, inv, existing=[live])
        res = ts.solve(pods)
        oracle = Scheduler(pools, inv, existing=[live]).solve(pods)
        assert not oracle.unschedulable, oracle.unschedulable
        assert not res.unschedulable, res.unschedulable
        assert set(res.existing_placements.values()) == {"live-r2"}
