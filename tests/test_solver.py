"""Tensor-solver tests: kernel behavior + parity with the FFD oracle.

Mirrors the reference's test strategy (SURVEY.md §4): real scheduling logic
over the fake cloud, with the oracle (scheduling/scheduler.py) as the
semantics definition the kernel must match or beat.
"""

import random

import numpy as np
import pytest

from karpenter_tpu.api import Pod, Requirement, Resources, Taint, Toleration
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import PodAffinityTerm, TopologySpreadConstraint
from karpenter_tpu.api.requirements import Op
from karpenter_tpu.ops.tensorize import compile_problem
from karpenter_tpu.scheduling import Scheduler, TensorScheduler
from karpenter_tpu.testing import Environment


@pytest.fixture(scope="module")
def env():
    return Environment()


@pytest.fixture(scope="module")
def setup(env):
    pool = env.default_node_pool()
    nc = env.default_node_class()
    types = env.instance_types.list(pool, nc)
    return pool, types


def both(pool, types, pods, **kw):
    oracle = Scheduler([pool], {pool.name: types}, **kw).solve(pods)
    ts = TensorScheduler([pool], {pool.name: types}, **kw)
    tensor = ts.solve(pods)
    return oracle, tensor, ts


# ---------------------------------------------------------------------------
# compile_problem
# ---------------------------------------------------------------------------


class TestTensorize:
    def test_classes_group_identical_pods(self, setup):
        pool, types = setup
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(50)]
        prob = compile_problem(pods, [pool], {pool.name: types})
        assert len(prob.classes) == 1
        assert prob.cnt[0] == 50
        assert prob.supported

    def test_configs_cover_zones_and_capacity_types(self, setup):
        pool, types = setup
        pods = [Pod(requests=Resources(cpu=1))]
        prob = compile_problem(pods, [pool], {pool.name: types})
        zones = {c.zone for c in prob.configs}
        cts = {c.capacity_type for c in prob.configs}
        assert zones == {"zone-a", "zone-b", "zone-c"}
        assert cts == {L.CAPACITY_TYPE_ON_DEMAND, L.CAPACITY_TYPE_SPOT}

    def test_node_selector_masks_feasibility(self, setup):
        pool, types = setup
        pod = Pod(
            requests=Resources(cpu=1),
            node_selector={L.LABEL_ARCH: "arm64"},
        )
        prob = compile_problem([pod], [pool], {pool.name: types})
        for c_idx in np.nonzero(prob.feas[0])[0]:
            cfg = prob.configs[c_idx]
            req = cfg.instance_type.requirements.get(L.LABEL_ARCH)
            assert req.has("arm64")

    def test_unsupported_constraints_reported(self, setup):
        pool, types = setup
        pod = Pod(
            requests=Resources(cpu=1),
            pod_affinity=[
                PodAffinityTerm(
                    topology_key=L.LABEL_ZONE,
                    label_selector=(("app", "x"),),
                    anti=False,
                )
            ],
        )
        prob = compile_problem([pod], [pool], {pool.name: types})
        assert not prob.supported

    def test_zone_spread_splits_classes(self, setup):
        pool, types = setup
        sel = (("app", "s"),)
        pods = [
            Pod(
                labels={"app": "s"},
                requests=Resources(cpu=1),
                topology_spread=[
                    TopologySpreadConstraint(
                        max_skew=1, topology_key=L.LABEL_ZONE, label_selector=sel
                    )
                ],
            )
            for _ in range(10)
        ]
        prob = compile_problem(pods, [pool], {pool.name: types})
        zone_pins = sorted(cm.zone_pin for cm in prob.classes)
        assert zone_pins == ["zone-a", "zone-b", "zone-c"]
        counts = sorted(len(cm.pods) for cm in prob.classes)
        assert counts == [3, 3, 4]


# ---------------------------------------------------------------------------
# Solver vs oracle parity
# ---------------------------------------------------------------------------


class TestParity:
    def test_homogeneous_matches_oracle(self, setup):
        pool, types = setup
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(200)]
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        assert not tensor.unschedulable
        assert tensor.node_count() <= oracle.node_count()
        assert sum(len(n.pods) for n in tensor.new_nodes) == 200

    def test_heterogeneous_close_to_oracle(self, setup):
        pool, types = setup
        random.seed(7)
        pods = []
        for i in range(300):
            pods.append(
                Pod(
                    requests=Resources(
                        cpu=random.choice([0.25, 0.5, 1, 2]),
                        memory=random.choice(["256Mi", "1Gi", "4Gi"]),
                    )
                )
            )
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        assert not tensor.unschedulable
        # quality bar: within 15% of the oracle's node count
        assert tensor.node_count() <= max(oracle.node_count() * 1.15, 1)

    def test_hostname_anti_affinity_one_per_node(self, setup):
        pool, types = setup
        sel = (("app", "dense"),)
        pods = [
            Pod(
                labels={"app": "dense"},
                requests=Resources(cpu=0.25),
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_HOSTNAME, label_selector=sel, anti=True
                    )
                ],
            )
            for _ in range(40)
        ]
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        assert tensor.node_count() == oracle.node_count() == 40
        assert all(len(n.pods) == 1 for n in tensor.new_nodes)

    def test_zone_spread_balances(self, setup):
        pool, types = setup
        sel = (("app", "z"),)
        pods = [
            Pod(
                labels={"app": "z"},
                requests=Resources(cpu=1, memory="1Gi"),
                topology_spread=[
                    TopologySpreadConstraint(
                        max_skew=1, topology_key=L.LABEL_ZONE, label_selector=sel
                    )
                ],
            )
            for _ in range(90)
        ]
        oracle, tensor, ts = both(pool, types, pods)
        assert ts.last_path == "tensor"
        zone_counts = {}
        for n in tensor.new_nodes:
            zone = n.requirements.get(L.LABEL_ZONE).any_value()
            zone_counts[zone] = zone_counts.get(zone, 0) + len(n.pods)
        assert max(zone_counts.values()) - min(zone_counts.values()) <= 1

    def test_zone_spread_levels_existing_skew(self, setup):
        """Bound pods matched by the spread SELECTOR (even if they carry no
        constraint themselves) must seed the skew counts — new placements go
        to the under-filled zones."""
        pool, types = setup
        from karpenter_tpu.state.cluster import StateNode

        bound = [Pod(labels={"app": "z"}, node_name="node-a") for _ in range(4)]
        existing = StateNode(
            name="node-a",
            provider_id="i-a",
            labels={
                L.LABEL_ZONE: "zone-a",
                L.LABEL_ARCH: "amd64",
                L.LABEL_OS: "linux",
            },
            taints=[],
            allocatable=Resources(cpu=0.5, pods=110),  # no room for new pods
            pods=bound,
        )
        sel = (("app", "z"),)
        pods = [
            Pod(
                labels={"app": "z"},
                requests=Resources(cpu=1, memory="1Gi"),
                topology_spread=[
                    TopologySpreadConstraint(
                        max_skew=1, topology_key=L.LABEL_ZONE, label_selector=sel
                    )
                ],
            )
            for _ in range(5)
        ]
        ts = TensorScheduler([pool], {pool.name: types}, existing=[existing])
        r = ts.solve(pods)
        assert ts.last_path == "tensor"
        totals = {"zone-a": 4, "zone-b": 0, "zone-c": 0}
        for n in r.new_nodes:
            zone = n.requirements.get(L.LABEL_ZONE).any_value()
            totals[zone] += len(n.pods)
        # leveling optimum given the pre-existing 4-in-zone-a: 4/3/2 (the
        # oracle produces the same); the buggy blank-slate split gave 6/2/1
        assert totals == {"zone-a": 4, "zone-b": 3, "zone-c": 2}, totals

    def test_zone_spread_respects_pod_zone_requirements(self, setup):
        """A zone-spread pod restricted to two zones must only split across
        those zones (Kubernetes filters skew domains by nodeAffinity)."""
        pool, types = setup
        sel = (("app", "zz"),)
        pods = [
            Pod(
                labels={"app": "zz"},
                requests=Resources(cpu=1),
                node_selector={},
                required_affinity=[
                    Requirement(L.LABEL_ZONE, Op.IN, ["zone-a", "zone-b"])
                ],
                topology_spread=[
                    TopologySpreadConstraint(
                        max_skew=1, topology_key=L.LABEL_ZONE, label_selector=sel
                    )
                ],
            )
            for _ in range(8)
        ]
        ts = TensorScheduler([pool], {pool.name: types})
        r = ts.solve(pods)
        assert not r.unschedulable
        zones = {
            n.requirements.get(L.LABEL_ZONE).any_value() for n in r.new_nodes
        }
        assert zones <= {"zone-a", "zone-b"}

    def test_tolerations_against_tainted_pool(self, env, setup):
        _, types = setup
        tainted = env.default_node_pool(
            name="tainted", taints=[Taint(key="team", value="ml")]
        )
        pods_no_tol = [Pod(requests=Resources(cpu=1))]
        pods_tol = [
            Pod(
                requests=Resources(cpu=1),
                tolerations=[Toleration(key="team", value="ml")],
            )
        ]
        ts = TensorScheduler([tainted], {"tainted": types})
        r1 = ts.solve(pods_no_tol)
        assert len(r1.unschedulable) == 1
        r2 = ts.solve(pods_tol)
        assert r2.node_count() == 1

    def test_oracle_fallback_for_pod_affinity(self, setup):
        pool, types = setup
        sel = (("app", "a"),)
        pods = [
            Pod(
                labels={"app": "a"},
                requests=Resources(cpu=1),
                pod_affinity=[
                    PodAffinityTerm(topology_key=L.LABEL_ZONE, label_selector=sel)
                ],
            )
            for _ in range(6)
        ]
        ts = TensorScheduler([pool], {pool.name: types})
        r = ts.solve(pods)
        assert ts.last_path == "oracle"
        assert not r.unschedulable
        # all anchored in one zone
        zones = {
            n.requirements.get(L.LABEL_ZONE).any_value() for n in r.new_nodes
        }
        assert len(zones) == 1

    def test_requirement_gt_lt(self, setup):
        pool, types = setup
        pod = Pod(
            requests=Resources(cpu=1),
            required_affinity=[
                Requirement(L.LABEL_INSTANCE_CPU, Op.GT, ["8"]),
                Requirement(L.LABEL_INSTANCE_CPU, Op.LT, ["64"]),
            ],
        )
        ts = TensorScheduler([pool], {pool.name: types})
        r = ts.solve([pod])
        assert ts.last_path == "tensor"
        assert r.node_count() == 1
        it = r.new_nodes[0].feasible_types[0]
        assert 8 < it.capacity.cpu < 64

    def test_existing_nodes_used_first(self, env, setup):
        pool, types = setup
        from karpenter_tpu.state.cluster import StateNode

        existing = StateNode(
            name="node-1",
            provider_id="i-1",
            labels={
                L.LABEL_ARCH: "amd64",
                L.LABEL_OS: "linux",
                L.LABEL_ZONE: "zone-a",
                L.LABEL_NODEPOOL: pool.name,
            },
            taints=[],
            allocatable=Resources(cpu=8, memory="32Gi", pods=110),
        )
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(4)]
        ts = TensorScheduler([pool], {pool.name: types}, existing=[existing])
        r = ts.solve(pods)
        assert ts.last_path == "tensor"
        assert r.node_count() == 0
        assert len(r.existing_placements) == 4

    def test_unschedulable_when_nothing_fits(self, setup):
        pool, types = setup
        pod = Pod(requests=Resources(cpu=10000))
        ts = TensorScheduler([pool], {pool.name: types})
        r = ts.solve([pod])
        assert len(r.unschedulable) == 1

    def test_spot_preferred_when_flexible(self, setup):
        pool, types = setup
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(10)]
        _, tensor, _ = both(pool, types, pods)
        for n in tensor.new_nodes:
            ct = n.requirements.get(L.LABEL_CAPACITY_TYPE).any_value()
            assert ct == L.CAPACITY_TYPE_SPOT  # spot is cheaper in the fake

    def test_on_demand_when_pinned(self, setup):
        pool, types = setup
        pods = [
            Pod(
                requests=Resources(cpu=1),
                node_selector={L.LABEL_CAPACITY_TYPE: L.CAPACITY_TYPE_ON_DEMAND},
            )
        ]
        ts = TensorScheduler([pool], {pool.name: types})
        r = ts.solve(pods)
        ct = r.new_nodes[0].requirements.get(L.LABEL_CAPACITY_TYPE).any_value()
        assert ct == L.CAPACITY_TYPE_ON_DEMAND

    def test_weighted_pools_respected(self, env, setup):
        _, types = setup
        heavy = env.default_node_pool(name="heavy", weight=10)
        light = env.default_node_pool(name="light", weight=1)
        pods = [Pod(requests=Resources(cpu=1)) for _ in range(5)]
        ts = TensorScheduler([light, heavy], {"heavy": types, "light": types})
        r = ts.solve(pods)
        for n in r.new_nodes:
            assert n.pool.name == "heavy"
